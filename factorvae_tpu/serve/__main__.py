"""CLI entry of the scoring daemon.

    # serve two checkpoints over stdin JSONL, metrics to RUN.jsonl
    python -m factorvae_tpu.serve \
        --model best_models/VAE-Revision2_factor_96_... \
        --model best_models/VAE-Revision2_factor_96_..._seed_43 \
        --dataset ./data/csi_data.pkl --metrics_jsonl RUN_SERVE.jsonl

    # one-shot batch file; HTTP instead of stdin
    python -m factorvae_tpu.serve --model m.aot --batch reqs.jsonl
    python -m factorvae_tpu.serve --model m.aot --http 8787

    # scale-out (ISSUE 15): N workers behind the sticky router
    python -m factorvae_tpu.serve --model ckpt0 --model ckpt1 \
        --dataset ./data/csi_data.pkl --workers 4 --router_port 8800 \
        --compile_cache ~/.cache/fvae-xla

Requests (one JSON object per line; an ARRAY line is one explicit
batch/tick): {"id": 1, "model": "<key|alias>", "day": "2020-01-03"}
plus optional "days"/"start"/"end", "top": k; commands {"cmd":
"stats"|"models"|"ping"|"shutdown"}. Responses mirror the id, carry
per-instrument scores, the serving precision and latency_ms. Full
schema: docs/serving.md.

Startup chatter goes to STDERR — stdout is the response stream.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.serve",
        description="long-lived scoring daemon over a warm AOT model "
                    "registry (docs/serving.md)")
    p.add_argument("--model", action="append", default=[],
                   metavar="PATH",
                   help="model to admit at startup (repeatable): a "
                        "weights-only checkpoint DIRECTORY (save_params "
                        "layout; Config from the sibling *_ckpt metadata "
                        "or a serve_config.json drop-in) or an AOT "
                        "artifact FILE (eval/export_aot.py)")
    p.add_argument("--dataset", type=str, default=None,
                   help="panel pickle to serve days from (the qlib ETL "
                        "artifact; data/README.md)")
    p.add_argument("--synthetic", type=str, default=None,
                   metavar="DAYS,STOCKS",
                   help="serve a synthetic dense panel instead of "
                        "--dataset (tests/bench): e.g. 64,96. Features/"
                        "seq_len follow the first model's config")
    p.add_argument("--max_stocks", type=int, default=None,
                   help="cross-section pad target (default: inferred; "
                        "must match an AOT artifact's exported n_max)")
    p.add_argument("--precision",
                   choices=["plan", "float32", "bfloat16", "int8"],
                   default="plan",
                   help="precision ladder rung for checkpoint models: "
                        "'plan' (default) resolves per shape from a "
                        "measured plan row's 'serve' block "
                        "(autotune_plan.py --serve), falling back to "
                        "float32 — the rung that is bitwise the offline "
                        "scan (docs/serving.md)")
    p.add_argument("--budget_mb", type=float, default=0,
                   help="registry bytes budget; LRU eviction past it "
                        "(0 = unbounded). Evicted disk-backed models "
                        "cold-start back in on demand")
    p.add_argument("--warmup", action="store_true",
                   help="compile every model against the panel shape "
                        "BEFORE serving (first request already warm)")
    p.add_argument("--stochastic", action="store_true",
                   help="sample at inference per each model's config "
                        "(reference-faithful); default: deterministic "
                        "scores (the reproducible serving mode)")
    p.add_argument("--seed", type=int, default=0,
                   help="scoring RNG seed of the stochastic path")
    p.add_argument("--batch", type=str, default=None, metavar="FILE",
                   help="score this JSONL request file and exit "
                        "(responses to --out or stdout)")
    p.add_argument("--out", type=str, default=None,
                   help="response JSONL path for --batch (default "
                        "stdout)")
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="serve HTTP on 127.0.0.1:PORT (POST /score "
                        "/profile, GET /stats /models /healthz "
                        "/metrics) instead of stdin")
    p.add_argument("--tick_ms", type=float, default=None,
                   help="batching window: stdin lines (default 20) or "
                        "— with --scheduler / --workers — how long an "
                        "under-full HTTP tick holds for late arrivals "
                        "(default: the plan row's serve block, raced "
                        "by autotune_plan.py --serve, else 2)")
    p.add_argument("--max_batch", type=int, default=None,
                   help="max requests per tick (default: the plan "
                        "row's serve block with --scheduler, else 64)")
    p.add_argument("--scheduler", action="store_true",
                   help="with --http: cross-tick continuous batching "
                        "(ThreadingHTTPServer + one scheduler thread; "
                        "concurrent clients' requests fuse into shared "
                        "dispatch ticks — trades p50 for QPS under "
                        "load; docs/serving.md). Implied for pool "
                        "workers")
    p.add_argument("--workers", type=int, default=1,
                   help="serving scale-out (docs/serving.md): spawn N "
                        "full daemon worker processes behind a "
                        "config-hash-sticky HTTP router. N=1 (default) "
                        "is exactly today's single daemon — no router "
                        "process")
    p.add_argument("--router_port", type=int, default=8800,
                   help="router listen port with --workers > 1 "
                        "(/score /admit /stats /metrics /healthz)")
    p.add_argument("--aot_store", type=str, default=None,
                   metavar="DIR",
                   help="AOT artifact store the pool pre-exports "
                        "admitted models into (respawned workers "
                        "cold-start from it with zero traces; "
                        "default: <work dir>/aot_store)")
    p.add_argument("--join", type=str, default=None, metavar="URL",
                   help="join an existing fleet as a REMOTE worker "
                        "(ISSUE 17): sync every artifact from the "
                        "router's content-addressed store "
                        "(GET /artifacts + /artifact/<sha256>, "
                        "digest-verified), mirror the fleet's panel/"
                        "worker args, serve, and register once "
                        "healthy. Needs no --model and no --dataset "
                        "— a cold host joins with zero local traces")
    p.add_argument("--advertise_host", type=str, default="127.0.0.1",
                   help="host address presented at registration with "
                        "--join (what the router forwards to)")
    p.add_argument("--slo_ms", type=float, default=None,
                   help="declared p99 latency SLO the router defends "
                        "(--workers > 1): exported on /metrics and "
                        "driving --autoscale. Default: the plan "
                        "row's serve block, else none")
    p.add_argument("--hedge_ms", type=float, default=None,
                   help="hedged-forward delay (--workers > 1): a "
                        "forward still unanswered past this "
                        "duplicates to the second candidate, first "
                        "answer wins. Default: the plan row's serve "
                        "block, else auto — the measured p90 of the "
                        "router's latency window")
    p.add_argument("--no_hedge", action="store_true",
                   help="disable hedged forwards entirely")
    p.add_argument("--autoscale", type=int, default=0, metavar="MAX",
                   help="SLO-driven autoscaling (--workers > 1): "
                        "scale the fleet between --workers and MAX "
                        "from queue depth + observed p99 vs --slo_ms "
                        "(hysteresis both ways; serve/autoscale.py). "
                        "0 (default) disables")
    p.add_argument("--max_inflight", type=int, default=64,
                   help="router load-shed bound: in-flight client "
                        "requests past this answer 503 with "
                        "retry_after_s (0 disables)")
    p.add_argument("--deadline_ms", type=float, default=0.0,
                   help="per-request scoring deadline (0 = none; a "
                        "request-level 'deadline_ms' field overrides): "
                        "scores landing later answer ok:false with the "
                        "measured latency (docs/robustness.md)")
    p.add_argument("--breaker_k", type=int, default=3,
                   help="consecutive failures (dispatch errors or "
                        "deadline misses) that open a model's circuit "
                        "breaker — later requests fast-fail with "
                        "retry_after_s until the cooldown elapses")
    p.add_argument("--breaker_cooldown_s", type=float, default=5.0,
                   help="open-breaker cooldown before one half-open "
                        "probe request is let through")
    p.add_argument("--drift_threshold", type=float, default=0.5,
                   help="served-score drift gate (obs/drift.py): a "
                        "model whose day-over-day rank correlation of "
                        "served scores lands below this emits a "
                        "score_drift mark (flagged by obs.report/"
                        "obs.live, exposed in /metrics); -1 disables "
                        "(no correlation lands below it)")
    p.add_argument("--metrics_jsonl", type=str, default=None,
                   help="RUN.jsonl stream for request spans + compile "
                        "records (render: python -m "
                        "factorvae_tpu.obs.timeline)")
    p.add_argument("--trace_off", action="store_true",
                   help="disable the distributed trace plane "
                        "(docs/observability.md pillar 6): no trace "
                        "contexts at router ingress, no "
                        "X-Factorvae-Trace propagation, no trace "
                        "fields on spans. Routing and scoring are "
                        "otherwise identical — this is the bench "
                        "A/B baseline (bench.py --serve reports "
                        "trace_overhead_frac)")
    p.add_argument("--compile_cache", type=str, default=None,
                   metavar="DIR",
                   help="persistent XLA compilation cache dir (default: "
                        "$FACTORVAE_COMPILE_CACHE; 'off' disables). "
                        "With it, a daemon restart deserializes its "
                        "programs instead of recompiling — compile "
                        "records become compile_cached")
    return p


def run_pool(args) -> int:
    """The scale-out entry (--workers N > 1): spawn the worker fleet
    behind the sticky router and block until SIGTERM drains it. This
    process never builds a panel or compiles a model — the workers are
    full daemons; the router is a thin forwarding tier (its only jax
    use is the pool's AOT pre-export)."""
    import tempfile

    from factorvae_tpu.serve.pool import PoolError, WorkerPool
    from factorvae_tpu.serve.router import Router
    from factorvae_tpu.utils.logging import (
        MetricsLogger,
        Timeline,
        install_timeline,
    )

    work_dir = tempfile.mkdtemp(prefix="serve_pool_")
    store_dir = args.aot_store or os.path.join(work_dir, "aot_store")
    cache_dir = args.compile_cache \
        or os.environ.get("FACTORVAE_COMPILE_CACHE")
    if not cache_dir or cache_dir == "off":
        # The shared cache IS the zero-compile cold-start transport:
        # a pool without one would compile per worker. Make one.
        cache_dir = os.path.join(work_dir, "xla_cache")
        print(f"[pool] no --compile_cache given; workers share "
              f"{cache_dir}", file=sys.stderr)
    dataset_args = (["--dataset", args.dataset] if args.dataset
                    else ["--synthetic", args.synthetic])
    if args.max_stocks is not None:
        dataset_args += ["--max_stocks", str(args.max_stocks)]
    extra: list = []
    if args.precision != "plan":
        extra += ["--precision", args.precision]
    if args.budget_mb:
        extra += ["--budget_mb", str(args.budget_mb)]
    if args.stochastic:
        extra += ["--stochastic"]
    if args.seed:
        extra += ["--seed", str(args.seed)]
    if args.deadline_ms:
        extra += ["--deadline_ms", str(args.deadline_ms)]
    extra += ["--breaker_k", str(args.breaker_k),
              "--breaker_cooldown_s", str(args.breaker_cooldown_s),
              "--drift_threshold", str(args.drift_threshold)]
    if args.trace_off:
        extra += ["--trace_off"]
    logger = MetricsLogger(jsonl_path=args.metrics_jsonl, echo=False,
                           run_name="serve_router")
    prev_tl = install_timeline(Timeline(logger)) \
        if args.metrics_jsonl else None
    pool = WorkerPool(
        args.model, dataset_args, args.workers, cache_dir, store_dir,
        work_dir=work_dir, warmup=True, extra_args=extra,
        # Each worker gets its own stream next to the requested one;
        # two processes appending one JSONL would tear records.
        metrics_base=args.metrics_jsonl,
        tick_ms=args.tick_ms, max_tick_batch=args.max_batch)
    try:
        print(f"[pool] starting {args.workers} worker(s) "
              f"(cache {cache_dir}, aot store {store_dir}, logs "
              f"{work_dir})", file=sys.stderr)
        pool.start()
        for w in pool.stats()["workers"]:
            print(f"[pool] {w['worker_id']} pid={w['pid']} "
                  f"{w['url']} ({w['state']})", file=sys.stderr)
        # SLO + hedge delay: explicit flags win, else the measured
        # plan row's serve block (autotune_plan.py --serve), else
        # no SLO and auto-quantile hedging.
        slo_ms, hedge_ms = args.slo_ms, args.hedge_ms
        if slo_ms is None or hedge_ms is None:
            pl = None
            try:
                from factorvae_tpu import plan as planlib
                from factorvae_tpu.serve.registry import (
                    checkpoint_config,
                )

                if os.path.isdir(args.model[0]):
                    pl = planlib.plan_for_config(
                        checkpoint_config(args.model[0]), pool.n_max)
            except Exception:  # graftlint: disable=JGL007 plan lookup is an optional default source for flags the user left unset — a missing/corrupt plan file or non-checkpoint model path degrades to the documented no-SLO/auto-quantile defaults, and the startup banner below reports the resolved hedge/SLO state
                pl = None
            if slo_ms is None:
                slo_ms = pl.serve_slo_ms if pl is not None else 0.0
            if hedge_ms is None:
                hedge_ms = (pl.serve_hedge_ms if pl is not None
                            else -1.0)
        pool.router_url = f"http://127.0.0.1:{args.router_port}"
        router = Router(pool, max_inflight=args.max_inflight,
                        slo_ms=slo_ms, hedge_ms=hedge_ms,
                        hedge=not args.no_hedge,
                        trace=not args.trace_off)
        scaler = None
        if args.autoscale and args.autoscale > args.workers:
            from factorvae_tpu.serve.autoscale import AutoScaler

            scaler = AutoScaler(pool, router,
                                min_workers=args.workers,
                                max_workers=args.autoscale,
                                slo_ms=slo_ms or 0.0)
            router.autoscaler = scaler
            scaler.start()
            print(f"[pool] autoscaler: {args.workers}.."
                  f"{args.autoscale} workers, SLO "
                  f"{slo_ms or 0:g}ms", file=sys.stderr)
        print(f"[pool] router ready: "
              f"http://127.0.0.1:{args.router_port}/score "
              f"({args.workers} workers, sticky rendezvous routing, "
              f"hedge={'off' if args.no_hedge else 'on'})",
              file=sys.stderr)
        try:
            router.serve(args.router_port)
        finally:
            if scaler is not None:
                scaler.stop()
        return 0
    except PoolError as e:
        print(f"error: {e}", file=sys.stderr)
        pool.stop()
        return 2
    finally:
        if args.metrics_jsonl:
            install_timeline(prev_tl)
        logger.finish()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.model and not args.join:
        print("error: at least one --model is required (or --join "
              "URL to sync models from a fleet)", file=sys.stderr)
        return 2
    if not args.dataset and not args.synthetic and not args.join:
        print("error: pass --dataset PATH or --synthetic DAYS,STOCKS",
              file=sys.stderr)
        return 2
    if args.join:
        # Remote-worker bootstrap (ISSUE 17): sync the fleet's
        # artifacts (digest-verified), mirror its args, then fall
        # through to the ordinary single-daemon path below — a
        # remote worker IS a daemon, just one whose inputs came off
        # the wire and who announces itself when healthy.
        if args.workers > 1:
            print("error: --join runs ONE worker agent; scale by "
                  "joining more hosts (or --autoscale on the "
                  "router)", file=sys.stderr)
            return 2
        from factorvae_tpu.serve import remote
        from factorvae_tpu.serve.pool import free_port

        if args.http is None:
            args.http = free_port()
        args.scheduler = True
        try:
            capability = remote.prepare_join(args, build_parser())
        except remote.JoinError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(f"[join] synced {len(args.model)} artifact(s) from "
              f"{args.join} into {args.aot_store}", file=sys.stderr)
        remote.register_when_healthy(
            args.join, args.http, capability,
            host=args.advertise_host)
    if args.workers > 1:
        # The scale-out tier (ISSUE 15). N=1 falls through to the
        # single-daemon path below — byte-identical to the pre-pool
        # CLI, no router process.
        return run_pool(args)

    # Cache + cache-aware compile-record taxonomy BEFORE jax warms up.
    from factorvae_tpu import plan as planlib

    cache_dir = planlib.setup_compilation_cache(args.compile_cache)
    if cache_dir:
        from factorvae_tpu.obs.watchdog import track_persistent_cache

        track_persistent_cache()

    from factorvae_tpu.serve.registry import (
        ModelRegistry,
        RegistryError,
        checkpoint_config,
    )
    from factorvae_tpu.utils.logging import (
        MetricsLogger,
        Timeline,
        install_timeline,
    )

    logger = MetricsLogger(jsonl_path=args.metrics_jsonl, echo=False,
                           run_name="serve")
    prev_tl = None
    if args.metrics_jsonl:
        prev_tl = install_timeline(Timeline(logger))
    try:
        registry = ModelRegistry(
            budget_bytes=int(args.budget_mb * 1e6))
        precision = None if args.precision == "plan" else args.precision

        # Resolve every model's architecture facts BEFORE building the
        # panel: the panel's feature width and seq_len follow the
        # first model, and checkpoint admission needs the panel's
        # cross-section width so `--precision plan` can actually
        # consult a measured row's serve block (n_stocks=None would
        # silently fall through to f32).
        import os

        from factorvae_tpu.eval.export_aot import (
            ArtifactError,
            read_artifact_header,
        )

        specs = []          # (spec, kind, Config | header)
        for spec in args.model:
            try:
                if os.path.isdir(spec):
                    specs.append((spec, "checkpoint",
                                  checkpoint_config(spec)))
                else:
                    with open(spec, "rb") as fh:
                        header = read_artifact_header(fh.read())
                    if header is None:
                        raise RegistryError(
                            f"artifact {spec} has no header "
                            f"(pre-ISSUE-8 export); re-export it so "
                            f"the registry can key it by config hash")
                    specs.append((spec, "artifact", header))
            except (RegistryError, ArtifactError, OSError) as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
        _, kind0, facts0 = specs[0]
        if kind0 == "checkpoint":
            num_features = facts0.model.num_features
            seq_len = facts0.model.seq_len
        else:
            num_features = int(facts0["num_features"])
            seq_len = int(facts0["seq_len"])

        from factorvae_tpu.data import PanelDataset

        if args.synthetic:
            from factorvae_tpu.data import synthetic_panel_dense

            try:
                n_days, n_stocks = (int(x) for x in
                                    args.synthetic.split(","))
            except ValueError:
                print("error: --synthetic wants DAYS,STOCKS (e.g. "
                      "64,96)", file=sys.stderr)
                return 2
            panel = synthetic_panel_dense(
                num_days=n_days, num_instruments=n_stocks,
                num_features=num_features)
            dataset = PanelDataset(panel, seq_len=seq_len,
                                   max_stocks=args.max_stocks)
        else:
            from factorvae_tpu.data import build_panel, load_frame

            if not os.path.exists(args.dataset):
                print(f"error: dataset not found: {args.dataset}",
                      file=sys.stderr)
                return 2
            panel = build_panel(load_frame(args.dataset, None))
            dataset = PanelDataset(panel, seq_len=seq_len,
                                   max_stocks=args.max_stocks)

        for spec, kind, facts in specs:
            try:
                if kind == "checkpoint":
                    key = registry.register_checkpoint(
                        spec, config=facts, precision=precision,
                        n_stocks=dataset.n_max)
                else:
                    key = registry.register_artifact(
                        spec,
                        expected_sha256=getattr(
                            args, "_expected_sha256", {}).get(spec))
            except RegistryError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            entry = registry.get(key)
            print(f"[serve] admitted {spec} as {key} "
                  f"(alias {entry.alias}, {entry.precision}, "
                  f"{entry.nbytes} bytes)", file=sys.stderr)

        from factorvae_tpu.serve.daemon import (
            ScoringDaemon,
            serve_batch_file,
            serve_http,
            serve_stdin,
        )

        daemon = ScoringDaemon(
            registry, dataset,
            stochastic=(None if args.stochastic else False),
            seed=args.seed, deadline_ms=args.deadline_ms,
            breaker_k=args.breaker_k,
            breaker_cooldown_s=args.breaker_cooldown_s,
            drift_threshold=args.drift_threshold,
            trace=not args.trace_off)
        if args.warmup:
            walls = registry.warmup(dataset,
                                    stochastic=daemon.stochastic)
            for key, wall in walls.items():
                print(f"[serve] warmed {key} in {wall:.3f}s",
                      file=sys.stderr)
        logger.log("serve_start", models=registry.keys(),
                   compile_cache=cache_dir,
                   n_days=len(dataset.dates), n_max=dataset.n_max)
        print(f"[serve] ready: {len(registry.keys())} model(s), "
              f"panel {len(dataset.dates)}d x {dataset.n_max} "
              f"(cache: {cache_dir or 'off'})", file=sys.stderr)

        if args.batch:
            out = open(args.out, "w") if args.out else sys.stdout
            try:
                n = serve_batch_file(daemon, args.batch, out,
                                     max_batch=args.max_batch or 64)
            finally:
                if args.out:
                    out.close()
            print(f"[serve] answered {n} request(s) from {args.batch}",
                  file=sys.stderr)
        elif args.http is not None:
            scheduler = None
            if args.scheduler:
                # Continuous batching (ISSUE 15): explicit knobs win,
                # else the measured plan row's serve block
                # (autotune_plan.py --serve), else the conservative
                # defaults (2ms window, 64/tick).
                from factorvae_tpu.serve.daemon import TickScheduler

                pl = planlib.plan_for_config(specs[0][2], dataset.n_max) \
                    if kind0 == "checkpoint" else None
                tick_ms = args.tick_ms if args.tick_ms is not None \
                    else (pl.serve_tick_ms
                          if pl is not None and pl.serve_tick_ms >= 0
                          else 2.0)
                max_tick = args.max_batch if args.max_batch is not None \
                    else (pl.serve_max_tick_batch
                          if pl is not None
                          and pl.serve_max_tick_batch > 0 else 64)
                scheduler = TickScheduler(daemon, tick_ms=tick_ms,
                                          max_tick_batch=max_tick)
                print(f"[serve] continuous batching: tick_ms="
                      f"{tick_ms:g} max_tick_batch={max_tick}",
                      file=sys.stderr)
            print(f"[serve] http://127.0.0.1:{args.http}/score",
                  file=sys.stderr)
            serve_http(daemon, args.http, scheduler=scheduler)
        else:
            serve_stdin(daemon, sys.stdin, sys.stdout,
                        tick_s=(20.0 if args.tick_ms is None
                                else args.tick_ms) / 1e3,
                        max_batch=args.max_batch or 64)
        logger.log("serve_stop", **daemon.stats())
        return 0
    finally:
        if args.metrics_jsonl:
            install_timeline(prev_tl)
        logger.finish()


if __name__ == "__main__":
    sys.exit(main())
