"""Remote worker bootstrap: join a fleet with zero local traces.

The agent side of the multi-host serving plane (ISSUE 17). A cold
host runs

    python -m factorvae_tpu.serve --join http://router:8800 \
        --http 8787 --scheduler

and this module turns that into a serving fleet member in three
moves:

1. **Sync** — `GET /artifacts` on the router lists every artifact as
   (alias, sha256, bytes); `fetch_artifact` downloads each blob from
   `GET /artifact/<sha256>` and VERIFIES the digest before a single
   byte lands under its final name (tmp + fsync-free `os.replace`,
   the store's own atomicity discipline). A mismatch retries — the
   transfer may have torn — and exhausted retries raise `JoinError`
   with the observed vs expected digests; a corrupt blob is never
   admitted and never left on disk where a respawn could find it.
   An artifact already on disk that hashes correctly is skipped — a
   respawned agent (the watcher's `kill_remote_worker` recovery path)
   re-joins warm.
2. **Mirror** — the manifest carries the fleet's `dataset_args` and
   worker `extra_args`; `prepare_join` applies them to the agent's
   own argparse namespace (explicit user flags win — argparse only
   fills attributes the namespace doesn't already pin).
3. **Register** — once the daemon's own `/healthz` answers,
   `register_when_healthy`'s thread POSTs `/register` with the host,
   port and the capability digest over what was ACTUALLY
   materialized (same formula as `AotStore.capability_digest`). The
   pool refuses a digest that differs from the fleet's — serving the
   wrong artifact set is the one failure routing can never detect —
   and registration is idempotent by (host, port), so a re-join
   heals the old slot instead of growing the table.

The registry then composes the same verification one layer deeper:
admission passes `expected_sha256` so the bytes are re-hashed at load
(serve/registry.py, the PR-9 manifest discipline extended to the
artifact service).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, Optional

from factorvae_tpu.serve.pool import http_bytes, http_json
from factorvae_tpu.utils.logging import timeline_event, timeline_now


class JoinError(RuntimeError):
    """The join bootstrap failed in a way a retry won't fix."""


def fetch_manifest(router_url: str, timeout: float = 30.0) -> dict:
    """The fleet's `GET /artifacts` manifest."""
    try:
        man = http_json(router_url.rstrip("/") + "/artifacts",
                        timeout=timeout)
    except (OSError, ValueError) as e:
        raise JoinError(
            f"cannot reach the fleet's artifact service at "
            f"{router_url}/artifacts: {e}") from e
    if not (isinstance(man, dict) and man.get("ok")
            and isinstance(man.get("artifacts"), list)):
        raise JoinError(
            f"{router_url}/artifacts answered {str(man)[:200]} — not "
            f"an artifact manifest; is that a router port?")
    return man


def fetch_artifact(router_url: str, alias: str, sha256: str,
                   dest_dir: str, retries: int = 3,
                   timeout: float = 600.0) -> str:
    """Download one artifact by content address into
    `dest_dir/<alias>`, digest-verified BEFORE the bytes land under
    the final name. Returns the path. Never leaves a corrupt file:
    the tmp is unlinked on mismatch and the final name only ever
    appears via `os.replace` of verified bytes."""
    os.makedirs(dest_dir, exist_ok=True)
    dest = os.path.join(dest_dir, alias)
    if os.path.isfile(dest):
        h = hashlib.sha256()
        with open(dest, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() == sha256:
            return dest   # warm re-join: already materialized
    url = (router_url.rstrip("/") + "/artifact/" + sha256)
    last = ""
    for attempt in range(max(1, int(retries))):
        try:
            blob = http_bytes(url, timeout=timeout)
        except (OSError, ValueError) as e:
            last = f"transfer failed: {e}"
            time.sleep(min(2.0, 0.2 * (attempt + 1)))
            continue
        got = hashlib.sha256(blob).hexdigest()
        if got != sha256:
            # torn/corrupt transfer — nothing touches disk; re-fetch
            last = (f"digest mismatch: fetched bytes hash to "
                    f"{got[:12]}… not {sha256[:12]}…")
            timeline_event("join_refetch", cat="serve",
                           resource="remote", alias=alias,
                           attempt=attempt, error=last)
            continue
        tmp = dest + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, dest)
        # sidecar so a local AotStore over dest_dir answers
        # sha256_for without re-hashing
        meta_tmp = dest + ".meta.json.tmp"
        with open(meta_tmp, "w") as fh:
            json.dump({"sha256": sha256, "source": url}, fh)
        os.replace(meta_tmp, dest + ".meta.json")
        return dest
    raise JoinError(
        f"artifact {alias} ({sha256[:12]}…) could not be fetched "
        f"from {url} after {retries} attempts ({last}); the agent "
        f"refuses to serve unverified bytes — check the router's "
        f"store and re-join")


def capability_digest(alias_to_sha: Dict[str, str]) -> str:
    """The digest over what this agent materialized — same formula as
    `AotStore.capability_digest`, so a faithful sync matches the
    fleet byte-for-byte."""
    lines = sorted(f"{a} {s}" for a, s in alias_to_sha.items())
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()


def prepare_join(args, parser) -> str:
    """Bootstrap an argparse namespace from the fleet: download every
    artifact (digest-verified), point `--model` at the local copies,
    mirror the fleet's dataset/worker args (explicit user flags win),
    and stash the per-path expected digests for registry admission.
    Returns the capability digest to present at registration."""
    import tempfile

    man = fetch_manifest(args.join)
    arts = man["artifacts"]
    if not arts:
        raise JoinError(
            f"{args.join}/artifacts lists no artifacts — the fleet "
            f"has nothing to serve yet; start the pool with --model "
            f"first")
    dest = args.aot_store or tempfile.mkdtemp(prefix="join_store_")
    args.aot_store = dest
    paths: Dict[str, str] = {}
    expected: Dict[str, str] = {}
    for a in arts:
        alias, sha = str(a.get("alias")), str(a.get("sha256"))
        p = fetch_artifact(args.join, alias, sha, dest)
        paths[alias] = p
        expected[p] = sha
    if not args.model:
        args.model = [paths[a] for a in sorted(paths)]
    args._expected_sha256 = expected
    # Fleet args: worker extra flags always mirror; panel args only
    # when the user pinned none (argparse leaves attributes already
    # present on the namespace alone unless the flag is in argv).
    argv = [str(x) for x in (man.get("extra_args") or [])]
    if not args.dataset and not args.synthetic:
        argv += [str(x) for x in (man.get("dataset_args") or [])]
    if argv:
        parser.parse_args(argv, namespace=args)
    if args.max_stocks is None and man.get("n_max"):
        args.max_stocks = int(man["n_max"])
    cap = capability_digest(
        {a: expected[p] for a, p in paths.items()})
    fleet_cap = man.get("capability_digest")
    if fleet_cap and cap != fleet_cap:
        raise JoinError(
            f"materialized capability digest {cap[:12]}… does not "
            f"match the fleet's {str(fleet_cap)[:12]}… — the "
            f"manifest changed mid-sync; re-join")
    timeline_event("join_synced", cat="serve", resource="remote",
                   artifacts=len(paths), capability=cap[:12],
                   store=dest)
    return cap


def register_when_healthy(router_url: str, port: int,
                          capability: str,
                          host: str = "127.0.0.1",
                          timeout_s: float = 600.0
                          ) -> threading.Thread:
    """Background thread: poll the daemon's OWN /healthz (it is
    starting up on this same process's serving thread), then POST
    /register to the router — with retries, since the router may
    itself be mid-restart. Daemon thread: it must never outlive the
    serving loop."""

    def run() -> None:
        deadline = time.monotonic() + timeout_s
        me = f"http://127.0.0.1:{port}/healthz"
        while time.monotonic() < deadline:
            try:
                if http_json(me, timeout=2.0).get("ok"):
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.2)
        else:
            return
        backoff = 0.2
        while time.monotonic() < deadline:
            try:
                t0 = timeline_now()
                out = http_json(
                    router_url.rstrip("/") + "/register",
                    payload={"host": host, "port": int(port),
                             "capability": capability},
                    timeout=10.0)
                t1 = timeline_now()
            except (OSError, ValueError):
                out = None
            if isinstance(out, dict) and out.get("ok"):
                # Reverse clock probe: the register response echoes
                # the ROUTER's timeline clock, logged into THIS
                # worker's stream — the mirror of the pool watcher's
                # forward probes, for cross-checking alignment from
                # the agent side (obs/collect.py).
                mono = out.get("mono")
                if (t0 is not None and t1 is not None
                        and isinstance(mono, (int, float))
                        and not isinstance(mono, bool)):
                    timeline_event("clock_probe", cat="serve",
                                   resource="remote",
                                   worker="router",
                                   remote_mono=float(mono),
                                   local_t0=t0, local_t1=t1)
                timeline_event("join_registered", cat="serve",
                               resource="remote", host=host,
                               port=int(port))
                return
            timeline_event("join_register_retry", cat="serve",
                           resource="remote",
                           answer=str(out)[:200])
            time.sleep(backoff)
            backoff = min(5.0, backoff * 2)

    t = threading.Thread(target=run, name="join-register")
    t.daemon = True
    t.start()
    return t
