"""HTTP router over a worker fleet: sticky routing, shedding, fleet
telemetry.

The thin tier that turns N `ScoringDaemon` workers (serve/pool.py)
into ONE serving endpoint (ISSUE 15):

- **Config-hash-sticky routing.** Scoring requests route by their
  `model` name (registry key or alias) through BOUNDED-LOAD RENDEZVOUS
  hashing over the currently-healthy workers: the sticky owner is the
  first candidate in the key's highest-random-weight ranking with
  spare sticky capacity (bound = ceil(assigned_keys / healthy), the
  c=1 consistent-hashing-with-bounded-loads rule — pure rendezvous
  skews badly at registry-sized key counts, and a 4:0 split is a fleet
  that does not scale). Each model's traffic concentrates on ONE
  worker — its warm registry entry, compiled programs and drift chain
  live in one place instead of N — assignments are cached sticky, and
  removing a worker remaps ONLY its own keys (the rendezvous
  property), so a death never cold-shuffles the whole fleet. The
  ranked candidate list doubles as the failover order: a forward that
  fails mid-flight reroutes to the next candidate and marks the worker
  for the pool's watcher.

- **Load shedding.** The router answers 503 with `retry_after_s` (and
  a `Retry-After` header) instead of queueing unboundedly: when the
  in-flight request count crosses `max_inflight`, or when every
  candidate worker for a request is failing/dead. Shed responses are
  `{"ok": false, "error": ..., "retry_after_s": ...}` — the same
  fast-fail shape the daemon's circuit breaker speaks.

- **Fleet telemetry.** `GET /metrics` scrapes every live worker's
  exposition, relabels each family with `worker_id`, merges them under
  single HELP/TYPE headers (obs/metrics.merge_expositions) and
  prepends the router's own families (`factorvae_router_*`).
  `GET /stats` carries the router counters plus the pool's worker
  table — per-worker scrape URLs included, so an operator can always
  reach a single worker directly. `GET /healthz` aggregates: 200
  while any worker is healthy, 503 when the fleet is failing or
  draining.

- **Fan-out admit.** `POST /admit` delegates to
  `pool.admit_fanout` — AOT-store refresh + rolling per-worker
  fidelity-gated alias flips (docs/walkforward.md).

Requests the router cannot attribute to a model (`cmd` requests)
route to the rendezvous owner of the literal key `#cmd` — stable, and
shutdown-by-cmd is deliberately NOT fanned out (stopping the fleet is
the pool's drain, not a client request).

Threading: ThreadingHTTPServer — each client connection is handled on
its own thread, forwarding to workers concurrently. All router
counters live behind `self._lock`; the worker table is read through
the pool's own lock. The SIGTERM drain keeps the daemon's shape: the
handler only sets an Event, the serve loop promotes it.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import threading
from typing import List, Optional

from factorvae_tpu.serve.pool import WorkerPool
from factorvae_tpu.utils.logging import timeline_event


def rendezvous_order(key: str, worker_ids: List[str]) -> List[str]:
    """Workers ranked by highest-random-weight hash for `key`: the
    first is the sticky owner, the rest the failover order. Properties
    the fleet relies on: deterministic across processes (sha256, no
    process-seeded hashing), and MINIMAL disruption — removing a
    worker only remaps keys it owned; every other key keeps its
    owner."""

    def weight(wid: str) -> int:
        h = hashlib.sha256(f"{key}|{wid}".encode()).digest()
        return int.from_bytes(h[:8], "big")

    return sorted(worker_ids, key=lambda w: (-weight(w), w))


class Router:
    """Routing/shedding state over one `WorkerPool`. `serve()` runs
    the blocking CLI loop; `start()`/`stop()` run it on an internal
    thread (bench + tests). `max_inflight=0` disables the depth
    shed."""

    def __init__(self, pool: WorkerPool, max_inflight: int = 64,
                 shed_retry_s: float = 1.0,
                 forward_timeout_s: float = 600.0):
        self.pool = pool
        self.max_inflight = int(max_inflight)
        self.shed_retry_s = float(shed_retry_s)
        self.forward_timeout_s = float(forward_timeout_s)
        self._lock = threading.Lock()
        self.requests = 0
        self.forwarded = 0
        self.shed = 0
        self.reroutes = 0
        self.proxy_errors = 0
        self.inflight = 0
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        # Checked-out/checked-in persistent worker connections:
        # forwarding over keep-alive halves the TCP setups per routed
        # request, and a shared pool (vs thread-locals) lets the
        # per-group forwarding threads reuse them too. A connection is
        # only ever held by one forward at a time.
        self._conns: dict = {}
        # Sticky owner cache: model key -> worker id. Guarded by
        # _lock; entries for no-longer-healthy workers re-place
        # lazily through the bounded-load rule.
        self._assign: dict = {}

    def _candidates(self, key: str, healthy: List[str]) -> List[str]:
        """The key's forward order: sticky owner first (cached, else
        placed by bounded-load rendezvous), then the rendezvous
        ranking as failover. Placement takes the first candidate whose
        sticky-key count is under ceil(keys / workers) — each model
        lives on ONE worker, and no worker owns more than its fair
        share plus the rounding key."""
        if not healthy:
            return []
        order = rendezvous_order(key, healthy)
        with self._lock:
            wid = self._assign.get(key)
            if wid not in healthy:
                counts = {w: 0 for w in healthy}
                live = 0
                for w in self._assign.values():
                    if w in counts:
                        counts[w] += 1
                        live += 1
                bound = -(-(live + 1) // len(healthy))  # ceil
                wid = next((w for w in order if counts[w] < bound),
                           order[0])
                self._assign[key] = wid
        order.remove(wid)
        return [wid] + order

    # ---- routing ---------------------------------------------------------

    def _shed_response(self, why: str) -> dict:
        with self._lock:
            self.shed += 1
        return {"ok": False,
                "error": f"router shedding load: {why}; retry in "
                         f"{self.shed_retry_s:g}s",
                "retry_after_s": self.shed_retry_s}

    def route_batch(self, requests: list) -> list:
        """Answer one client submission: group scoring requests by
        their sticky worker, forward each group, merge responses in
        request order. Per-request failures (no healthy candidate,
        every forward failed) answer in place — one sick model's
        routing must not 503 the rest of the batch."""
        healthy = self.pool.healthy_ids()
        groups: dict = {}
        responses: list = [None] * len(requests)
        for i, req in enumerate(requests):
            if isinstance(req, dict) and "_parse_error" in req:
                responses[i] = {"id": None, "ok": False,
                                "error": req["_parse_error"]}
                continue
            key = "#cmd"
            if isinstance(req, dict) and req.get("model"):
                key = str(req["model"])
            order = self._candidates(key, healthy)
            if not order:
                responses[i] = self._shed_response(
                    "no healthy worker")
                continue
            groups.setdefault(tuple(order), []).append((i, req))
        group_list = list(groups.items())
        # Fan the groups out CONCURRENTLY — a mixed-model batch split
        # over two workers must run on both at once, not serialize the
        # fleet through one proxy thread (the first group rides this
        # thread; responses slots are disjoint per group).
        threads = [threading.Thread(
            target=self._forward_group,
            args=(list(order), items, responses),
            name="router-forward")
            for order, items in group_list[1:]]
        for t in threads:
            t.start()
        if group_list:
            order, items = group_list[0]
            self._forward_group(list(order), items, responses)
        for t in threads:
            t.join()
        return responses

    def _forward(self, wid: str, port: int, body: bytes):
        """POST one group to a worker over a pooled persistent
        connection (fresh one on first use or after any failure — a
        respawned worker keeps its port, so a stale socket heals on
        the retry)."""
        import http.client

        last = None
        for fresh in (False, True):
            conn = None
            if not fresh:
                with self._lock:
                    stack = self._conns.get(wid)
                    if stack:
                        conn = stack.pop()
            if conn is None:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=self.forward_timeout_s)
            try:
                conn.request("POST", "/score", body=body, headers={
                    "Content-Type": "application/json"})
                resp = conn.getresponse()
                out = json.loads(resp.read().decode() or "null")
            except (OSError, ValueError, http.client.HTTPException) \
                    as e:
                last = e
                with contextlib.suppress(OSError):
                    conn.close()
                continue
            with self._lock:
                stack = self._conns.setdefault(wid, [])
                if len(stack) < 16:
                    stack.append(conn)
                    conn = None
            if conn is not None:
                conn.close()
            return out
        raise last

    def _forward_group(self, order: List[str], items: list,
                       responses: list) -> None:
        body = json.dumps([req for _, req in items]).encode()
        for attempt, wid in enumerate(order):
            worker = self.pool.worker(wid)
            try:
                out = self._forward(wid, worker.port, body)
            except Exception as e:
                # Transport failure: the worker just died or hung —
                # tell the pool, reroute to the next candidate.
                with self._lock:
                    self.proxy_errors += 1
                    if attempt + 1 < len(order):
                        self.reroutes += 1
                self.pool.note_failure(wid)
                timeline_event("router_reroute", cat="serve",
                               resource="router", worker=wid,
                               error=str(e)[:200])
                continue
            if isinstance(out, dict):
                out = [out]
            if not isinstance(out, list) or len(out) != len(items):
                with self._lock:
                    self.proxy_errors += 1
                continue
            with self._lock:
                self.forwarded += len(items)
            for (i, _), resp in zip(items, out):
                if isinstance(resp, dict):
                    resp.setdefault("worker", wid)
                responses[i] = resp
            return
        shed = self._shed_response("every candidate worker failed")
        for i, _ in items:
            responses[i] = dict(shed)

    # ---- telemetry -------------------------------------------------------

    def healthz(self) -> dict:
        pool = self.pool.stats()
        healthy, total = pool["healthy"], len(pool["workers"])
        if pool["draining"]:
            status = "draining"
        elif healthy == 0:
            status = "failing"
        elif healthy < total:
            status = "degraded"
        else:
            status = "ok"
        return {"status": status,
                "ok": status in ("ok", "degraded"),
                "workers_healthy": healthy, "workers": total}

    def stats(self) -> dict:
        with self._lock:
            router = {
                "requests": self.requests,
                "forwarded": self.forwarded,
                "shed": self.shed,
                "reroutes": self.reroutes,
                "proxy_errors": self.proxy_errors,
                "inflight": self.inflight,
                "max_inflight": self.max_inflight,
            }
        return {"router": router, "health": self.healthz(),
                "pool": self.pool.stats()}

    def metrics(self) -> str:
        """The fleet-level exposition: router families first, then
        every reachable worker's `/metrics` relabeled with its
        `worker_id` and merged under single family headers."""
        from factorvae_tpu.obs.metrics import (
            PREFIX,
            merge_expositions,
            metric_line,
        )

        pool = self.pool.stats()
        with self._lock:
            counters = [("requests_total", "counter",
                         "client requests through the router",
                         self.requests),
                        ("forwarded_total", "counter",
                         "requests forwarded to a worker",
                         self.forwarded),
                        ("shed_total", "counter",
                         "requests shed with 503 + retry_after",
                         self.shed),
                        ("reroutes_total", "counter",
                         "forwards retried on a failover candidate",
                         self.reroutes),
                        ("proxy_errors_total", "counter",
                         "worker forwards that failed",
                         self.proxy_errors),
                        ("inflight", "gauge",
                         "client requests currently in flight",
                         self.inflight)]
        fam = [(f"{PREFIX}_router_{n}", typ, help_,
                [metric_line(f"{PREFIX}_router_{n}", v)])
               for n, typ, help_, v in counters]
        fam.append((f"{PREFIX}_router_workers", "gauge",
                    "pool workers by liveness",
                    [metric_line(f"{PREFIX}_router_workers",
                                 pool["healthy"],
                                 {"state": "healthy"}),
                     metric_line(f"{PREFIX}_router_workers",
                                 len(pool["workers"]),
                                 {"state": "total"})]))
        fam.append((f"{PREFIX}_router_respawns_total", "counter",
                    "workers respawned by the pool watcher",
                    [metric_line(f"{PREFIX}_router_respawns_total",
                                 pool["respawns"])]))
        parts = []
        for w in pool["workers"]:
            if w["state"] == "dead":
                continue
            try:
                text = self.pool.scrape_metrics(
                    self.pool.worker(w["worker_id"]))
            except Exception as e:
                # a mid-scrape worker death drops ITS families only;
                # the merged exposition carries the rest
                timeline_event("router_scrape_failed", cat="serve",
                               resource="router",
                               worker=w["worker_id"],
                               error=str(e)[:200])
                continue
            parts.append(({"worker_id": w["worker_id"]}, text))
        return merge_expositions(parts, extra_families=fam)

    # ---- HTTP front ------------------------------------------------------

    def _build_server(self, port: int, host: str):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from factorvae_tpu.serve.daemon import _parse_line

        router = self

        class Handler(BaseHTTPRequestHandler):
            # Threaded front + Content-Length on every response:
            # keep-alive is safe and saves a TCP setup per request.
            protocol_version = "HTTP/1.1"

            def _send(self, code: int, payload,
                      retry_after: Optional[float] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if retry_after is not None:
                    self.send_header("Retry-After",
                                     f"{retry_after:g}")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                if self.path == "/healthz":
                    health = router.healthz()
                    self._send(200 if health["ok"] else 503, health)
                elif self.path == "/stats":
                    self._send(200, router.stats())
                elif self.path == "/metrics":
                    from factorvae_tpu.obs.metrics import CONTENT_TYPE

                    body = router.metrics().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(404, {
                        "ok": False,
                        "error": f"unknown path {self.path} (router "
                                 f"serves /score /admit /stats "
                                 f"/metrics /healthz)"})

            def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
                if self.path not in ("/score", "/admit"):
                    self._send(404, {"ok": False,
                                     "error": f"unknown path "
                                              f"{self.path}"})
                    return
                n = int(self.headers.get("Content-Length") or 0)
                requests = _parse_line(self.rfile.read(n).decode())
                if self.path == "/admit":
                    req = requests[0] if requests else {}
                    if not (isinstance(req, dict)
                            and isinstance(req.get("path"), str)):
                        self._send(400, {
                            "ok": False,
                            "error": "POST /admit wants {\"path\": "
                                     "\"<checkpoint dir>\", "
                                     "\"alias\": \"<alias>\"}; the "
                                     "router fans it out to every "
                                     "worker"})
                        return
                    self._send(200, router.pool.admit_fanout(req))
                    return
                single = (len(requests) == 1)
                with router._lock:
                    router.requests += len(requests)
                    overloaded = (router.max_inflight > 0
                                  and router.inflight
                                  >= router.max_inflight)
                    if not overloaded:
                        router.inflight += 1
                if overloaded:
                    shed = router._shed_response(
                        f"inflight >= {router.max_inflight}")
                    self._send(503, shed if single
                               else [dict(shed) for _ in requests],
                               retry_after=router.shed_retry_s)
                    return
                try:
                    responses = router.route_batch(requests)
                finally:
                    with router._lock:
                        router.inflight -= 1
                if single and isinstance(responses[0], dict) \
                        and responses[0].get("retry_after_s") \
                        and "shedding" in str(
                            responses[0].get("error", "")):
                    self._send(503, responses[0],
                               retry_after=router.shed_retry_s)
                    return
                self._send(200, responses if not single
                           else responses[0])

            def log_message(self, fmt, *args):  # stderr stays quiet
                timeline_event("router_http", cat="serve",
                               resource="router", line=fmt % args)

        server = ThreadingHTTPServer((host, port), Handler)
        server.timeout = 0.25
        return server

    def serve(self, port: int, host: str = "127.0.0.1") -> None:
        """The CLI loop: blocks until SIGTERM (drain: stop accepting,
        stop the pool) — the daemon's set-flag-and-return SIGTERM
        shape, promoted to a fleet-wide drain in main-line code."""
        from factorvae_tpu.serve.daemon import _drain_on_sigterm

        server = self._build_server(port, host)
        self.port = port

        class _Stub:
            # _drain_on_sigterm only needs somewhere to hang the flag
            closing = False

            def request_drain(self):
                self.closing = True

        stub = _Stub()
        with _drain_on_sigterm(stub) as term:
            try:
                while not stub.closing:
                    if term.is_set():
                        stub.request_drain()
                        break
                    server.handle_request()
            finally:
                server.server_close()
                self.pool.stop()

    def start(self, port: Optional[int] = None,
              host: str = "127.0.0.1") -> int:
        """Serve on an internal thread (bench/tests); returns the
        port. `stop()` shuts the server down and joins the thread."""
        from factorvae_tpu.serve.pool import free_port

        port = port or free_port()
        server = self._build_server(port, host)
        self._server = server
        self.port = port
        self._thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.1},
            name="router-http")
        self._thread.start()
        return port

    def stop(self, stop_pool: bool = True) -> None:
        server, thread = self._server, self._thread
        self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None and thread.is_alive():
            thread.join(timeout=30)
        if stop_pool:
            self.pool.stop()
