"""HTTP router over a worker fleet: sticky routing, shedding, fleet
telemetry.

The thin tier that turns N `ScoringDaemon` workers (serve/pool.py)
into ONE serving endpoint (ISSUE 15):

- **Config-hash-sticky routing.** Scoring requests route by their
  `model` name (registry key or alias) through BOUNDED-LOAD RENDEZVOUS
  hashing over the currently-healthy workers: the sticky owner is the
  first candidate in the key's highest-random-weight ranking with
  spare sticky capacity (bound = ceil(assigned_keys / healthy), the
  c=1 consistent-hashing-with-bounded-loads rule — pure rendezvous
  skews badly at registry-sized key counts, and a 4:0 split is a fleet
  that does not scale). Each model's traffic concentrates on ONE
  worker — its warm registry entry, compiled programs and drift chain
  live in one place instead of N — assignments are cached sticky, and
  removing a worker remaps ONLY its own keys (the rendezvous
  property), so a death never cold-shuffles the whole fleet. The
  ranked candidate list doubles as the failover order: a forward that
  fails mid-flight reroutes to the next candidate and marks the worker
  for the pool's watcher.

- **Load shedding.** The router answers 503 with `retry_after_s` (and
  a `Retry-After` header) instead of queueing unboundedly: when the
  in-flight request count crosses `max_inflight`, or when every
  candidate worker for a request is failing/dead. Shed responses are
  `{"ok": false, "error": ..., "retry_after_s": ...}` — the same
  fast-fail shape the daemon's circuit breaker speaks.

- **Fleet telemetry.** `GET /metrics` scrapes every live worker's
  exposition, relabels each family with `worker_id`, merges them under
  single HELP/TYPE headers (obs/metrics.merge_expositions) and
  prepends the router's own families (`factorvae_router_*`).
  `GET /stats` carries the router counters plus the pool's worker
  table — per-worker scrape URLs included, so an operator can always
  reach a single worker directly. `GET /healthz` aggregates: 200
  while any worker is healthy, 503 when the fleet is failing or
  draining.

- **Fan-out admit.** `POST /admit` delegates to
  `pool.admit_fanout` — AOT-store refresh + rolling per-worker
  fidelity-gated alias flips (docs/walkforward.md).

- **Multi-host control plane (ISSUE 17).** `POST /register` adopts a
  remote worker into the pool's table (host, port, capability digest —
  refused with an actionable error on a digest mismatch);
  `GET /artifacts` publishes the content-addressed artifact manifest a
  cold host joins from and `GET /artifact/<sha256>` serves the bytes;
  `POST /deregister` is the graceful leave; `POST /upgrade` starts the
  pool's rolling drain/join upgrade on a background thread.

- **Hedged forwards (ISSUE 17).** The router keeps a sliding window of
  client-request latencies; once a forward has been in flight past the
  measured `hedge_quantile` (default p90 — by construction only the
  slowest decile waits that long), the SAME request duplicates to the
  key's second rendezvous candidate, the first answer wins and the
  loser's socket is shut down (its response is discarded, its
  connection never pooled). A hedged pair stays ONE request in every
  counter and in the router's latency histogram; `hedges`/`hedge_wins`
  count the duplication itself. A plan row's `serve` block (or
  `--hedge_ms`) pins the delay instead of measuring it; scoring
  requests are idempotent by construction, which is what makes the
  duplicate safe.

- **Trace plane (ISSUE 20).** Every `/score` request gets a
  deterministic root trace context (`r-<request counter>`, no RNG) at
  ingress — or parents under an incoming `X-Factorvae-Trace` header —
  and the context rides every forward leg as both the header and a
  per-request `trace` body field. Hedged duplicates are sibling spans
  (`h0`/`h1`) of ONE trace annotated winner/loser/cancelled; serial
  failover attempts chain parent spans. `GET /runstream?since=` serves
  the router's own RUN.jsonl tail to the fleet collector
  (obs/collect.py), and the latency histogram carries per-bucket trace
  exemplars. `trace=False` turns propagation off (the bench A/B
  baseline).

Requests the router cannot attribute to a model (`cmd` requests)
route to the rendezvous owner of the literal key `#cmd` — stable, and
shutdown-by-cmd is deliberately NOT fanned out (stopping the fleet is
the pool's drain, not a client request).

Threading: ThreadingHTTPServer — each client connection is handled on
its own thread, forwarding to workers concurrently. All router
counters live behind `self._lock`; the worker table is read through
the pool's own lock. The SIGTERM drain keeps the daemon's shape: the
handler only sets an Event, the serve loop promotes it.
"""

from __future__ import annotations

import collections
import contextlib
import hashlib
import json
import threading
import time
from typing import List, Optional

from factorvae_tpu.obs.trace import (
    TRACE_HEADER,
    child,
    format_header,
    parse_header,
    span_fields,
)
from factorvae_tpu.serve.pool import WorkerPool
from factorvae_tpu.utils.logging import (
    timeline_event,
    timeline_now,
    timeline_span,
    timeline_span_at,
)


class _Cancelled(Exception):
    """A hedged forward lost the race: its socket was shut down by the
    winner. NOT a worker failure — the loser must neither retry nor
    mark the worker failing."""


def rendezvous_order(key: str, worker_ids: List[str]) -> List[str]:
    """Workers ranked by highest-random-weight hash for `key`: the
    first is the sticky owner, the rest the failover order. Properties
    the fleet relies on: deterministic across processes (sha256, no
    process-seeded hashing), and MINIMAL disruption — removing a
    worker only remaps keys it owned; every other key keeps its
    owner."""

    def weight(wid: str) -> int:
        h = hashlib.sha256(f"{key}|{wid}".encode()).digest()
        return int.from_bytes(h[:8], "big")

    return sorted(worker_ids, key=lambda w: (-weight(w), w))


class Router:
    """Routing/shedding state over one `WorkerPool`. `serve()` runs
    the blocking CLI loop; `start()`/`stop()` run it on an internal
    thread (bench + tests). `max_inflight=0` disables the depth
    shed."""

    def __init__(self, pool: WorkerPool, max_inflight: int = 64,
                 shed_retry_s: float = 1.0,
                 forward_timeout_s: float = 600.0,
                 slo_ms: float = 0.0, hedge_ms: float = -1.0,
                 hedge: bool = True, hedge_quantile: float = 0.9,
                 hedge_min_samples: int = 20, trace: bool = True):
        from factorvae_tpu.obs.metrics import LatencyHistogram

        self.pool = pool
        # Trace plane (docs/observability.md pillar 6): when on, every
        # /score request gets a deterministic root context derived from
        # the request counter and the context propagates on every
        # forward leg (header + per-request `trace` field). Off is the
        # bench A/B baseline — routing behavior is identical.
        self.trace_enabled = bool(trace)
        self.max_inflight = int(max_inflight)
        self.shed_retry_s = float(shed_retry_s)
        self.forward_timeout_s = float(forward_timeout_s)
        # SLO declared by --slo_ms / the plan row's serve block: the
        # autoscaler defends it, /stats and /metrics publish it. 0 =
        # none declared (autoscaling then keys on queue depth alone).
        self.slo_ms = float(slo_ms)
        # hedge_ms >= 0 pins the hedge delay; -1 = measure it as the
        # hedge_quantile of the sliding latency window (no hedging
        # until hedge_min_samples latencies have been observed — an
        # unmeasured fleet must not guess).
        self.hedge_enabled = bool(hedge)
        self.hedge_ms = float(hedge_ms)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_samples = int(hedge_min_samples)
        self._lock = threading.Lock()
        self.requests = 0
        self.forwarded = 0
        self.shed = 0
        self.reroutes = 0
        self.proxy_errors = 0
        self.inflight = 0
        self.hedges = 0
        self.hedge_wins = 0
        # One observation per CLIENT request group item — a hedged
        # pair lands exactly one sample. The deque feeds the hedge
        # delay quantile and /stats p50/p99; the histogram feeds
        # /metrics.
        self.lat_hist = LatencyHistogram()
        self._lat_window: collections.deque = collections.deque(
            maxlen=512)
        self._worker_inflight: dict = {}
        # set by serve/__main__ when --autoscale is on; /stats and
        # /metrics publish its state when present
        self.autoscaler = None
        self.last_upgrade: Optional[dict] = None
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        # Checked-out/checked-in persistent worker connections:
        # forwarding over keep-alive halves the TCP setups per routed
        # request, and a shared pool (vs thread-locals) lets the
        # per-group forwarding threads reuse them too. A connection is
        # only ever held by one forward at a time.
        self._conns: dict = {}
        # Sticky owner cache: model key -> worker id. Guarded by
        # _lock; entries for no-longer-healthy workers re-place
        # lazily through the bounded-load rule.
        self._assign: dict = {}

    def _candidates(self, key: str, healthy: List[str]) -> List[str]:
        """The key's forward order: sticky owner first (cached, else
        placed by bounded-load rendezvous), then the rendezvous
        ranking as failover. Placement takes the first candidate whose
        sticky-key count is under ceil(keys / workers) — each model
        lives on ONE worker, and no worker owns more than its fair
        share plus the rounding key."""
        if not healthy:
            return []
        order = rendezvous_order(key, healthy)
        with self._lock:
            wid = self._assign.get(key)
            if wid not in healthy:
                counts = {w: 0 for w in healthy}
                live = 0
                for w in self._assign.values():
                    if w in counts:
                        counts[w] += 1
                        live += 1
                bound = -(-(live + 1) // len(healthy))  # ceil
                wid = next((w for w in order if counts[w] < bound),
                           order[0])
                self._assign[key] = wid
        order.remove(wid)
        return [wid] + order

    # ---- routing ---------------------------------------------------------

    def _shed_response(self, why: str) -> dict:
        with self._lock:
            self.shed += 1
        return {"ok": False,
                "error": f"router shedding load: {why}; retry in "
                         f"{self.shed_retry_s:g}s",
                "retry_after_s": self.shed_retry_s}

    def route_batch(self, requests: list,
                    ctx: Optional[dict] = None) -> list:
        """Answer one client submission: group scoring requests by
        their sticky worker, forward each group, merge responses in
        request order. Per-request failures (no healthy candidate,
        every forward failed) answer in place — one sick model's
        routing must not 503 the rest of the batch.

        `ctx` is the request's root trace context (built at HTTP
        ingress from the request counter); when present the whole
        routing decision runs under a `router_ingress` span and every
        forward leg becomes a child span of it."""
        if ctx is not None:
            with timeline_span("router_ingress", cat="serve",
                               resource="router",
                               **span_fields(ctx,
                                             requests=len(requests))):
                return self._route_batch(requests, ctx)
        return self._route_batch(requests, None)

    def _route_batch(self, requests: list,
                     ctx: Optional[dict]) -> list:
        healthy = self.pool.healthy_ids()
        groups: dict = {}
        responses: list = [None] * len(requests)
        for i, req in enumerate(requests):
            if isinstance(req, dict) and "_parse_error" in req:
                responses[i] = {"id": None, "ok": False,
                                "error": req["_parse_error"]}
                continue
            key = "#cmd"
            if isinstance(req, dict) and req.get("model"):
                key = str(req["model"])
            order = self._candidates(key, healthy)
            if not order:
                responses[i] = self._shed_response(
                    "no healthy worker")
                continue
            groups.setdefault(tuple(order), []).append((i, req))
        group_list = list(groups.items())
        # Fan the groups out CONCURRENTLY — a mixed-model batch split
        # over two workers must run on both at once, not serialize the
        # fleet through one proxy thread (the first group rides this
        # thread; responses slots are disjoint per group).
        threads = [threading.Thread(
            target=self._forward_group,
            args=(list(order), items, responses, ctx, gi),
            name="router-forward")
            for gi, (order, items) in enumerate(group_list[1:], 1)]
        for t in threads:
            t.start()
        if group_list:
            order, items = group_list[0]
            self._forward_group(list(order), items, responses, ctx, 0)
        for t in threads:
            t.join()
        return responses

    def _forward(self, wid: str, host: str, port: int, body: bytes,
                 cancel: Optional[threading.Event] = None,
                 slot: Optional[list] = None,
                 trace_hdr: Optional[str] = None):
        """POST one group to a worker over a pooled persistent
        connection (fresh one on first use or after any failure — a
        respawned worker keeps its port, so a stale socket heals on
        the retry). Hedged legs pass `cancel` (the lost-the-race
        signal) and `slot` (a one-element list the live connection
        parks in so the winner can shut its socket down); a cancelled
        leg raises `_Cancelled` and never pools its connection."""
        import http.client

        last = None
        for fresh in (False, True):
            if cancel is not None and cancel.is_set():
                raise _Cancelled()
            conn = None
            if not fresh:
                with self._lock:
                    stack = self._conns.get(wid)
                    if stack:
                        conn = stack.pop()
            if conn is None:
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.forward_timeout_s)
            if slot is not None:
                slot[0] = conn
            headers = {"Content-Type": "application/json"}
            if trace_hdr is not None:
                headers[TRACE_HEADER] = trace_hdr
            try:
                conn.request("POST", "/score", body=body,
                             headers=headers)
                resp = conn.getresponse()
                out = json.loads(resp.read().decode() or "null")
            except (OSError, ValueError, http.client.HTTPException) \
                    as e:
                if slot is not None:
                    slot[0] = None
                with contextlib.suppress(OSError):
                    conn.close()
                if cancel is not None and cancel.is_set():
                    # the winner shut this socket down mid-recv — a
                    # race loss, not a worker failure
                    raise _Cancelled()
                last = e
                continue
            if slot is not None:
                slot[0] = None
            if cancel is not None and cancel.is_set():
                conn.close()
                raise _Cancelled()
            with self._lock:
                stack = self._conns.setdefault(wid, [])
                if len(stack) < 16:
                    stack.append(conn)
                    conn = None
            if conn is not None:
                conn.close()
            return out
        raise last

    def _try_forward(self, wid: str, body: bytes, n: int,
                     cancel: Optional[threading.Event] = None,
                     slot: Optional[list] = None,
                     trace_hdr: Optional[str] = None
                     ) -> Optional[list]:
        """One validated forward attempt: the worker's answers as a
        list of `n` responses, else None. Transport failures count a
        proxy_error and mark the worker for the watcher; a CANCELLED
        hedge leg counts nothing — losing the race says nothing about
        the worker's health."""
        worker = self.pool.worker(wid)
        with self._lock:
            self._worker_inflight[wid] = \
                self._worker_inflight.get(wid, 0) + 1
        try:
            out = self._forward(wid, worker.host, worker.port, body,
                                cancel=cancel, slot=slot,
                                trace_hdr=trace_hdr)
        except _Cancelled:
            return None
        except Exception as e:
            with self._lock:
                self.proxy_errors += 1
            self.pool.note_failure(wid)
            timeline_event("router_reroute", cat="serve",
                           resource="router", worker=wid,
                           error=str(e)[:200])
            return None
        finally:
            with self._lock:
                self._worker_inflight[wid] = \
                    max(0, self._worker_inflight.get(wid, 1) - 1)
        if isinstance(out, dict):
            out = [out]
        if not isinstance(out, list) or len(out) != n:
            with self._lock:
                self.proxy_errors += 1
            return None
        return out

    # ---- hedging (ISSUE 17) ----------------------------------------------

    def _hedge_delay_s(self) -> Optional[float]:
        """The delay before a forward duplicates, in seconds — or None
        when hedging must not fire: disabled, or auto mode
        (`hedge_ms < 0`) without `hedge_min_samples` measured
        latencies yet (an unmeasured fleet must not guess a delay)."""
        if not self.hedge_enabled:
            return None
        if self.hedge_ms >= 0:
            return self.hedge_ms / 1e3
        with self._lock:
            if len(self._lat_window) < self.hedge_min_samples:
                return None
            lat = sorted(self._lat_window)
        return lat[min(len(lat) - 1,
                       int(self.hedge_quantile * len(lat)))]

    @staticmethod
    def _cancel_leg(cancel: threading.Event, slot: list) -> None:
        """Wake a losing hedge leg: set its cancel flag, then shut the
        parked socket down — `close()` alone does NOT interrupt a
        blocked `recv`, `shutdown(SHUT_RDWR)` does."""
        import socket as _socket

        cancel.set()
        conn = slot[0]
        if conn is not None:
            with contextlib.suppress(OSError):
                if getattr(conn, "sock", None) is not None:
                    conn.sock.shutdown(_socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                conn.close()

    def _forward_hedged(self, primary: str, secondary: str,
                        body_for, n: int, delay_s: float,
                        ctx: Optional[dict] = None,
                        prefix: str = ""):
        """Forward to `primary`; past `delay_s` without an answer,
        duplicate to `secondary` — first validated answer wins, the
        loser's socket is shut down and its (eventual) response
        discarded. Returns `(out, wid, hedged)`; a FAST primary
        failure returns `(None, primary, False)` so the caller's
        serial failover takes over (an immediate failure is reroute
        ground, not hedge ground).

        `body_for(leg_ctx)` serializes the group per leg — the two
        legs of a hedged pair carry DIFFERENT span ids (`h0`/`h1`,
        siblings under the ingress span of the SAME trace), so each
        leg's worker-side spans parent under the leg that actually
        reached it. Each leg emits its own `router_forward` span, but
        only after the coordinator settles the race (the `settled`
        event, set on every return path): the loser's span closes with
        outcome loser/cancelled instead of leaking or lying."""
        import queue

        q: "queue.Queue" = queue.Queue()
        legs: dict = {}
        verdict: dict = {}
        settled = threading.Event()

        def run(wid: str, leg: str) -> None:
            cancel, slot = legs[wid]
            leg_ctx = child(ctx, leg) if ctx is not None else None
            hdr = (format_header(leg_ctx)
                   if leg_ctx is not None else None)
            t0 = time.perf_counter()
            out = self._try_forward(wid, body_for(leg_ctx), n,
                                    cancel=cancel, slot=slot,
                                    trace_hdr=hdr)
            t1 = time.perf_counter()
            q.put((wid, out))
            if leg_ctx is None:
                return
            settled.wait(timeout=30.0)
            if out is None:
                outcome = ("cancelled" if cancel.is_set()
                           else "error")
            else:
                outcome = verdict.get(wid, "ok")
            timeline_span_at("router_forward", t0, t1, cat="serve",
                             resource="router", worker=wid,
                             hedge=leg, outcome=outcome,
                             **span_fields(leg_ctx))

        def launch(wid: str, leg: str) -> None:
            legs[wid] = (threading.Event(), [None])
            threading.Thread(target=run, args=(wid, leg),
                             name="router-hedge").start()

        try:
            launch(primary, f"{prefix}h0")
            try:
                wid, out = q.get(timeout=delay_s)
            except queue.Empty:  # primary is past the delay
                with self._lock:
                    self.hedges += 1
                timeline_event("router_hedge", cat="serve",
                               resource="router", primary=primary,
                               secondary=secondary,
                               delay_ms=round(delay_s * 1e3, 3),
                               **({"trace": ctx["trace_id"]}
                                  if ctx else {}))
                launch(secondary, f"{prefix}h1")
                wid, out = q.get()
                if out is None:
                    wid, out = q.get()  # first finisher failed
            else:
                return out, wid, False  # answered/failed pre-delay
            if out is not None:
                with self._lock:
                    if wid == secondary:
                        self.hedge_wins += 1
                verdict[wid] = "winner"
                for lw in legs:
                    verdict.setdefault(lw, "loser")
                for lw, (cancel, slot) in legs.items():
                    if lw != wid:
                        self._cancel_leg(cancel, slot)
            return out, wid, True
        finally:
            settled.set()

    def _forward_group(self, order: List[str], items: list,
                       responses: list, ctx: Optional[dict] = None,
                       gi: int = 0) -> None:
        # Per-leg serialization: each forward leg stamps ITS span id
        # into every request's `trace` field, so the worker's queue
        # span parents under the leg that actually delivered it (hedge
        # siblings and failover retries carry distinct ids).
        def body_for(leg_ctx: Optional[dict]) -> bytes:
            if leg_ctx is None:
                return json.dumps(
                    [req for _, req in items]).encode()
            reqs = []
            for _, req in items:
                if isinstance(req, dict):
                    req = dict(req)
                    req["trace"] = {
                        "trace_id": leg_ctx["trace_id"],
                        "span_id": leg_ctx["span_id"]}
                reqs.append(req)
            return json.dumps(reqs).encode()

        prefix = f"g{gi}" if gi else ""
        n = len(items)
        t0 = time.monotonic()
        out, wid, start = None, None, 0
        delay = (self._hedge_delay_s() if len(order) >= 2 else None)
        if delay is not None:
            out, wid, hedged = self._forward_hedged(
                order[0], order[1], body_for, n, delay,
                ctx=ctx, prefix=prefix)
            # hand the serial loop whatever the hedge didn't consume
            start = 2 if hedged else 1
            if out is None and start < len(order):
                with self._lock:
                    self.reroutes += 1
        if out is None:
            # Serial failover: attempt k+1 is a CHILD of attempt k's
            # span, so a reroute renders as a cause chain under the
            # ingress span rather than an unordered fan.
            parent_ctx = ctx
            for attempt in range(start, len(order)):
                wid = order[attempt]
                leg_ctx = None
                if parent_ctx is not None:
                    leg_ctx = child(parent_ctx,
                                    f"{prefix}f{attempt}")
                hdr = (format_header(leg_ctx)
                       if leg_ctx is not None else None)
                lt0 = time.perf_counter()
                out = self._try_forward(wid, body_for(leg_ctx), n,
                                        trace_hdr=hdr)
                lt1 = time.perf_counter()
                if leg_ctx is not None:
                    timeline_span_at(
                        "router_forward", lt0, lt1, cat="serve",
                        resource="router", worker=wid,
                        outcome="ok" if out is not None
                        else "error",
                        **span_fields(leg_ctx))
                if out is not None:
                    break
                parent_ctx = leg_ctx or parent_ctx
                if attempt + 1 < len(order):
                    with self._lock:
                        self.reroutes += 1
        if out is not None:
            dt = time.monotonic() - t0
            tid = ctx["trace_id"] if ctx is not None else None
            with self._lock:
                self.forwarded += n
                for _ in range(n):
                    self._lat_window.append(dt)
            for _ in range(n):
                self.lat_hist.observe(dt, trace_id=tid)
            for (i, _), resp in zip(items, out):
                if isinstance(resp, dict):
                    resp.setdefault("worker", wid)
                responses[i] = resp
            return
        shed = self._shed_response("every candidate worker failed")
        for i, _ in items:
            responses[i] = dict(shed)

    # ---- telemetry -------------------------------------------------------

    def healthz(self) -> dict:
        pool = self.pool.stats()
        healthy, total = pool["healthy"], len(pool["workers"])
        if pool["draining"]:
            status = "draining"
        elif healthy == 0:
            status = "failing"
        elif healthy < total:
            status = "degraded"
        else:
            status = "ok"
        return {"status": status,
                "ok": status in ("ok", "degraded"),
                "workers_healthy": healthy, "workers": total}

    def _quantiles(self):
        """(p50_ms, p99_ms) over the sliding latency window, or
        (None, None) before any request landed."""
        with self._lock:
            lat = sorted(self._lat_window)
        if not lat:
            return None, None

        def q(p: float) -> float:
            return lat[min(len(lat) - 1, int(p * len(lat)))] * 1e3

        return q(0.5), q(0.99)

    def autoscale_signals(self) -> dict:
        """The signal dict the autoscaler decides from and /metrics
        exports (obs.metrics.autoscale_families): queue depth,
        observed p50/p99 vs the declared SLO, per-worker inflight,
        fleet liveness."""
        p50, p99 = self._quantiles()
        pool = self.pool.stats()
        with self._lock:
            return {
                "queue_depth": self.inflight,
                "p50_ms": p50,
                "p99_ms": p99,
                "slo_ms": self.slo_ms,
                "workers_healthy": pool["healthy"],
                "workers_total": len(pool["workers"]),
                "worker_inflight": dict(self._worker_inflight),
            }

    def stats(self) -> dict:
        delay = self._hedge_delay_s()
        p50, p99 = self._quantiles()
        with self._lock:
            router = {
                "requests": self.requests,
                "forwarded": self.forwarded,
                "shed": self.shed,
                "reroutes": self.reroutes,
                "proxy_errors": self.proxy_errors,
                "inflight": self.inflight,
                "max_inflight": self.max_inflight,
                "slo_ms": self.slo_ms,
                "observed_p50_ms": p50,
                "observed_p99_ms": p99,
                "worker_inflight": dict(self._worker_inflight),
                "hedge": {
                    "enabled": self.hedge_enabled,
                    "delay_ms": (None if delay is None
                                 else round(delay * 1e3, 3)),
                    "hedges": self.hedges,
                    "hedge_wins": self.hedge_wins,
                },
            }
        out = {"router": router, "health": self.healthz(),
               "pool": self.pool.stats()}
        scaler = self.autoscaler
        if scaler is not None:
            out["autoscale"] = scaler.describe()
        if self.last_upgrade is not None:
            out["last_upgrade"] = self.last_upgrade
        return out

    def metrics(self) -> str:
        """The fleet-level exposition: router families first, then
        every reachable worker's `/metrics` relabeled with its
        `worker_id` and merged under single family headers."""
        from factorvae_tpu.obs.metrics import (
            PREFIX,
            autoscale_families,
            merge_expositions,
            metric_line,
        )

        pool = self.pool.stats()
        signals = self.autoscale_signals()
        with self._lock:
            counters = [("requests_total", "counter",
                         "client requests through the router",
                         self.requests),
                        ("forwarded_total", "counter",
                         "requests forwarded to a worker",
                         self.forwarded),
                        ("shed_total", "counter",
                         "requests shed with 503 + retry_after",
                         self.shed),
                        ("reroutes_total", "counter",
                         "forwards retried on a failover candidate",
                         self.reroutes),
                        ("proxy_errors_total", "counter",
                         "worker forwards that failed",
                         self.proxy_errors),
                        ("hedges_total", "counter",
                         "forwards duplicated past the hedge delay",
                         self.hedges),
                        ("hedge_wins_total", "counter",
                         "hedged forwards won by the speculative "
                         "duplicate",
                         self.hedge_wins),
                        ("inflight", "gauge",
                         "client requests currently in flight",
                         self.inflight)]
        fam = [(f"{PREFIX}_router_{n}", typ, help_,
                [metric_line(f"{PREFIX}_router_{n}", v)])
               for n, typ, help_, v in counters]
        fam.append((f"{PREFIX}_router_workers", "gauge",
                    "pool workers by liveness",
                    [metric_line(f"{PREFIX}_router_workers",
                                 pool["healthy"],
                                 {"state": "healthy"}),
                     metric_line(f"{PREFIX}_router_workers",
                                 len(pool["workers"]),
                                 {"state": "total"})]))
        fam.append((f"{PREFIX}_router_respawns_total", "counter",
                    "workers respawned by the pool watcher",
                    [metric_line(f"{PREFIX}_router_respawns_total",
                                 pool["respawns"])]))
        fam.append((f"{PREFIX}_router_request_latency_seconds",
                    "histogram",
                    "router-observed client request latency (a hedged "
                    "pair observes once)",
                    self.lat_hist.render(
                        f"{PREFIX}_router_request_latency_seconds")))
        fam.extend(autoscale_families(signals))
        scaler = self.autoscaler
        if scaler is not None:
            fam.extend(scaler.metric_families())
        parts = []
        for w in pool["workers"]:
            if w["state"] == "dead":
                continue
            try:
                text = self.pool.scrape_metrics(
                    self.pool.worker(w["worker_id"]))
            except Exception as e:
                # a mid-scrape worker death drops ITS families only;
                # the merged exposition carries the rest
                timeline_event("router_scrape_failed", cat="serve",
                               resource="router",
                               worker=w["worker_id"],
                               error=str(e)[:200])
                continue
            parts.append(({"worker_id": w["worker_id"]}, text))
        return merge_expositions(parts, extra_families=fam)

    # ---- HTTP front ------------------------------------------------------

    def _build_server(self, port: int, host: str):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from factorvae_tpu.serve.daemon import _parse_line

        router = self

        class Handler(BaseHTTPRequestHandler):
            # Threaded front + Content-Length on every response:
            # keep-alive is safe and saves a TCP setup per request.
            protocol_version = "HTTP/1.1"

            def _send(self, code: int, payload,
                      retry_after: Optional[float] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if retry_after is not None:
                    self.send_header("Retry-After",
                                     f"{retry_after:g}")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                if self.path == "/healthz":
                    health = router.healthz()
                    self._send(200 if health["ok"] else 503, health)
                elif self.path == "/stats":
                    self._send(200, router.stats())
                elif self.path == "/metrics":
                    from factorvae_tpu.obs.metrics import CONTENT_TYPE

                    body = router.metrics().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.startswith("/runstream"):
                    from factorvae_tpu.serve.daemon import \
                        _serve_runstream

                    _serve_runstream(self)
                elif self.path == "/artifacts":
                    self._send(200, router.pool.artifact_manifest())
                elif self.path.startswith("/artifact/"):
                    sha = self.path[len("/artifact/"):]
                    path = router.pool.store.blob_path(sha)
                    if path is None:
                        self._send(404, {
                            "ok": False,
                            "error": f"no artifact with sha256 "
                                     f"{sha[:16]}… in the store; "
                                     f"GET /artifacts lists the "
                                     f"aliases + digests this fleet "
                                     f"serves"})
                        return
                    with open(path, "rb") as fh:
                        blob = fh.read()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                else:
                    self._send(404, {
                        "ok": False,
                        "error": f"unknown path {self.path} (router "
                                 f"serves /score /admit /stats "
                                 f"/metrics /healthz /runstream "
                                 f"/artifacts /artifact/<sha256> "
                                 f"/register /deregister /upgrade)"})

            def _control_body(self) -> Optional[dict]:
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    req = json.loads(
                        self.rfile.read(n).decode() or "{}")
                except ValueError:
                    return None
                return req if isinstance(req, dict) else None

            def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
                if self.path == "/register":
                    req = self._control_body()
                    if req is None or not req.get("port"):
                        self._send(400, {
                            "ok": False,
                            "error": "POST /register wants {\"port\": "
                                     "<int>, \"host\": \"...\" "
                                     "(defaults to the caller's "
                                     "address), \"capability\": "
                                     "\"<sha256 digest from GET "
                                     "/artifacts>\"}"})
                        return
                    host = str(req.get("host")
                               or self.client_address[0])
                    try:
                        w = router.pool.adopt_remote(
                            host, int(req["port"]),
                            capability=req.get("capability"))
                    except Exception as e:
                        self._send(400, {"ok": False,
                                         "error": str(e)})
                        return
                    # `mono` echoes the router's timeline clock so the
                    # joining agent can log a REVERSE clock probe into
                    # its own stream (serve/remote.py) — the mirror of
                    # the pool watcher's forward probes.
                    self._send(200, {"ok": True,
                                     "worker": w.describe(),
                                     "mono": timeline_now()})
                    return
                if self.path == "/deregister":
                    req = self._control_body()
                    wid = (req or {}).get("worker_id")
                    if not wid:
                        self._send(400, {
                            "ok": False,
                            "error": "POST /deregister wants "
                                     "{\"worker_id\": \"<wid>\"}"})
                        return
                    try:
                        self._send(200, router.pool.deregister(
                            str(wid)))
                    except Exception as e:
                        self._send(400, {"ok": False,
                                         "error": str(e)})
                    return
                if self.path == "/upgrade":
                    self._control_body()  # drain the request body

                    def run_upgrade() -> None:
                        try:
                            router.last_upgrade = \
                                router.pool.rolling_upgrade()
                        except Exception as e:
                            router.last_upgrade = {
                                "ok": False, "error": str(e)[:500]}

                    router.last_upgrade = {"ok": None,
                                           "running": True}
                    threading.Thread(target=run_upgrade,
                                     name="router-upgrade").start()
                    self._send(200, {
                        "ok": True, "started": True,
                        "note": "rolling upgrade running in the "
                                "background; watch last_upgrade in "
                                "GET /stats"})
                    return
                if self.path not in ("/score", "/admit"):
                    self._send(404, {"ok": False,
                                     "error": f"unknown path "
                                              f"{self.path}"})
                    return
                n = int(self.headers.get("Content-Length") or 0)
                requests = _parse_line(self.rfile.read(n).decode())
                if self.path == "/admit":
                    req = requests[0] if requests else {}
                    if not (isinstance(req, dict)
                            and isinstance(req.get("path"), str)):
                        self._send(400, {
                            "ok": False,
                            "error": "POST /admit wants {\"path\": "
                                     "\"<checkpoint dir>\", "
                                     "\"alias\": \"<alias>\"}; the "
                                     "router fans it out to every "
                                     "worker"})
                        return
                    actx = None
                    if router.trace_enabled:
                        up = parse_header(
                            self.headers.get(TRACE_HEADER))
                        if up is None:
                            from factorvae_tpu.obs.trace import \
                                wire_ctx

                            up = wire_ctx(req)
                        if up is not None:
                            actx = child(up, "admit")
                            req = dict(req)
                            req["trace"] = {
                                "trace_id": actx["trace_id"],
                                "span_id": actx["span_id"]}
                    if actx is not None:
                        with timeline_span(
                                "router_admit", cat="serve",
                                resource="router",
                                **span_fields(actx)):
                            fanned = router.pool.admit_fanout(req)
                    else:
                        fanned = router.pool.admit_fanout(req)
                    self._send(200, fanned)
                    return
                single = (len(requests) == 1)
                ingress = None
                with router._lock:
                    router.requests += len(requests)
                    # Deterministic trace root: the request counter,
                    # stamped under the SAME lock hold that counts the
                    # request — replayable, no host RNG. An incoming
                    # X-Factorvae-Trace header (a wf operator's cycle
                    # span, an upstream router) parents this hop
                    # instead of starting a fresh trace.
                    if router.trace_enabled:
                        up = parse_header(
                            self.headers.get(TRACE_HEADER))
                        ingress = (
                            child(up, "rt") if up is not None
                            else {"trace_id":
                                  f"r-{router.requests:06d}",
                                  "span_id": "in"})
                    overloaded = (router.max_inflight > 0
                                  and router.inflight
                                  >= router.max_inflight)
                    if not overloaded:
                        router.inflight += 1
                if overloaded:
                    shed = router._shed_response(
                        f"inflight >= {router.max_inflight}")
                    self._send(503, shed if single
                               else [dict(shed) for _ in requests],
                               retry_after=router.shed_retry_s)
                    return
                try:
                    responses = router.route_batch(requests,
                                                   ctx=ingress)
                finally:
                    with router._lock:
                        router.inflight -= 1
                if single and isinstance(responses[0], dict) \
                        and responses[0].get("retry_after_s") \
                        and "shedding" in str(
                            responses[0].get("error", "")):
                    self._send(503, responses[0],
                               retry_after=router.shed_retry_s)
                    return
                self._send(200, responses if not single
                           else responses[0])

            def log_message(self, fmt, *args):  # stderr stays quiet
                timeline_event("router_http", cat="serve",
                               resource="router", line=fmt % args)

        server = ThreadingHTTPServer((host, port), Handler)
        server.timeout = 0.25
        return server

    def serve(self, port: int, host: str = "127.0.0.1") -> None:
        """The CLI loop: blocks until SIGTERM (drain: stop accepting,
        stop the pool) — the daemon's set-flag-and-return SIGTERM
        shape, promoted to a fleet-wide drain in main-line code."""
        from factorvae_tpu.serve.daemon import _drain_on_sigterm

        server = self._build_server(port, host)
        self.port = port

        class _Stub:
            # _drain_on_sigterm only needs somewhere to hang the flag
            closing = False

            def request_drain(self):
                self.closing = True

        stub = _Stub()
        with _drain_on_sigterm(stub) as term:
            try:
                while not stub.closing:
                    if term.is_set():
                        stub.request_drain()
                        break
                    server.handle_request()
            finally:
                server.server_close()
                self.pool.stop()

    def start(self, port: Optional[int] = None,
              host: str = "127.0.0.1") -> int:
        """Serve on an internal thread (bench/tests); returns the
        port. `stop()` shuts the server down and joins the thread."""
        from factorvae_tpu.serve.pool import free_port

        port = port or free_port()
        server = self._build_server(port, host)
        self._server = server
        self.port = port
        self._thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.1},
            name="router-http")
        self._thread.start()
        return port

    def stop(self, stop_pool: bool = True) -> None:
        server, thread = self._server, self._thread
        self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None and thread.is_alive():
            thread.join(timeout=30)
        if stop_pool:
            self.pool.stop()
