"""Warm AOT model registry: N compiled scoring models resident at once.

The serving side of the repo (ISSUE 8 / ROADMAP 1): a long-lived
scoring daemon must hold MANY model variants warm — different seeds,
different architectures, yesterday's refit next to today's — and admit
or evict them under a bytes budget, without ever paying a recompile on
the request path. This module owns that state:

- **Keying.** Every entry is keyed by the canonical config hash
  (`utils.logging.config_hash` of the full Config dict) — the same
  digest the `run_meta` stream headers carry, the full-state checkpoint
  metadata embeds (`config` in Checkpointer meta), and the AOT artifact
  header records (eval/export_aot.py). Whatever produced the model, the
  registry and its clients agree on its identity.

- **Sources.** `register_params` admits an in-memory (params, Config)
  pair; `register_checkpoint` admits a weights-only orbax directory
  (the `save_params` layout the trainer writes), resolving the Config
  from the sibling full-state `<dir>_ckpt` manager's metadata or a
  `serve_config.json` drop-in; `register_artifact` admits a serialized
  AOT export through the validated `load_exported` round-trip — the
  cold-start path that involves no flax, no checkpoint and no trace.

- **Precision ladder.** Each entry serves at one rung of
  f32 → bf16 → int8, resolved per entry: an explicit request at
  admission wins, else the measured plan row's `"serve"` block
  (`Plan.serve_precision`, raced by `scripts/autotune_plan.py
  --serve`), else float32. f32 entries score BITWISE what
  `eval/predict.predict_panel` scores (they call exactly that scan
  path); bf16 casts activations; int8 quantizes the weight matrices
  ONCE at admission (`ops/quant.ensure_quantized`) and dequantizes
  inside the compiled program. Tolerances are pinned in
  tests/test_serve.py and documented in docs/serving.md.

- **Warmth.** Compilation is LAZY (first request per entry compiles;
  `warmup()` prefronts it) and SHARED (entries with the same
  (architecture, precision, stochasticity) reuse one compiled scan —
  eval/predict's lru-cached jit factories are the cache). Eviction is
  LRU by parameter bytes against `budget_bytes`; an evicted key
  re-admits from its recorded source on the next request when possible
  (checkpoint/artifact sources reload lazily; in-memory sources are
  gone and say so).

The registry performs NO jit of its own — the request path runs through
`eval/predict.py`'s watched scoring jits, so every compile lands as a
`compile` (or persistent-cache `compile_cached`) record on the
installed timeline for free.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from factorvae_tpu.chaos import fault as chaos_fault
from factorvae_tpu.config import Config
from factorvae_tpu.utils.logging import (
    config_hash,
    timeline_event,
    timeline_span,
)

PRECISIONS = ("float32", "bfloat16", "int8")


class RegistryError(ValueError):
    """Admission/lookup failure with an actionable one-line message."""


def precision_config(config: Config, precision: str) -> Config:
    """The Config an entry actually scores under at one ladder rung:
    f32/bf16 set the activation compute dtype; int8 keeps float32
    activations (the quantization lives on the WEIGHTS — ops/quant.py;
    `scoring_int8` below carries the flag the scorer needs)."""
    if precision not in PRECISIONS:
        raise RegistryError(
            f"precision must be one of {PRECISIONS}; got {precision!r}")
    dtype = "float32" if precision == "int8" else precision
    return dataclasses.replace(
        config, model=dataclasses.replace(config.model,
                                          compute_dtype=dtype))


def _params_nbytes(tree) -> int:
    from factorvae_tpu.ops.quant import tree_nbytes

    return int(tree_nbytes(tree))


def _params_digest(tree) -> str:
    """sha256 over the parameter leaves in tree-path order — the
    weights identity a re-admission is judged by (two trees with the
    same config hash but different bytes are DIFFERENT models; the
    registry must version-bump, never silently refresh-in-place and
    keep stale sibling executables serving)."""
    import hashlib

    import jax
    import numpy as np

    h = hashlib.sha256()
    for path, leaf in sorted(
            jax.tree_util.tree_flatten_with_path(tree)[0],
            key=lambda kv: jax.tree_util.keystr(kv[0])):
        h.update(jax.tree_util.keystr(path).encode())
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode() + str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class Entry:
    """One resident model. `params` is the SERVING tree (pre-quantized
    for int8 entries); `score_config` already carries the rung's
    compute dtype, so the request path never re-derives either."""

    key: str
    config: Config
    precision: str
    params: object = None
    artifact: object = None            # LoadedArtifact (artifact source)
    score_config: Optional[Config] = None
    nbytes: int = 0
    source: str = "params"             # params | checkpoint | artifact
    source_path: Optional[str] = None  # reload origin for re-admission
    alias: Optional[str] = None
    compiled: bool = False
    compile_s: Optional[float] = None
    requests: int = 0
    #: weights identity (sha256 of the serving tree / artifact blob)
    digest: Optional[str] = None
    #: bumped when a re-admission under the SAME key carries DIFFERENT
    #: weights (the walk-forward rollover path) — stats/describe carry
    #: it so "which weights is this key serving" is answerable
    generation: int = 1

    @property
    def int8(self) -> bool:
        return self.precision == "int8"

    def describe(self) -> dict:
        if self.artifact is not None:
            # The arch facts an artifact HAS live in its validated
            # header; h/k/m are baked into the serialized program and
            # honestly unknown — self.config here is only a default
            # placeholder, never report it as the architecture.
            h = self.artifact.header or {}
            arch = {"c": h.get("num_features"), "t": h.get("seq_len"),
                    "h": None, "k": None, "m": None,
                    "n_max": h.get("n_max")}
        else:
            arch = {
                "c": self.config.model.num_features,
                "t": self.config.model.seq_len,
                "h": self.config.model.hidden_size,
                "k": self.config.model.num_factors,
                "m": self.config.model.num_portfolios,
            }
        return {
            "key": self.key, "alias": self.alias,
            "precision": self.precision, "source": self.source,
            "nbytes": self.nbytes, "compiled": self.compiled,
            "compile_s": self.compile_s, "requests": self.requests,
            "generation": self.generation,
            "arch": arch,
        }


def checkpoint_config(path: str) -> Config:
    """Resolve the Config of a weights-only checkpoint directory (the
    `save_params` layout): the sibling full-state `<path>_ckpt`
    manager's latest metadata (the trainer embeds `config` in every
    Checkpointer meta), or a `serve_config.json` inside the directory.
    One-line actionable error when neither exists."""
    path = os.path.abspath(path)
    drop_in = os.path.join(path, "serve_config.json")
    if os.path.exists(drop_in):
        with open(drop_in) as fh:
            return Config.from_dict(json.load(fh))
    mgr_dir = path if path.endswith("_ckpt") else path + "_ckpt"
    if os.path.isdir(mgr_dir):
        import orbax.checkpoint as ocp

        mgr = ocp.CheckpointManager(mgr_dir)
        try:
            step = mgr.latest_step()
            if step is not None:
                out = mgr.restore(step, args=ocp.args.Composite(
                    meta=ocp.args.JsonRestore()))
                cfg_dict = (out["meta"] or {}).get("config")
                if cfg_dict:
                    return Config.from_dict(cfg_dict)
        finally:
            mgr.close()
    raise RegistryError(
        f"cannot resolve the Config for checkpoint {path}: no "
        f"{os.path.basename(mgr_dir)} full-state metadata and no "
        f"serve_config.json — train with checkpoint_every>0 or drop a "
        f"serve_config.json (Config.to_dict) next to the weights")


class ModelRegistry:
    """LRU-by-bytes registry of warm scoring models.

    `budget_bytes=0` (default) means unbounded. `plan_table` overrides
    the planner's table for precision resolution (tests)."""

    #: tombstone cold-start reloads retry this many extra times with
    #: bounded exponential backoff before answering with a
    #: RegistryError — a transient IO/orbax flake costs one retry,
    #: never a dead model. Deterministic admission failures
    #: (RegistryError: missing config, manifest mismatch) never retry:
    #: a corrupt source does not heal on the second read.
    COLD_RETRIES = 2
    COLD_BACKOFF_S = 0.05

    def __init__(self, budget_bytes: int = 0, plan_table=None):
        self.budget_bytes = int(budget_bytes)
        self._plan_table = plan_table
        # One re-entrant lock over admission/lookup/eviction and the
        # hit/miss/eviction tallies (graftlint JGL009): requests mutate
        # this state from whatever thread serves them (the stdin tick
        # loop, an HTTP handler) while `GET /metrics` reads stats() —
        # the LRU OrderedDict and `hits += 1` are not atomic. RLock
        # because a cold-start's register_checkpoint re-enters through
        # _admit. Cold-start RELOADS (disk I/O + bounded backoff
        # sleeps) run OUTSIDE the lock so a retrying model never
        # stalls /metrics, /healthz or other models' lookups; reloads
        # are idempotent re-admissions (freshest wins), so two racing
        # cold-starts of one key are safe.
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, Entry]" = OrderedDict()
        self._aliases: dict = {}
        # Evicted entries with a reload origin on disk leave a
        # tombstone so a later request COLD-STARTS them back in
        # (checkpoint reload or the artifact `load_exported` round
        # trip) instead of failing.
        self._tombstones: dict = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.cold_starts = 0
        # Changed-weights re-admissions under an existing key (the
        # rollover path; each one version-bumps the entry's generation
        # and tombstones its stale sibling rungs).
        self.readmissions = 0
        # Bumped on every admission/eviction (weights may have
        # changed): consumers caching derived state — the daemon's
        # stacked fused-dispatch param trees — invalidate on it.
        self.version = 0

    # ---- admission -------------------------------------------------------

    def _admit(self, entry: Entry) -> str:
        with self._lock:
            self.version += 1
            prev = self._entries.get(entry.key)
            if prev is not None:
                if (prev.digest is not None and entry.digest is not None
                        and prev.digest != entry.digest):
                    # Re-admission under the SAME key with DIFFERENT
                    # weights — the walk-forward rollover: version-bump
                    # the entry and TOMBSTONE every sibling precision
                    # rung derived from the same base hash. Their
                    # executables (int8-quantized trees, serialized
                    # artifact programs) were built from the OLD bytes;
                    # a tombstoned sibling cold-starts from its source
                    # on the next request and picks the fresh weights
                    # up, where the pre-fix behavior silently kept
                    # serving the stale ones.
                    entry.generation = prev.generation + 1
                    self.readmissions += 1
                    stale = self._retire_siblings_locked(entry.key)
                    timeline_event(
                        "registry_readmit", cat="serve",
                        resource="serve", model=entry.key,
                        generation=entry.generation,
                        stale_siblings=stale)
                else:
                    # Same bytes (or an unverifiable side): refresh in
                    # place — the idempotent resume path must not burn
                    # generations or evict healthy siblings.
                    entry.generation = prev.generation
            self._entries[entry.key] = entry
            self._entries.move_to_end(entry.key)
            if entry.alias:
                self._aliases[entry.alias] = entry.key
            self._evict_to_budget()
            return entry.key

    def _base_hash(self, key: str) -> str:
        return key.split(":", 1)[0]

    def _retire_siblings_locked(self, key: str) -> list:
        """Drop every OTHER precision rung of `key`'s base config hash
        (tombstoning reloadable ones). Caller holds the lock."""
        base = self._base_hash(key)
        stale = [k for k in self._entries
                 if k != key and self._base_hash(k) == base]
        for k in stale:
            entry = self._entries.pop(k)
            self.version += 1
            self._tombstone_or_drop(k, entry)
        return stale

    def _tombstone_or_drop(self, key: str, entry: Entry) -> None:
        """Post-removal bookkeeping for an entry already popped from
        `_entries`: a reloadable source leaves a tombstone (the next
        request cold-starts it back in — from the CURRENT bytes on
        disk); otherwise its aliases are unhooked so they cannot
        resolve to a key with nothing behind it. Caller holds the
        lock."""
        if entry.source_path:
            self._tombstones[key] = {
                "source": entry.source,
                "source_path": entry.source_path,
                "precision": entry.precision,
                "config": entry.config,
                "alias": entry.alias,
            }
        else:
            for alias, k in list(self._aliases.items()):
                if k == key:
                    del self._aliases[alias]

    def _resolve_precision(self, config: Config,
                           precision: Optional[str],
                           n_stocks: Optional[int]) -> str:
        """Explicit choice > measured plan row's serve block > float32.
        The plan lookup needs the real cross-section width; without one
        the conservative f32 rung is the only honest answer."""
        if precision is not None:
            if precision not in PRECISIONS:
                raise RegistryError(
                    f"precision must be one of {PRECISIONS}; "
                    f"got {precision!r}")
            return precision
        if n_stocks:
            from factorvae_tpu import plan as planlib

            pl = planlib.plan_for_config(config, int(n_stocks),
                                         table=self._plan_table)
            return pl.serve_precision
        return "float32"

    def register_params(self, params, config: Config,
                        precision: Optional[str] = None,
                        n_stocks: Optional[int] = None,
                        alias: Optional[str] = None,
                        source: str = "params",
                        source_path: Optional[str] = None) -> str:
        """Admit an in-memory (params, Config) pair; returns the key:
        the config hash, suffixed `:{precision}` below the f32 rung so
        one model's f32 and int8 variants are DISTINCT entries (same
        config hash — without the suffix the second admission would
        silently replace the first while both aliases kept resolving).
        Re-admitting an existing key refreshes the entry in place
        (same identity, freshest weights win)."""
        precision = self._resolve_precision(config, precision, n_stocks)
        key = config_hash(config.to_dict())
        if precision != "float32":
            key = f"{key}:{precision}"
        if precision == "int8":
            from factorvae_tpu.ops.quant import ensure_quantized

            params = ensure_quantized(params)
        entry = Entry(
            key=key, config=config, precision=precision, params=params,
            score_config=precision_config(config, precision),
            nbytes=_params_nbytes(params), source=source,
            source_path=source_path, alias=alias,
            digest=_params_digest(params))
        return self._admit(entry)

    def register_checkpoint(self, path: str,
                            config: Optional[Config] = None,
                            precision: Optional[str] = None,
                            n_stocks: Optional[int] = None,
                            alias: Optional[str] = None) -> str:
        """Admit a weights-only checkpoint directory (save_params
        layout). Config resolves per `checkpoint_config` unless given."""
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            raise RegistryError(
                f"no checkpoint directory at {path}; train first "
                f"(cli.py) or pass an AOT artifact file instead")
        # Integrity (ISSUE 9): the same sha256 manifest discipline the
        # trainer's restore path enforces — a weights directory whose
        # bytes no longer match its save_params manifest is never
        # loaded (silently serving garbage scores is the worst failure
        # mode a scoring service has). Pre-manifest directories have no
        # manifest and admit unverified, exactly like pre-manifest
        # training checkpoints.
        from factorvae_tpu.train.checkpoint import verify_params_dir

        bad = verify_params_dir(path)
        if bad is not None:
            timeline_event("serve_quarantine", cat="recovery",
                           resource="serve", path=path, reason=bad)
            raise RegistryError(
                f"checkpoint {path} failed manifest verification ({bad}) "
                f"— the weights on disk are not the bytes save_params "
                f"wrote; re-export from the full-state checkpoint or "
                f"retrain")
        if config is None:
            config = checkpoint_config(path)
        from factorvae_tpu.models.factorvae import load_model

        _, params = load_model(config, checkpoint_path=path, n_max=1)
        return self.register_params(
            params, config, precision=precision, n_stocks=n_stocks,
            alias=alias or os.path.basename(path), source="checkpoint",
            source_path=path)

    def register_artifact(self, path_or_blob,
                          alias: Optional[str] = None,
                          expected_sha256: Optional[str] = None) -> str:
        """Admit a serialized AOT export (eval/export_aot.py) through
        the validated `load_exported` round-trip — the cold-start path.
        The key comes from the artifact HEADER's config hash (headerless
        pre-ISSUE-8 blobs cannot be admitted: the registry has nothing
        to key them on — re-export them).

        `expected_sha256` extends the ISSUE-9 manifest discipline to
        content-addressed artifacts (ISSUE 17): a remote worker that
        fetched the blob from the fleet's artifact service passes the
        advertised digest, and bytes that no longer hash to it are
        REFUSED before any deserialization — a corrupt download (or a
        disk flip between download and admission) never serves."""
        from factorvae_tpu.eval.export_aot import (
            ArtifactError,
            load_exported,
        )

        path = None
        if isinstance(path_or_blob, (bytes, bytearray)):
            blob = bytes(path_or_blob)
        else:
            path = os.path.abspath(path_or_blob)
            with open(path, "rb") as fh:
                blob = fh.read()
        if expected_sha256 is not None:
            import hashlib

            got = hashlib.sha256(blob).hexdigest()
            if got != expected_sha256:
                timeline_event("serve_quarantine", cat="recovery",
                               resource="serve", path=path or "<bytes>",
                               reason="artifact sha256 mismatch")
                raise RegistryError(
                    f"artifact {path or '<bytes>'} hashes to "
                    f"{got[:12]}… but the store advertised "
                    f"{expected_sha256[:12]}… — the bytes are corrupt; "
                    f"re-fetch from the artifact service "
                    f"(GET /artifact/<sha256>) instead of admitting")
        try:
            art = load_exported(blob)
        except ArtifactError as e:
            raise RegistryError(str(e)) from None
        if art.header is None:
            raise RegistryError(
                f"artifact {path or '<bytes>'} has no header (pre-ISSUE-8 "
                f"export); re-export it with cli.py --export so the "
                f"registry can key it by config hash")
        precision = "int8" if art.header.get("int8") else "float32"
        key = str(art.header["config_hash"])
        if precision != "float32":
            # Same suffix rule as register_params: an f32 and an int8
            # export of one checkpoint are distinct registry entries.
            key = f"{key}:{precision}"
        import hashlib

        entry = Entry(
            key=key,
            config=Config(),  # arch facts live in the header
            precision=precision,
            artifact=art, nbytes=len(blob), source="artifact",
            source_path=path,
            alias=alias or (os.path.basename(path) if path else None),
            compiled=True,  # nothing left to trace — the program IS the blob
            digest=hashlib.sha256(blob).hexdigest())
        return self._admit(entry)

    # ---- lookup / eviction ----------------------------------------------

    def resolve_key(self, name: str) -> str:
        with self._lock:
            if name in self._entries or name in self._tombstones:
                return name
            if name in self._aliases:
                return self._aliases[name]
            known = sorted(set(self._entries) | set(self._aliases)
                           | set(self._tombstones))
        raise RegistryError(
            f"unknown model {name!r} (known: {', '.join(known) or 'none'})")

    def get(self, name: str) -> Entry:
        """Entry by key or alias; LRU-touches it. A key that was
        EVICTED but has a reloadable source cold-starts back in
        transparently (checkpoint reload / artifact round-trip; counted
        as a miss, not a hit); a truly unknown key is a miss+error."""
        with self._lock:
            try:
                key = self.resolve_key(name)
            except RegistryError:
                self.misses += 1
                raise
            entry = self._entries.get(key)
            if entry is not None:
                self.hits += 1
                self._entries.move_to_end(key)
                return entry
            # Tombstone stays until the reload SUCCEEDS: a failed
            # cold-start (deleted/corrupt source) must answer this
            # and every later request with an actionable error,
            # never KeyError the daemon on the retry.
            stone = self._tombstones[key]
            self.misses += 1
        # The reload itself — disk I/O, manifest verification, and the
        # bounded backoff sleeps — runs WITHOUT the lock: one model
        # retrying a flaky source must not stall /metrics, /healthz or
        # every other model's lookups for the whole backoff window.
        # register_* re-take the lock for the admission proper, and a
        # racing cold-start of the same key just re-admits (freshest
        # wins, the documented re-admission semantics).
        for attempt in range(self.COLD_RETRIES + 1):
            try:
                # Chaos hook (factorvae_tpu/chaos): a transient
                # cold-start failure — the recovery exercised is
                # exactly this retry loop. A None check when off.
                if chaos_fault("serve_cold_fail") is not None:
                    raise RuntimeError(
                        "chaos: injected cold-start reload failure")
                if stone["source"] == "artifact":
                    self.register_artifact(stone["source_path"],
                                           alias=stone.get("alias"))
                else:
                    self.register_checkpoint(
                        stone["source_path"],
                        config=stone.get("config"),
                        precision=stone.get("precision"),
                        alias=stone.get("alias"))
                break
            except RegistryError:
                # Deterministic admission failure (missing config,
                # manifest mismatch): a retry cannot heal it, and the
                # message is already actionable.
                raise
            except Exception as e:
                # orbax/OSError/... from a vanished or flaky source:
                # bounded exponential-backoff retry, then the request
                # path speaks RegistryError only.
                if attempt == self.COLD_RETRIES:
                    raise RegistryError(
                        f"cold-start of evicted model {name!r} from "
                        f"{stone['source']} {stone['source_path']} "
                        f"failed after {attempt + 1} attempts: "
                        f"{e}") from e
                timeline_event("cold_start_retry", cat="recovery",
                               resource="serve", model=key,
                               attempt=attempt + 1, error=str(e))
                time.sleep(self.COLD_BACKOFF_S * (2 ** attempt))
        with self._lock:
            self.cold_starts += 1
            self._tombstones.pop(key, None)
            entry = self._entries.get(key)
        if entry is None:
            # Admitted and immediately evicted by a concurrent
            # admission racing the bytes budget: answer actionably —
            # the next request cold-starts through the re-laid
            # tombstone.
            raise RegistryError(
                f"cold-started model {name!r} was evicted by a "
                f"concurrent admission before it could serve; retry")
        return entry

    def _evict_to_budget(self) -> None:
        if self.budget_bytes <= 0:
            return
        while (len(self._entries) > 1
               and sum(e.nbytes for e in self._entries.values())
               > self.budget_bytes):
            key, entry = self._entries.popitem(last=False)
            self.version += 1
            self.evictions += 1
            # Reloadable sources leave a tombstone so the next request
            # cold-starts the model back in instead of 404.
            self._tombstone_or_drop(key, entry)

    def set_alias(self, alias: str, name: str) -> str:
        """(Re)point an alias at an entry — the rollover's atomic
        serving flip: requests by alias resolve to the new key from the
        next lookup on. Returns the resolved key."""
        with self._lock:
            key = self.resolve_key(name)
            self._aliases[str(alias)] = key
            self.version += 1
            return key

    def retire(self, name: str) -> bool:
        """Remove an entry from the warm set — the incumbent-drain leg
        of a promotion (serve/daemon.admit drains in-flight requests
        first via the tick lock). Reloadable sources leave a tombstone
        (an old alias or key still resolves by cold-starting the
        CURRENT bytes from disk); in-memory entries drop with their
        aliases. Returns True when something was removed; a name that
        is already gone is a no-op, so a crashed-and-resumed promotion
        retires idempotently."""
        with self._lock:
            try:
                key = self.resolve_key(name)
            except RegistryError:
                return False
            entry = self._entries.pop(key, None)
            if entry is None:
                return False
            self.version += 1
            self._tombstone_or_drop(key, entry)
        timeline_event("registry_retire", cat="serve", resource="serve",
                       model=key)
        return True

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def keys(self) -> list:
        with self._lock:
            return list(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "models": len(self._entries),
                "bytes": self.total_bytes(),
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "cold_starts": self.cold_starts,
                "readmissions": self.readmissions,
                "aliases": dict(sorted(self._aliases.items())),
                "entries": [e.describe()
                            for e in self._entries.values()],
            }

    # ---- scoring ---------------------------------------------------------

    def score(self, name: str, dataset, days: np.ndarray,
              stochastic: Optional[bool] = False,
              seed: int = 0, chunk: Optional[int] = None,
              entry: Optional[Entry] = None) -> np.ndarray:
        """(len(days), N_max) scores for one entry — the serial request
        path. Params entries run the single-scan scoring jit
        (`eval/predict.predict_panel`): the f32 rung is BITWISE that
        path because it IS that path. Artifact entries replay their
        serialized program per day (pre-gathered windows; ~1-ulp from
        the in-graph gather, documented in docs/serving.md). Lazy
        compile-on-first-request: the first call per (arch, precision)
        pays the trace, tracked on the entry. A caller that already
        resolved the Entry (the daemon's request path does, at parse
        time) passes it to keep hits/misses one-count-per-request."""
        if entry is None:
            entry = self.get(name)
        # Chaos hook: a stalled backend (slow device, contended host).
        # The recovery exercised lives in the DAEMON: the per-request
        # deadline turns the stall into an explicit ok:false, and the
        # circuit breaker fast-fails the entry after K of them.
        stall = chaos_fault("serve_stall")
        if stall is not None:
            time.sleep(stall.delay_s)
        t0 = time.perf_counter()
        first = not entry.compiled
        with timeline_span(f"serve_score:{entry.key}", cat="serve",
                           resource="device", model=entry.key,
                           n_days=int(len(days))):
            if entry.artifact is not None:
                out = self._score_artifact(entry, dataset, days)
            else:
                from factorvae_tpu.eval.predict import predict_panel

                kw = {} if chunk is None else {"chunk": int(chunk)}
                out = predict_panel(
                    entry.params, entry.score_config, dataset, days,
                    stochastic=stochastic, seed=seed, int8=entry.int8,
                    **kw)
        if first:
            entry.compiled = True
            entry.compile_s = round(time.perf_counter() - t0, 6)
        entry.requests += 1
        return out

    def _score_artifact(self, entry: Entry, dataset,
                        days: np.ndarray) -> np.ndarray:
        header = entry.artifact.header or {}
        n_max = header.get("n_max")
        if n_max is not None and int(n_max) != int(dataset.n_max):
            raise RegistryError(
                f"artifact {entry.alias or entry.key} was exported for "
                f"n_max={n_max} but the serving panel pads to "
                f"{dataset.n_max}; re-export at this width or align "
                f"--max_stocks")
        out = np.full((len(days), dataset.n_max), np.nan, np.float32)
        for i, day in enumerate(np.asarray(days, np.int64)):
            x, _, mask = dataset.day_batch(int(day))
            scores = entry.artifact.call(
                np.asarray(x, np.float32)[None],
                np.asarray(mask, bool)[None])
            out[i] = np.asarray(scores, np.float32)[0]
        return out

    def warmup(self, dataset, names: Optional[list] = None,
               stochastic: Optional[bool] = False) -> dict:
        """Compile every (or the named) entries against this dataset's
        shapes with a one-day scoring pass — the daemon's --warmup
        path, so the first REAL request is already warm. Returns
        {key: compile_seconds}."""
        days = dataset.split_days(None, None)[:1]
        walls = {}
        with self._lock:
            # snapshot the key list only: the scoring passes below
            # must NOT hold the registry lock through their compiles
            keys = list(names or self._entries)
        for key in keys:
            entry = self.get(key)
            if entry.compiled:
                continue
            self.score(key, dataset, days, stochastic=stochastic)
            walls[entry.key] = entry.compile_s
        return walls
