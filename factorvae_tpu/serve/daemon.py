"""Long-lived scoring daemon: the request path over the model registry.

The deployment story of the paper is cross-sectional scoring of each
new trading day; E2EAI (PAPERS.md) frames it as an end-to-end
production loop. This module is that loop's serving half: a resident
process that holds a panel dataset plus a `ModelRegistry` of warm
models, takes JSONL scoring requests, and answers with per-instrument
scores — through the SAME single-scan scoring jits the offline
evaluator uses, so the f32 rung of the precision ladder is bitwise
`eval/predict.predict_panel` by construction.

**Batched multi-model dispatch.** Requests arriving in one tick are
BUCKETED: params-backed entries that share (architecture, precision,
stochasticity, requested days) stack their param trees and run ONE
`predict_panel_fleet` program — S users' model variants for the price
of one dispatch, the "millions of users" lever fleet training built
(train/fleet.py). Requests that don't bucket (different days, artifact
entries, lone models) dispatch serially through `registry.score`.
Mixed-precision requests never share a bucket; S=1 buckets take the
serial path, so a lone request is always bitwise the offline scan.

**Drivers.** `serve_stdin` (JSONL in/out; a line may be one request
object or an ARRAY of requests — an explicit tick; bursts of single
lines within `tick_s` coalesce into one tick too), `serve_batch_file`
(score a request file, write a response file, exit) and `serve_http`
(stdlib http.server: POST /score, GET /stats /models /healthz) all
funnel into `ScoringDaemon.handle_batch`. Responses preserve request
order; malformed lines get `{"ok": false, "error": ...}` instead of
killing the process.

**Observability.** With a timeline installed (serve `--metrics_jsonl`)
every request emits a `serve_request` span and every fused dispatch a
`serve_dispatch` span into the same RUN.jsonl the scoring jits'
`compile`/`compile_cached` records land in — `python -m
factorvae_tpu.obs.timeline RUN.jsonl` renders the request-level Gantt
with zero extra wiring.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

import numpy as np

from factorvae_tpu.serve.registry import (
    Entry,
    ModelRegistry,
    RegistryError,
)
from factorvae_tpu.utils.logging import timeline_span

_CMDS = ("ping", "stats", "models", "shutdown")


@dataclasses.dataclass
class _Resolved:
    """One parsed request, ready to dispatch."""

    request: dict
    entry: Optional[Entry] = None
    days: Optional[np.ndarray] = None
    error: Optional[str] = None
    cmd: Optional[str] = None
    scores: Optional[np.ndarray] = None   # filled by dispatch
    batched_with: int = 1
    done_t: Optional[float] = None        # when THIS request's scores landed


class ScoringDaemon:
    """Request handler over (registry, dataset).

    `stochastic=False` (default) serves deterministic scores — the
    reproducible-backtest mode; True defers to each entry's config the
    way `predict_panel(stochastic=None)` does. `seed` is the scoring
    RNG stream of the stochastic path, shared across models like the
    sweep shares it across seeds."""

    def __init__(self, registry: ModelRegistry, dataset,
                 stochastic: Optional[bool] = False, seed: int = 0):
        self.registry = registry
        self.dataset = dataset
        self.stochastic = stochastic
        self.seed = seed
        self.requests_served = 0
        self.dispatches = 0
        self.fused_requests = 0
        self._closing = False
        # Fused-dispatch stacked param tree of the MOST RECENT group
        # (keyed by its tuple of entry keys; cleared whenever the
        # registry mutates). Repeat ticks over the same warm models
        # must not re-stack (and re-transfer) every model's weights —
        # that copy would dominate the multi-model hot path — but the
        # cache is capped at one group so the duplicate bytes it holds
        # (invisible to the registry's budget) stay bounded.
        self._stack_cache: dict = {}
        self._stack_version: Optional[int] = None

    # ---- request parsing -------------------------------------------------

    def _resolve_days(self, req: dict) -> np.ndarray:
        ds = self.dataset
        if "day" in req:
            sel = [req["day"]]
        elif "days" in req:
            sel = list(req["days"])
        elif "start" in req or "end" in req:
            return ds.split_days(req.get("start"), req.get("end"))
        else:
            raise ValueError(
                "request needs 'day', 'days' or 'start'/'end'")
        out = []
        import pandas as pd

        dates = pd.DatetimeIndex(ds.dates)
        for d in sel:
            if isinstance(d, (int, np.integer)) and not isinstance(d, bool):
                i = int(d)
                if not 0 <= i < len(dates):
                    raise ValueError(
                        f"day index {i} out of range [0, {len(dates)})")
            else:
                i = dates.get_indexer([pd.Timestamp(str(d))])[0]
                if i < 0:
                    raise ValueError(
                        f"day {d!r} not in the serving panel "
                        f"[{dates[0].date()}, {dates[-1].date()}]")
            out.append(i)
        return np.asarray(out, np.int64)

    def _resolve(self, req) -> _Resolved:
        if not isinstance(req, dict):
            return _Resolved(request={}, error="request must be a JSON "
                                               "object")
        cmd = req.get("cmd")
        if cmd is not None:
            if cmd not in _CMDS:
                return _Resolved(request=req,
                                 error=f"unknown cmd {cmd!r} "
                                       f"(known: {', '.join(_CMDS)})")
            return _Resolved(request=req, cmd=cmd)
        model = req.get("model")
        if not model:
            return _Resolved(request=req,
                             error="request needs a 'model' (key or "
                                   "alias; see {\"cmd\": \"models\"})")
        try:
            entry = self.registry.get(str(model))
            days = self._resolve_days(req)
        except Exception as e:
            # Untrusted request input: whatever a malformed day value
            # (or a failing cold-start) raises becomes an {"ok": false}
            # response, never a daemon death.
            return _Resolved(request=req, error=str(e))
        return _Resolved(request=req, entry=entry, days=days)

    # ---- dispatch --------------------------------------------------------

    def _bucket_key(self, r: _Resolved):
        """Requests fuse when one fleet program can serve them all:
        same scoring config (architecture + rung dtype), same int8
        flag, same day set. Artifact entries never fuse (their program
        is fixed at export)."""
        if r.entry.artifact is not None:
            return None
        return (r.entry.score_config.model, r.entry.int8,
                tuple(int(d) for d in r.days))

    def _dispatch(self, resolved: list) -> None:
        """Fill `scores` on every resolvable request, fusing bucketed
        multi-model groups into one `predict_panel_fleet` call."""
        import jax
        import jax.numpy as jnp

        buckets: dict = {}
        for r in resolved:
            if r.error or r.cmd:
                continue
            key = self._bucket_key(r)
            if key is None:
                self._dispatch_serial(r)
                continue
            buckets.setdefault(key, []).append(r)
        for key, group in buckets.items():
            distinct: dict = {}
            for r in group:
                distinct.setdefault(r.entry.key, r.entry)
            if len(distinct) == 1:
                # One model (possibly asked for twice): the serial,
                # bitwise path — score once, share the result.
                first = None
                for r in group:
                    if first is None:
                        self._dispatch_serial(r)
                        first = r
                    else:
                        r.scores = first.scores
                        r.done_t = first.done_t
                        r.error = first.error
                continue
            entries = list(distinct.values())
            days = group[0].days
            from factorvae_tpu.eval.predict import predict_panel_fleet

            if self._stack_version != self.registry.version:
                self._stack_cache.clear()
                self._stack_version = self.registry.version
            cache_key = tuple(e.key for e in entries)
            try:
                stacked = self._stack_cache.get(cache_key)
                if stacked is None:
                    stacked = jax.tree.map(
                        lambda *xs: jnp.stack(
                            [jnp.asarray(x) for x in xs]),
                        *[e.params for e in entries])
                    self._stack_cache = {cache_key: stacked}
                with timeline_span("serve_dispatch", cat="serve",
                                   resource="device",
                                   models=len(entries),
                                   n_days=int(len(days))):
                    fleet = predict_panel_fleet(
                        stacked, entries[0].score_config, self.dataset,
                        days, stochastic=self.stochastic,
                        seed=self.seed, int8=entries[0].int8)
            except Exception:
                # One bad group (mismatched leaf shapes, an OOM in the
                # S-way program) must not kill the daemon: fall back to
                # the serial path, whose per-request error handling
                # turns failures into {"ok": false} responses.
                self._stack_cache.pop(cache_key, None)
                for r in group:
                    self._dispatch_serial(r)
                continue
            t1 = time.perf_counter()
            self.dispatches += 1
            by_key = {e.key: fleet[i] for i, e in enumerate(entries)}
            # NOTE: entries are NOT marked compiled here — `compiled`
            # means the SERIAL scan program is warm (registry.score /
            # warmup semantics); the fleet program compiled above is a
            # different executable, and marking entries warm off it
            # would make warmup() skip the serial compile a later lone
            # request then pays on the request path.
            for r in group:
                r.scores = by_key[r.entry.key]
                r.batched_with = len(entries)
                r.done_t = t1
                r.entry.requests += 1
                self.fused_requests += 1

    def _dispatch_serial(self, r: _Resolved) -> None:
        try:
            r.scores = self.registry.score(
                r.entry.key, self.dataset, r.days,
                stochastic=self.stochastic, seed=self.seed,
                entry=r.entry)
            r.done_t = time.perf_counter()
            self.dispatches += 1
        except Exception as e:
            # The execution leg of the never-kill-the-process contract:
            # an XLA OOM or a panel/arch shape mismatch (TypeError from
            # the jit) must answer THIS request with {"ok": false}, not
            # take down every other warm model — and the fused path's
            # serial fallback relies on exactly this.
            r.error = str(e)

    # ---- responses -------------------------------------------------------

    def _respond(self, r: _Resolved, t0: float) -> dict:
        rid = (r.request or {}).get("id")
        if r.error is not None:
            return {"id": rid, "ok": False, "error": r.error}
        if r.cmd is not None:
            if r.cmd == "shutdown":
                self._closing = True
                return {"id": rid, "ok": True, "cmd": "shutdown"}
            if r.cmd == "ping":
                return {"id": rid, "ok": True, "cmd": "ping"}
            if r.cmd == "models":
                return {"id": rid, "ok": True, "cmd": "models",
                        "models": self.registry.stats()["entries"]}
            return {"id": rid, "ok": True, "cmd": "stats",
                    **self.stats()}
        ds = self.dataset
        top = (r.request or {}).get("top")
        results = []
        n_total = 0
        valid = ds.valid[r.days]
        inst = np.asarray(ds.instruments)
        for i, day in enumerate(r.days):
            # valid is (n_max,)-padded; instruments covers the REAL
            # cross-section only (pad slots are never valid, but clip
            # defensively rather than index out of range).
            idx = np.nonzero(valid[i])[0]
            idx = idx[idx < inst.size]
            names = inst[idx]
            vals = np.asarray(r.scores[i], np.float32)[idx]
            if top:
                order = np.argsort(-vals)[: int(top)]
                names, vals = names[order], vals[order]
            n_total += int(vals.size)
            results.append({
                "day": str(np.datetime_as_string(
                    np.datetime64(ds.dates[int(day)]), unit="D")),
                "instruments": [str(n) for n in names],
                "scores": [float(v) for v in vals],
            })
        self.requests_served += 1
        return {
            "id": rid, "ok": True,
            "model": r.entry.key, "alias": r.entry.alias,
            "precision": r.entry.precision,
            "n": n_total,
            "batched_with": r.batched_with,
            "results": results,
            # Tick arrival -> THIS request's scores landing: batch-file
            # ticks of many serial dispatch groups must not report
            # every request at the full tick wall.
            "latency_ms": round(
                ((r.done_t or time.perf_counter()) - t0) * 1e3, 3),
        }

    # ---- public API ------------------------------------------------------

    def handle_batch(self, requests: list) -> list:
        """Responses (in order) for one tick's worth of requests."""
        t0 = time.perf_counter()
        with timeline_span("serve_tick", cat="serve", resource="serve",
                           requests=len(requests)):
            resolved = [self._resolve(r) for r in requests]
            self._dispatch(resolved)
            out = []
            for r in resolved:
                with timeline_span("serve_request", cat="serve",
                                   resource="serve",
                                   model=(r.entry.key if r.entry
                                          else None)):
                    out.append(self._respond(r, t0))
        return out

    def handle(self, request: dict) -> dict:
        return self.handle_batch([request])[0]

    @property
    def closing(self) -> bool:
        return self._closing

    def stats(self) -> dict:
        return {
            "requests_served": self.requests_served,
            "dispatches": self.dispatches,
            "fused_requests": self.fused_requests,
            "registry": self.registry.stats(),
        }


# ---------------------------------------------------------------------------
# Drivers
# ---------------------------------------------------------------------------


def _parse_line(line: str) -> list:
    """One JSONL line -> a list of request dicts (an array is an
    explicit batch). A parse failure yields one error-carrying dict the
    daemon turns into an {"ok": false} response."""
    try:
        obj = json.loads(line)
    except ValueError as e:
        return [{"_parse_error": f"bad JSON: {e}"}]
    return obj if isinstance(obj, list) else [obj]


def _with_parse_errors(daemon: ScoringDaemon, requests: list) -> list:
    ok, responses_at = [], {}
    for i, r in enumerate(requests):
        if isinstance(r, dict) and "_parse_error" in r:
            responses_at[i] = {"id": None, "ok": False,
                               "error": r["_parse_error"]}
        else:
            ok.append((i, r))
    answered = daemon.handle_batch([r for _, r in ok])
    for (i, _), resp in zip(ok, answered):
        responses_at[i] = resp
    return [responses_at[i] for i in range(len(requests))]


def _stdin_ticks(inp, tick_s: float, max_batch: int):
    """Yield lists of raw lines, one list per tick. On a selectable
    stream, lines arriving within `tick_s` of each other coalesce into
    one tick (up to `max_batch`); otherwise (StringIO tests) each line
    is its own tick. Reads the RAW fd exclusively — mixing readline
    with select would strand data in Python's buffer."""
    try:
        fd = inp.fileno()
    except (AttributeError, OSError, ValueError):
        for line in inp:
            if line.strip():
                yield [line]
        return
    import select

    buf = b""
    pending: list = []
    eof = False
    while True:
        while b"\n" in buf and len(pending) < max_batch:
            line, buf = buf.split(b"\n", 1)
            if line.strip():
                pending.append(line.decode(errors="replace"))
        if pending and len(pending) >= max_batch:
            yield pending
            pending = []
            continue
        if eof:
            if buf.strip():
                pending.append(buf.decode(errors="replace"))
                buf = b""
            if pending:
                yield pending
            return
        try:
            ready, _, _ = select.select(
                [fd], [], [], tick_s if pending else None)
        except OSError:  # fd closed under us
            eof = True
            continue
        if not ready:
            if pending:
                yield pending
                pending = []
            continue
        data = os.read(fd, 65536)
        if not data:
            eof = True
        else:
            buf += data


def serve_stdin(daemon: ScoringDaemon, inp, out,
                tick_s: float = 0.02, max_batch: int = 64) -> int:
    """JSONL request/response loop until EOF or a shutdown cmd.
    Returns the number of requests answered."""
    answered = 0
    for lines in _stdin_ticks(inp, tick_s, max_batch):
        requests = [r for line in lines for r in _parse_line(line)]
        for resp in _with_parse_errors(daemon, requests):
            out.write(json.dumps(resp) + "\n")
            answered += 1
        out.flush()
        if daemon.closing:
            break
    return answered


def serve_batch_file(daemon: ScoringDaemon, path: str, out,
                     max_batch: int = 64) -> int:
    """Score a JSONL request file as maximally-fused ticks; write JSONL
    responses to `out`. Returns the number answered."""
    with open(path) as fh:
        lines = [ln for ln in fh if ln.strip()]
    requests = [r for line in lines for r in _parse_line(line)]
    answered = 0
    for i in range(0, len(requests), max_batch):
        for resp in _with_parse_errors(daemon,
                                       requests[i:i + max_batch]):
            out.write(json.dumps(resp) + "\n")
            answered += 1
    out.flush()
    return answered


def serve_http(daemon: ScoringDaemon, port: int,
               host: str = "127.0.0.1"):
    """Minimal stdlib HTTP front: POST /score (object or array body),
    GET /stats, /models, /healthz. Single-threaded by design — jax
    dispatch is the bottleneck and wants no concurrency. Blocks until
    a shutdown request arrives."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path == "/healthz":
                self._send(200, {"ok": True})
            elif self.path == "/stats":
                self._send(200, daemon.stats())
            elif self.path == "/models":
                self._send(200, daemon.registry.stats()["entries"])
            else:
                self._send(404, {"ok": False,
                                 "error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path != "/score":
                self._send(404, {"ok": False,
                                 "error": f"unknown path {self.path}"})
                return
            n = int(self.headers.get("Content-Length") or 0)
            requests = _parse_line(self.rfile.read(n).decode())
            responses = _with_parse_errors(daemon, requests)
            # An empty array body gets an empty array back — never an
            # IndexError-dropped connection.
            self._send(200, responses if len(responses) != 1
                       else responses[0])

        def log_message(self, fmt, *args):  # quiet: stdout is sacred
            from factorvae_tpu.utils.logging import timeline_event

            timeline_event("http", cat="serve", resource="serve",
                           line=fmt % args)

    server = HTTPServer((host, port), Handler)
    try:
        while not daemon.closing:
            server.handle_request()
    finally:
        server.server_close()
    return server
