"""Long-lived scoring daemon: the request path over the model registry.

The deployment story of the paper is cross-sectional scoring of each
new trading day; E2EAI (PAPERS.md) frames it as an end-to-end
production loop. This module is that loop's serving half: a resident
process that holds a panel dataset plus a `ModelRegistry` of warm
models, takes JSONL scoring requests, and answers with per-instrument
scores — through the SAME single-scan scoring jits the offline
evaluator uses, so the f32 rung of the precision ladder is bitwise
`eval/predict.predict_panel` by construction.

**Batched multi-model dispatch.** Requests arriving in one tick are
BUCKETED: params-backed entries that share (architecture, precision,
stochasticity, requested days) stack their param trees and run ONE
`predict_panel_fleet` program — S users' model variants for the price
of one dispatch, the "millions of users" lever fleet training built
(train/fleet.py). Requests that don't bucket (different days, artifact
entries, lone models) dispatch serially through `registry.score`.
Mixed-precision requests never share a bucket; S=1 buckets take the
serial path, so a lone request is always bitwise the offline scan.

**Drivers.** `serve_stdin` (JSONL in/out; a line may be one request
object or an ARRAY of requests — an explicit tick; bursts of single
lines within `tick_s` coalesce into one tick too), `serve_batch_file`
(score a request file, write a response file, exit) and `serve_http`
(stdlib http.server: POST /score /profile /admit, GET /stats /models
/healthz /metrics) all funnel into `ScoringDaemon.handle_batch`. Responses
preserve request order; malformed lines get `{"ok": false, "error":
...}` instead of killing the process.

**Observability.** With a timeline installed (serve `--metrics_jsonl`)
every request emits a `serve_request` span and every fused dispatch a
`serve_dispatch` span into the same RUN.jsonl the scoring jits'
`compile`/`compile_cached` records land in — `python -m
factorvae_tpu.obs.timeline RUN.jsonl` renders the request-level Gantt
with zero extra wiring, and `python -m factorvae_tpu.obs.live
RUN_SERVE.jsonl --follow` raises its flags live. On top, the live
telemetry plane (ISSUE 10): a request-latency histogram plus
registry/breaker/health/drift gauges on `GET /metrics` (Prometheus
text, obs/metrics.py), `run_meta` provenance on `/stats` and
`/models`, on-demand `jax.profiler` capture via `POST /profile`, and
per-(model, day) served-score digests with day-over-day rank
correlation (obs/drift.py) flagged as `score_drift` when the ranking
collapses — the regime-shift telemetry ROADMAP item 4's walk-forward
loop consumes.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

import numpy as np

from factorvae_tpu.obs.drift import ScoreDriftMonitor
from factorvae_tpu.obs.metrics import LatencyHistogram
from factorvae_tpu.obs.trace import TRACE_HEADER, parse_header, wire_ctx
from factorvae_tpu.serve.registry import (
    Entry,
    ModelRegistry,
    RegistryError,
)
from factorvae_tpu.utils.logging import (
    run_meta,
    timeline_event,
    timeline_span,
    timeline_span_begin,
    timeline_span_end,
)

_CMDS = ("ping", "stats", "models", "shutdown", "admit")


@dataclasses.dataclass
class _Resolved:
    """One parsed request, ready to dispatch."""

    request: dict
    entry: Optional[Entry] = None
    days: Optional[np.ndarray] = None
    error: Optional[str] = None
    cmd: Optional[str] = None
    scores: Optional[np.ndarray] = None   # filled by dispatch
    batched_with: int = 1
    done_t: Optional[float] = None        # when THIS request's scores landed
    deadline_ms: float = 0.0              # 0 = none
    deadline_from_request: bool = False   # client override, not config
    paid_compile: bool = False            # entry was cold at resolve time
    retry_after_s: Optional[float] = None  # circuit-breaker fast-fail
    fast_failed: bool = False             # never dispatched (breaker open)
    server_fault: bool = False            # resolve failed on OUR side
    shared_outcome: bool = False          # copy of another request's dispatch
    # Trace plane (obs/trace.py): {"trace_id", "base", "n"} — the
    # ingress context this request's spans hang under plus the daemon's
    # per-request sequence number that keeps span ids unique when many
    # requests share one wire context (a wf judge stage).
    trace: Optional[dict] = None
    dispatch_span: Optional[str] = None   # span id of the dispatch leg


class ScoringDaemon:
    """Request handler over (registry, dataset).

    `stochastic=False` (default) serves deterministic scores — the
    reproducible-backtest mode; True defers to each entry's config the
    way `predict_panel(stochastic=None)` does. `seed` is the scoring
    RNG stream of the stochastic path, shared across models like the
    sweep shares it across seeds.

    **Resilience (ISSUE 9, docs/robustness.md).** `deadline_ms` bounds
    every scoring request (a per-request "deadline_ms" field overrides;
    0 disables): a request whose scores land past its deadline answers
    `ok:false` with the measured latency instead of pretending the
    stall didn't happen. A per-entry CIRCUIT BREAKER opens after
    `breaker_k` consecutive failures (dispatch errors or deadline
    misses): requests fast-fail with `retry_after_s` for
    `breaker_cooldown_s` without touching the sick model, then ONE
    probe request is let through (half-open) — success closes the
    breaker, failure re-opens it. `health()` summarizes a sliding
    window of the last `health_window` scoring outcomes into
    ok → degraded → failing (`/healthz` returns 503 only on failing).
    Every breaker transition lands on the timeline as a `circuit_open`
    / `circuit_close` recovery mark."""

    #: LRU cap on cached fused-dispatch stacked param trees (distinct
    #: model groups whose stacked weights stay resident between ticks)
    _STACK_CACHE_GROUPS = 8

    def __init__(self, registry: ModelRegistry, dataset,
                 stochastic: Optional[bool] = False, seed: int = 0,
                 deadline_ms: float = 0.0, breaker_k: int = 3,
                 breaker_cooldown_s: float = 5.0,
                 health_window: int = 64, degraded_at: float = 0.1,
                 failing_at: float = 0.5,
                 drift_threshold: float = 0.5,
                 drift_min_overlap: int = 8,
                 trace: bool = True):
        self.registry = registry
        self.dataset = dataset
        self.stochastic = stochastic
        self.seed = seed
        self.deadline_ms = float(deadline_ms)
        self.breaker_k = max(1, int(breaker_k))
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.degraded_at = float(degraded_at)
        self.failing_at = float(failing_at)
        self.requests_served = 0
        self.dispatches = 0
        self.fused_requests = 0
        self.deadline_misses = 0
        self.breaker_fast_fails = 0
        self.ticks = 0
        # Walk-forward rollover surface (POST /admit, ISSUE 14)
        self.admits = 0
        self.promotions = 0
        # Trace plane (ISSUE 20, obs/trace.py). `trace_enabled=False`
        # drops every trace annotation — the bench A/B's "off" leg.
        # `_trace_seq` uniquifies span ids when requests share a wire
        # context; `_tick_span` is the in-flight tick's span id, the
        # parent dispatch spans chain under. Both mutate under the tick
        # lock only.
        self.trace_enabled = bool(trace)
        self._trace_seq = 0
        self._tick_span: Optional[str] = None
        # Request-latency histogram for /metrics (obs/metrics.py):
        # tick arrival -> scores landing, the same clock latency_ms
        # reports. Host-side counters only — the scoring path and its
        # outputs are untouched.
        self.latency = LatencyHistogram()
        # Served-score drift (obs/drift.py): per-(model, day)
        # distribution digests + day-over-day rank correlation of what
        # this daemon actually answered; collapses below
        # `drift_threshold` emit score_drift marks that obs.report /
        # obs.live flag and /metrics exposes. Digested once per
        # (model, day) — repeat requests for a scored day are free.
        self.drift = ScoreDriftMonitor(threshold=drift_threshold,
                                       min_overlap=drift_min_overlap)
        # Provenance header for scraped snapshots (ISSUE 10): the same
        # run_meta a metrics stream opens with (jax version, platform,
        # git sha, rig env), so a saved /stats or /models payload is
        # ledger-attributable without the RUN.jsonl next to it.
        self.run_meta = run_meta(run_name="serve")
        # The tick lock (graftlint JGL009): one re-entrant lock held
        # for the whole handle_batch tick and by the health/stats
        # readers. The daemon's counters, breaker table, outcome
        # window and fused-dispatch caches are mutated across
        # _dispatch/_respond while `GET /metrics` and `/healthz` read
        # them — in a threaded front (or ROADMAP item 3's N-worker
        # tier) those interleave. Single-tick invariant preserved: the
        # stdlib HTTP driver is single-threaded, so the lock is
        # uncontended there and costs one atomic acquire per tick.
        self._lock = threading.RLock()
        self._closing = False
        self._draining = False
        # key -> {"fails": consecutive failures, "open_until": t}
        self._breakers: dict = {}
        # Sliding scoring-outcome window (True=answered ok) — the
        # error-rate the health status derives from.
        self._outcomes: deque = deque(maxlen=max(1, int(health_window)))
        # Fused-dispatch stacked param trees of RECENT groups (keyed
        # by their tuples of entry keys; cleared whenever the registry
        # mutates). Repeat ticks over the same warm models must not
        # re-stack (and re-transfer) every model's weights — that copy
        # would dominate the multi-model hot path. Under continuous
        # batching (ISSUE 15) tick composition ROTATES between a
        # handful of groups, so the cache is a small LRU
        # (_STACK_CACHE_GROUPS) rather than the previous
        # single-entry slot — measured on this rig, the one-slot cache
        # re-stacked on every alternating tick and the copy, not the
        # scoring, bounded fleet QPS. Duplicate bytes stay bounded by
        # the cap (invisible to the registry's budget either way).
        self._stack_cache: "OrderedDict" = OrderedDict()
        self._stack_version: Optional[int] = None
        # Fused groups that already paid their one-time fleet-program
        # compile (keyed by (entry keys, n_days) — the jit cache's
        # effective key here). `paid_compile` from _resolve only knows
        # the SERIAL warm state; without this, a daemon that only ever
        # scores fused would forgive deadline misses forever.
        self._fused_compiled: set = set()

    # ---- request parsing -------------------------------------------------

    def _resolve_days(self, req: dict) -> np.ndarray:
        ds = self.dataset
        if "day" in req:
            sel = [req["day"]]
        elif "days" in req:
            sel = list(req["days"])
        elif "start" in req or "end" in req:
            return ds.split_days(req.get("start"), req.get("end"))
        else:
            raise ValueError(
                "request needs 'day', 'days' or 'start'/'end'")
        out = []
        import pandas as pd

        dates = pd.DatetimeIndex(ds.dates)
        for d in sel:
            if isinstance(d, (int, np.integer)) and not isinstance(d, bool):
                i = int(d)
                if not 0 <= i < len(dates):
                    raise ValueError(
                        f"day index {i} out of range [0, {len(dates)})")
            else:
                i = dates.get_indexer([pd.Timestamp(str(d))])[0]
                if i < 0:
                    raise ValueError(
                        f"day {d!r} not in the serving panel "
                        f"[{dates[0].date()}, {dates[-1].date()}]")
            out.append(i)
        return np.asarray(out, np.int64)

    def _resolve(self, req) -> _Resolved:
        if not isinstance(req, dict):
            return _Resolved(request={}, error="request must be a JSON "
                                               "object")
        cmd = req.get("cmd")
        if cmd is not None:
            if cmd not in _CMDS:
                return _Resolved(request=req,
                                 error=f"unknown cmd {cmd!r} "
                                       f"(known: {', '.join(_CMDS)})")
            return _Resolved(request=req, cmd=cmd)
        model = req.get("model")
        if not model:
            return _Resolved(request=req,
                             error="request needs a 'model' (key or "
                                   "alias; see {\"cmd\": \"models\"})")
        from_req = "deadline_ms" in req
        try:
            deadline = float(req.get("deadline_ms", self.deadline_ms) or 0)
            days = self._resolve_days(req)
        except Exception as e:
            # Untrusted request input: whatever a malformed deadline or
            # day value raises becomes an {"ok": false} response, never
            # a daemon death — and never a health-window sample (one
            # misconfigured client replaying garbage must not 503 a
            # daemon that is scoring everyone else correctly).
            return _Resolved(request=req, error=str(e))
        try:
            entry = self.registry.get(str(model))
        except Exception as e:
            # A name the registry KNOWS that fails to produce an entry
            # (cold-start reload death after retries) is OUR failure
            # and feeds /healthz; an unknown name is client input.
            try:
                self.registry.resolve_key(str(model))
                known = True
            except RegistryError:
                known = False
            return _Resolved(request=req, error=str(e),
                             server_fault=known)
        return _Resolved(request=req, entry=entry, days=days,
                         deadline_ms=deadline,
                         deadline_from_request=from_req,
                         paid_compile=not entry.compiled)

    def _ingress_ctx(self, req) -> Optional[dict]:
        """The trace context one raw request enters the tick under:
        the request's own `"trace"` field (router forward / scheduler
        queue / wf stage) when present, else a deterministic
        daemon-local root for scoring requests so router-less stdin/
        batch traffic is traceable too. Called under the tick lock
        (mutates `_trace_seq`)."""
        if not self.trace_enabled or not isinstance(req, dict) \
                or req.get("cmd") is not None:
            return None
        ctx = wire_ctx(req)
        if ctx is None and "model" in req:
            self._trace_seq += 1
            ctx = {"trace_id": f"d-{self._trace_seq:06d}",
                   "span_id": "in"}
        return ctx

    # ---- circuit breaker -------------------------------------------------

    def _breaker_gate(self, r: _Resolved) -> bool:
        """True when this request may dispatch. An OPEN breaker inside
        its cooldown fast-fails the request (retry_after_s tells the
        client when); a breaker whose cooldown elapsed goes HALF-OPEN —
        the request proceeds as the probe."""
        b = self._breakers.get(r.entry.key)
        if b is None or b.get("open_until") is None:
            return True
        remaining = b["open_until"] - time.perf_counter()
        if remaining <= 0:
            # half-open: exactly this request probes; re-arm the window
            # so a slow probe doesn't let a burst through behind it.
            b["open_until"] = time.perf_counter() + self.breaker_cooldown_s
            b["half_open"] = True
            return True
        r.error = (
            f"circuit open for model {r.entry.alias or r.entry.key} "
            f"after {b['fails']} consecutive failures; "
            f"retry in {remaining:.2f}s")
        r.retry_after_s = round(remaining, 3)
        r.fast_failed = True
        self.breaker_fast_fails += 1
        return False

    def _breaker_record(self, entry: Entry, ok: bool) -> None:
        """Feed one dispatch outcome (including deadline misses) into
        the entry's breaker; opens after `breaker_k` consecutive
        failures, closes on any success."""
        b = self._breakers.setdefault(
            entry.key, {"fails": 0, "open_until": None,
                        "half_open": False})
        if ok:
            if b["open_until"] is not None:
                # Only an actually-open breaker CLOSES; resetting a
                # sub-threshold failure streak is not a breaker cycle
                # and must not fabricate circuit_close marks in the
                # recovery telemetry.
                timeline_event("circuit_close", cat="recovery",
                               resource="serve", model=entry.key)
            b.update(fails=0, open_until=None, half_open=False)
            return
        b["fails"] += 1
        if b["fails"] >= self.breaker_k or b["half_open"]:
            b["open_until"] = time.perf_counter() + self.breaker_cooldown_s
            b["half_open"] = False
            timeline_event("circuit_open", cat="recovery",
                           resource="serve", model=entry.key,
                           fails=b["fails"],
                           retry_after_s=self.breaker_cooldown_s)

    def open_breakers(self) -> list:
        now = time.perf_counter()
        return sorted(k for k, b in self._breakers.items()
                      if b.get("open_until") is not None
                      and b["open_until"] > now)

    # ---- dispatch --------------------------------------------------------

    def _bucket_key(self, r: _Resolved):
        """Requests fuse when one fleet program can serve them all:
        same scoring config (architecture + rung dtype), same int8
        flag, same day set. Artifact entries never fuse (their program
        is fixed at export)."""
        if r.entry.artifact is not None:
            return None
        return (r.entry.score_config.model, r.entry.int8,
                tuple(int(d) for d in r.days))

    def _dispatch(self, resolved: list) -> None:
        """Fill `scores` on every resolvable request, fusing bucketed
        multi-model groups into one `predict_panel_fleet` call."""
        import jax
        import jax.numpy as jnp

        buckets: dict = {}
        for r in resolved:
            if r.error or r.cmd:
                continue
            if not self._breaker_gate(r):
                continue
            key = self._bucket_key(r)
            if key is None:
                self._dispatch_serial(r)
                continue
            buckets.setdefault(key, []).append(r)
        for bi, (key, group) in enumerate(buckets.items()):
            distinct: dict = {}
            for r in group:
                distinct.setdefault(r.entry.key, r.entry)
            if len(distinct) == 1:
                # One model (possibly asked for twice): the serial,
                # bitwise path — score once, share the result. Copies
                # answer normally but must not re-feed the breaker or
                # the health window: ONE dispatch is one piece of
                # evidence, and K duplicate requests sharing one
                # transient failure must not count as K consecutive
                # failures.
                first = None
                for r in group:
                    if first is None:
                        self._dispatch_serial(r)
                        first = r
                    else:
                        r.scores = first.scores
                        r.done_t = first.done_t
                        r.error = first.error
                        r.shared_outcome = True
                        if r.trace is not None:
                            r.dispatch_span = first.dispatch_span
                continue
            entries = list(distinct.values())
            days = group[0].days
            from factorvae_tpu.eval.predict import predict_panel_fleet

            if self._stack_version != self.registry.version:
                self._stack_cache.clear()
                self._stack_version = self.registry.version
            cache_key = tuple(e.key for e in entries)
            try:
                stacked = self._stack_cache.get(cache_key)
                if stacked is None:
                    stacked = jax.tree.map(
                        lambda *xs: jnp.stack(
                            [jnp.asarray(x) for x in xs]),
                        *[e.params for e in entries])
                    self._stack_cache[cache_key] = stacked
                    while len(self._stack_cache) > \
                            self._STACK_CACHE_GROUPS:
                        self._stack_cache.popitem(last=False)
                else:
                    self._stack_cache.move_to_end(cache_key)
                # Fused dispatch span: one span, many traces — it
                # parents under the tick span and carries the member
                # trace ids so each trace's tree grafts it in.
                d_members = [r for r in group if r.trace is not None]
                dfields: dict = {}
                dspan = None
                if d_members and self._tick_span:
                    dspan = f"{self._tick_span}.d{bi}"
                    dfields = dict(
                        span=dspan, parent=self._tick_span,
                        traces=sorted({r.trace["trace_id"]
                                       for r in d_members})[:16])
                with timeline_span("serve_dispatch", cat="serve",
                                   resource="device",
                                   models=len(entries),
                                   n_days=int(len(days)), **dfields):
                    fleet = predict_panel_fleet(
                        stacked, entries[0].score_config, self.dataset,
                        days, stochastic=self.stochastic,
                        seed=self.seed, int8=entries[0].int8)
            except Exception as e:
                # One bad group (mismatched leaf shapes, an OOM in the
                # S-way program) must not kill the daemon: fall back to
                # the serial path, whose per-request error handling
                # turns failures into {"ok": false} responses.
                timeline_event("fused_fallback", cat="serve",
                               resource="serve", models=len(entries),
                               error=str(e))
                self._stack_cache.pop(cache_key, None)
                for r in group:
                    self._dispatch_serial(r)
                continue
            t1 = time.perf_counter()
            self.dispatches += 1
            by_key = {e.key: fleet[i] for i, e in enumerate(entries)}
            fused_key = (cache_key, int(len(days)))
            paid_fused = fused_key not in self._fused_compiled
            self._fused_compiled.add(fused_key)
            # NOTE: entries are NOT marked compiled here — `compiled`
            # means the SERIAL scan program is warm (registry.score /
            # warmup semantics); the fleet program compiled above is a
            # different executable, and marking entries warm off it
            # would make warmup() skip the serial compile a later lone
            # request then pays on the request path.
            seen_keys: set = set()
            for r in group:
                r.scores = by_key[r.entry.key]
                r.batched_with = len(entries)
                r.done_t = t1
                if r.trace is not None:
                    r.dispatch_span = dspan
                # the fleet program's compile is the fused path's
                # one-time wall (entry.compiled only tracks the SERIAL
                # program — see the NOTE above)
                r.paid_compile = paid_fused
                r.shared_outcome = r.entry.key in seen_keys
                seen_keys.add(r.entry.key)
                r.entry.requests += 1
                self.fused_requests += 1

    def _dispatch_serial(self, r: _Resolved) -> None:
        # A traced request's serial dispatch gets its own span so the
        # per-trace tree shows the dispatch leg whether or not the
        # request fused; untraced requests keep the pre-trace record
        # stream exactly (no new spans).
        dfields: dict = {}
        if r.trace is not None:
            r.dispatch_span = f"{r.trace['base']}.d{r.trace['n']}"
            dfields = dict(trace=r.trace["trace_id"],
                           span=r.dispatch_span,
                           parent=self._tick_span or r.trace["base"])
        cm = (timeline_span("serve_dispatch", cat="serve",
                            resource="device", models=1, **dfields)
              if r.trace is not None else contextlib.nullcontext())
        try:
            with cm:
                r.scores = self.registry.score(
                    r.entry.key, self.dataset, r.days,
                    stochastic=self.stochastic, seed=self.seed,
                    entry=r.entry)
            r.done_t = time.perf_counter()
            self.dispatches += 1
        except Exception as e:
            # The execution leg of the never-kill-the-process contract:
            # an XLA OOM or a panel/arch shape mismatch (TypeError from
            # the jit) must answer THIS request with {"ok": false}, not
            # take down every other warm model — and the fused path's
            # serial fallback relies on exactly this.
            r.error = str(e)

    # ---- responses -------------------------------------------------------

    def _respond(self, r: _Resolved, t0: float) -> dict:
        rid = (r.request or {}).get("id")
        if r.error is not None:
            if (r.entry is not None and not r.fast_failed
                    and not r.shared_outcome):
                # Dispatch-stage failure: feeds the entry's breaker.
                # Fast-fails don't re-record — the breaker is already
                # open and a queue of fast-fails must not extend it.
                # Shared copies don't either: one dispatch, one piece
                # of evidence.
                self._breaker_record(r.entry, False)
            if (r.cmd is None and not r.fast_failed
                    and not r.shared_outcome
                    and (r.entry is not None or r.server_fault)):
                # Health samples are OUR scoring outcomes only.
                # Fast-fails are the BREAKER working, not new evidence:
                # a sick model under client retry traffic must surface
                # as degraded (open_breakers) — not 503 the whole
                # daemon and starve the half-open probe. And client
                # input errors (unknown model, malformed day) are not
                # evidence about the daemon at all.
                self._outcomes.append(False)
            out = {"id": rid, "ok": False, "error": r.error}
            if r.retry_after_s is not None:
                out["retry_after_s"] = r.retry_after_s
            return out
        if r.cmd is not None:
            if r.cmd == "shutdown":
                self._closing = True
                return {"id": rid, "ok": True, "cmd": "shutdown"}
            if r.cmd == "ping":
                return {"id": rid, "ok": True, "cmd": "ping"}
            if r.cmd == "models":
                return {"id": rid, "ok": True, "cmd": "models",
                        "run_meta": self.run_meta,
                        "models": self.registry.stats()["entries"]}
            if r.cmd == "admit":
                # Never reached: handle_batch defers admit cmds OUT of
                # the tick lock (the gate scoring must not stall the
                # tick) and answers them via _cmd_admit below.
                return self._cmd_admit(r)
            return {"id": rid, "ok": True, "cmd": "stats",
                    **self.stats()}
        # Per-request deadline: judged from tick arrival to THIS
        # request's scores landing (the same clock latency_ms reports).
        # The work is already done — the contract is honesty, not
        # cancellation (one jit dispatch is not interruptible): a
        # stalled backend answers ok:false with the measured latency,
        # and K of those in a row open the entry's breaker so later
        # requests stop queueing behind the stall.
        done_lat_ms = ((r.done_t or time.perf_counter()) - t0) * 1e3
        # Every scoring request that produced scores lands one latency
        # sample (ok AND deadline-missed: the stall is the histogram's
        # most interesting tail).
        self.latency.observe(done_lat_ms / 1e3)
        # A miss against the SERVER's own deadline is evidence the
        # model is sick no matter whose deadline the RESPONSE used —
        # including a client that RAISED (or disabled) its deadline and
        # gets ok:true for a dispatch the server's policy calls a stall.
        server_miss = bool(self.deadline_ms) \
            and done_lat_ms > self.deadline_ms
        if r.deadline_ms and done_lat_ms > r.deadline_ms:
            self.deadline_misses += 1
            if not r.shared_outcome:
                if r.paid_compile or (r.deadline_from_request
                                      and not server_miss):
                    # A CLIENT-chosen deadline is that client's latency
                    # budget: as long as the server's own policy holds,
                    # one client sending deadline_ms=0.001 must not
                    # open the shared breaker (fast-failing everyone
                    # else) or drag /healthz toward failing — but a
                    # stall past the SERVER deadline stays a failure
                    # even on a client-deadline response, else override
                    # traffic interleaved with real misses would keep
                    # resetting the streak on a genuinely stalled
                    # backend. A request that paid the ONE-TIME jit
                    # compile (cold first tick without --warmup) is
                    # forgiven outright: the wall it blew the deadline
                    # on is gone for every later request.
                    self._breaker_record(r.entry, True)
                    self._outcomes.append(True)
                else:
                    self._breaker_record(r.entry, False)
                    self._outcomes.append(False)
            return {
                "id": rid, "ok": False,
                "error": (f"deadline exceeded: scores landed at "
                          f"{done_lat_ms:.1f}ms > deadline_ms="
                          f"{r.deadline_ms:g}"),
                "model": r.entry.key, "alias": r.entry.alias,
                "latency_ms": round(done_lat_ms, 3),
            }
        if not r.shared_outcome:
            # ok response, but the evidence is judged by SERVER policy:
            # a stall past --deadline_ms that only answered ok because
            # the client raised its own deadline still feeds the
            # breaker/health as a failure (one-time compile walls
            # excepted) — otherwise override traffic would keep
            # resetting the failure streak on a stalled backend.
            ok_ev = r.paid_compile or not server_miss
            self._breaker_record(r.entry, ok_ev)
            self._outcomes.append(ok_ev)
        ds = self.dataset
        top = (r.request or {}).get("top")
        results = []
        n_total = 0
        valid = ds.valid[r.days]
        inst = np.asarray(ds.instruments)
        for i, day in enumerate(r.days):
            # valid is (n_max,)-padded; instruments covers the REAL
            # cross-section only (pad slots are never valid, but clip
            # defensively rather than index out of range).
            idx = np.nonzero(valid[i])[0]
            idx = idx[idx < inst.size]
            names = inst[idx]
            vals = np.asarray(r.scores[i], np.float32)[idx]
            # Drift feed BEFORE any top-k truncation: the digest and
            # the day-over-day rank pairing must see the full served
            # cross-section (idempotent per (model, day)).
            self.drift.observe(r.entry.key, int(day), names, vals,
                               alias=r.entry.alias)
            if top:
                order = np.argsort(-vals)[: int(top)]
                names, vals = names[order], vals[order]
            n_total += int(vals.size)
            results.append({
                "day": str(np.datetime_as_string(
                    np.datetime64(ds.dates[int(day)]), unit="D")),
                "instruments": [str(n) for n in names],
                "scores": [float(v) for v in vals],
            })
        self.requests_served += 1
        return {
            "id": rid, "ok": True,
            "model": r.entry.key, "alias": r.entry.alias,
            "precision": r.entry.precision,
            "n": n_total,
            "batched_with": r.batched_with,
            "results": results,
            # Tick arrival -> THIS request's scores landing: batch-file
            # ticks of many serial dispatch groups must not report
            # every request at the full tick wall.
            "latency_ms": round(done_lat_ms, 3),
        }

    # ---- walk-forward rollover (ISSUE 14) --------------------------------

    def extend_dataset(self, piece) -> bool:
        """Append new trading days to the serving panel in place
        (PanelDataset.extend_days) under the tick lock, so the in-flight
        tick finishes on the old day axis and the next one sees the new
        days — the walk-forward append stage's serving-side pickup.
        Returns True when days were added (False = idempotent no-op)."""
        with self._lock:
            added = bool(self.dataset.extend_days(piece))
        if added:
            timeline_event("serve_extend", cat="serve", resource="serve",
                           n_days=len(self.dataset.dates))
        return added

    def _holdout_days(self, holdout_days) -> np.ndarray:
        """Resolve the fidelity gate's holdout days: an explicit list
        resolves like a request's 'days' field; default = the newest
        rankably-labeled day per the SHARED holdout rule
        (`eval.metrics.labeled_holdout_days` — the same days the
        walk-forward refit A/B judges on)."""
        if holdout_days:
            return self._resolve_days({"days": list(holdout_days)})
        from factorvae_tpu.eval.metrics import labeled_holdout_days

        days = labeled_holdout_days(self.dataset, 1)
        if not days:
            raise ValueError(
                "no holdout day with >=3 finite labels in the serving "
                "panel; pass explicit holdout_days")
        return np.asarray(days, np.int64)

    def _gate_rank_ic(self, key: str, days: np.ndarray) -> float:
        """Mean holdout Rank-IC of one registry entry, judged by
        ops.stats.masked_spearman (average-rank scipy semantics — the
        same judge the serve precision ladder uses)."""
        from factorvae_tpu.eval.metrics import panel_rank_ic

        ds = self.dataset
        scores = self.registry.score(key, ds, days,
                                     stochastic=self.stochastic,
                                     seed=self.seed)
        return panel_rank_ic(scores, ds.day_labels(days), ds.valid[days])

    def admit(self, path: str, alias: str,
              holdout_days=None, min_margin: float = 0.0,
              drift_threshold: Optional[float] = None,
              precision: Optional[str] = None,
              trace: Optional[dict] = None) -> dict:
        """Trace-aware wrapper over `_admit_impl`: `trace` is a wire
        context ({"trace_id", "span_id"} — a wf promote stage, or the
        X-Factorvae-Trace header on `POST /admit`) under which the
        whole admission renders as one `serve_admit` span in the
        cycle's tree. Traceless admits are untouched."""
        kw = dict(holdout_days=holdout_days, min_margin=min_margin,
                  drift_threshold=drift_threshold, precision=precision)
        ctx = wire_ctx({"trace": trace}) if trace is not None else None
        if ctx is None or not self.trace_enabled:
            return self._admit_impl(path, alias, **kw)
        with timeline_span("serve_admit", cat="serve", resource="serve",
                           alias=str(alias), trace=ctx["trace_id"],
                           span=f"{ctx['span_id']}.a",
                           parent=ctx["span_id"]):
            return self._admit_impl(path, alias, **kw)

    def _admit_impl(self, path: str, alias: str,
                    holdout_days=None, min_margin: float = 0.0,
                    drift_threshold: Optional[float] = None,
                    precision: Optional[str] = None) -> dict:
        """The rollover control surface (`POST /admit` / cmd "admit"):
        admit a candidate checkpoint into the live registry under its
        config hash, judge it against the incumbent behind `alias`
        with a fidelity gate — candidate Rank-IC vs incumbent Rank-IC
        on the holdout day(s), by `masked_spearman` — and on a win flip
        the alias and DRAIN the incumbent (the flip happens under the
        tick lock, so every in-flight request completes on the model
        that was serving when it arrived; zero requests drop). Losers
        are retired from the registry and logged. With no incumbent
        behind `alias` the candidate is promoted unconditionally (the
        bootstrap admission).

        The gate SCORING runs outside the tick lock — a slow gate must
        not stall /healthz or the request path; only the promotion
        mutation itself serializes with ticks. Crash-idempotent: a kill
        between admission and drain (the `kill_between_admit_and_drain`
        chaos class) leaves the incumbent serving; re-running admit
        re-admits the same bytes (a refresh, not a generation bump) and
        completes the flip."""
        from factorvae_tpu import chaos

        alias = str(alias)
        with self._lock:
            self.admits += 1
            admit_no = self.admits   # chaos coordinate: Nth admission
            try:
                # Resolve the incumbent's KEY only — resolve_key
                # touches no disk. A tombstoned incumbent must not
                # cold-start (checkpoint reload + sha256 verify) under
                # the tick lock; the gate scoring below runs outside
                # it and cold-starts on demand.
                inc_key = self.registry.resolve_key(alias)
            except RegistryError as e:
                # Nothing behind the alias: bootstrap admission.
                inc_key = None
                timeline_event("admit_no_incumbent", cat="serve",
                               resource="serve", alias=alias,
                               error=str(e))
        cand_key = self.registry.register_checkpoint(
            str(path), precision=precision,
            n_stocks=self.dataset.n_max)
        out = {"ok": True, "alias": alias, "model": cand_key,
               "incumbent": inc_key}
        cand_ic = inc_ic = None
        reason = "no incumbent behind alias (bootstrap admission)"
        promote = True
        if inc_key is not None and inc_key != cand_key:
            try:
                days = self._holdout_days(holdout_days)
                cand_ic = self._gate_rank_ic(cand_key, days)
                inc_ic = self._gate_rank_ic(inc_key, days)
            except Exception:
                # A gate that cannot judge (no labeled holdout day,
                # scoring failure, a dead incumbent cold-start) must
                # not leave the never-gated candidate resident —
                # retire it before surfacing the error; whatever was
                # serving keeps serving.
                self.registry.retire(cand_key)
                raise
            out["holdout_days"] = [int(d) for d in days]
            if np.isnan(cand_ic):
                # An unrankable candidate never ships — even against an
                # equally unrankable incumbent (known beats unknown).
                promote, reason = False, "candidate Rank-IC undefined"
            elif np.isnan(inc_ic):
                promote, reason = True, "incumbent Rank-IC undefined"
            else:
                promote = cand_ic >= inc_ic - float(min_margin)
                reason = (f"candidate {cand_ic:+.4f} vs incumbent "
                          f"{inc_ic:+.4f} (margin {min_margin:g})")
        elif inc_key is not None:
            # Same config hash: the admission above already refreshed
            # the serving entry in place (version-bump semantics live
            # in the registry); there is no second model to gate.
            reason = "same config hash as incumbent (in-place refresh)"
        if chaos.fault("fidelity_gate_reject",
                       request=admit_no) is not None:
            promote, reason = False, "chaos: forced fidelity-gate reject"
        out.update(candidate_rank_ic=cand_ic, incumbent_rank_ic=inc_ic,
                   reason=reason)
        if not promote:
            if inc_key is not None and inc_key != cand_key:
                self.registry.retire(cand_key)
            timeline_event("admit_rejected", cat="serve",
                           resource="serve", model=cand_key,
                           alias=alias, reason=reason,
                           candidate_rank_ic=cand_ic,
                           incumbent_rank_ic=inc_ic)
            out["promoted"] = False
            return out
        # Chaos window: candidate admitted + verdict in, alias not yet
        # flipped — a kill here leaves the incumbent serving and the
        # promote stage re-runs idempotently. `request` pins the Nth
        # admission of the process (the wf rig's bootstrap re-admit is
        # #1, the cycle's promote #2).
        if chaos.fault("kill_between_admit_and_drain",
                       request=admit_no) is not None:
            chaos.ops.kill_now()
        with self._lock:
            # The flip + drain, serialized with ticks: in-flight
            # requests finished on the incumbent; the next tick
            # resolves the alias to the candidate.
            self.registry.set_alias(alias, cand_key)
            if inc_key is not None and inc_key != cand_key:
                self.registry.retire(inc_key)
                # The retired incumbent's per-model threshold override
                # goes with it — a long-lived nightly daemon must not
                # accumulate one stale entry per promoted cycle.
                self.drift.set_threshold(inc_key, None)
            if drift_threshold is not None:
                self.drift.set_threshold(cand_key,
                                         float(drift_threshold))
            self.promotions += 1
        timeline_event("admit_promoted", cat="serve", resource="serve",
                       model=cand_key, alias=alias,
                       incumbent=out["incumbent"], reason=reason,
                       candidate_rank_ic=cand_ic,
                       incumbent_rank_ic=inc_ic)
        entry = self.registry.get(cand_key)
        out.update(promoted=True, generation=entry.generation,
                   precision=entry.precision)
        return out

    def _cmd_admit(self, r: _Resolved) -> dict:
        """The {"cmd": "admit"} surface, executed OUTSIDE the tick
        lock (handle_batch defers it past the locked section): the
        admission's checkpoint load + gate scoring must not stall the
        tick, /healthz or the operator thread — the same contract the
        HTTP /admit route keeps. Consequence (documented in
        docs/serving.md): the flip takes effect from the NEXT tick."""
        rid = (r.request or {}).get("id")
        req = r.request or {}
        if not isinstance(req.get("path"), str):
            return {"id": rid, "ok": False,
                    "error": "admit wants a 'path' (candidate "
                             "checkpoint directory) and an 'alias'"}
        try:
            return {"id": rid, "cmd": "admit", **self.admit(
                req["path"], req.get("alias", "prod"),
                holdout_days=req.get("holdout_days"),
                min_margin=float(req.get("min_margin", 0.0) or 0),
                drift_threshold=req.get("drift_threshold"),
                precision=req.get("precision"),
                trace=req.get("trace"))}
        except Exception as e:
            # Admission failures (bad path, manifest mismatch,
            # unresolvable config) answer THIS request — the
            # incumbent keeps serving, the daemon keeps living.
            return {"id": rid, "ok": False, "error": str(e)}

    # ---- public API ------------------------------------------------------

    def handle_batch(self, requests: list) -> list:
        """Responses (in order) for one tick's worth of requests.
        Runs under the tick lock: every counter/breaker/window
        mutation below (including the ones inside _dispatch/_respond)
        is serialized against the health/stats/metrics readers.
        Admit cmds are the exception: they are answered AFTER the
        locked section (slot order preserved) so their checkpoint load
        + gate scoring never stalls the tick — scoring requests in the
        same tick resolve against tick-start state either way."""
        t0 = time.perf_counter()
        admits: list = []
        with self._lock:
            self.ticks += 1
            # Trace plane: the tick span is SHARED by every traced
            # request it fuses — it carries the member trace ids
            # (`traces`) plus the member ingress span ids (`members`)
            # the renderer grafts it under, and its own id parents the
            # dispatch spans. Ids stay deterministic: ingress span id +
            # the daemon's tick counter.
            bases = [self._ingress_ctx(r) for r in requests]
            traced = [b for b in bases if b is not None]
            tick_fields: dict = {}
            self._tick_span = None
            if traced:
                self._tick_span = f"{traced[0]['span_id']}.t{self.ticks}"
                tick_fields = dict(
                    span=self._tick_span,
                    traces=sorted({b["trace_id"] for b in traced})[:16],
                    members=[b["span_id"] for b in traced][:64])
            with timeline_span("serve_tick", cat="serve",
                               resource="serve",
                               requests=len(requests), **tick_fields):
                resolved = [self._resolve(r) for r in requests]
                for r, base in zip(resolved, bases):
                    if base is not None:
                        self._trace_seq += 1
                        r.trace = {"trace_id": base["trace_id"],
                                   "base": base["span_id"],
                                   "n": self._trace_seq}
                self._dispatch(resolved)
                out = []
                for r in resolved:
                    if r.cmd == "admit":
                        admits.append((len(out), r))
                        out.append(None)
                        continue
                    tf: dict = {}
                    if r.trace is not None:
                        tf = dict(
                            trace=r.trace["trace_id"],
                            span=f"{r.trace['base']}.r{r.trace['n']}",
                            parent=(r.dispatch_span or self._tick_span
                                    or r.trace["base"]))
                    with timeline_span("serve_request", cat="serve",
                                       resource="serve",
                                       model=(r.entry.key if r.entry
                                              else None), **tf):
                        out.append(self._respond(r, t0))
        for i, r in admits:
            out[i] = self._cmd_admit(r)
        return out

    def handle(self, request: dict) -> dict:
        return self.handle_batch([request])[0]

    @property
    def closing(self) -> bool:
        return self._closing

    def request_drain(self) -> None:
        """Graceful-shutdown request: the serving loop finishes its
        in-flight tick, answers it, and exits — the timeline/metrics
        stream flushes through the driver's normal teardown instead of
        being torn mid-record. Called from MAIN-LINE code only (the
        serving loops, after the SIGTERM handler sets its Event): the
        timeline write below takes the metrics-stream lock, which a
        signal handler must never do (graftlint JGL010)."""
        with self._lock:
            if not self._draining:
                self._draining = True
                timeline_event("sigterm_drain", cat="recovery",
                               resource="serve",
                               requests_served=self.requests_served)
            self._closing = True

    def health(self) -> dict:
        """Sliding-window health: error rate over the last
        `health_window` scoring outcomes, degraded past `degraded_at`,
        failing past `failing_at` (or while DRAINING — a terminating
        daemon must tell its load balancer to stop sending). Open
        breakers degrade an otherwise-clean window: some models are
        fast-failing even if the overall rate looks fine."""
        with self._lock:
            n = len(self._outcomes)
            errs = sum(1 for ok in self._outcomes if not ok)
            rate = errs / n if n else 0.0
            open_b = self.open_breakers()
            if self._closing or rate >= self.failing_at:
                status = "failing" if not self._closing else "draining"
            elif rate >= self.degraded_at or open_b:
                status = "degraded"
            else:
                status = "ok"
            return {
                "status": status,
                "ok": status in ("ok", "degraded"),
                "error_rate": round(rate, 4),
                "window": n,
                "open_breakers": open_b,
                "deadline_misses": self.deadline_misses,
                "breaker_fast_fails": self.breaker_fast_fails,
            }

    def breaker_states(self) -> dict:
        """key -> {"fails", "open"} for every entry the breaker has
        seen — the /metrics gauge source (open_breakers() lists only
        the currently-open subset)."""
        with self._lock:
            open_b = set(self.open_breakers())
            return {k: {"fails": b.get("fails", 0), "open": k in open_b}
                    for k, b in self._breakers.items()}

    def stats(self) -> dict:
        with self._lock:
            return {
                "run_meta": self.run_meta,
                "requests_served": self.requests_served,
                "dispatches": self.dispatches,
                "fused_requests": self.fused_requests,
                "ticks": self.ticks,
                "admits": self.admits,
                "promotions": self.promotions,
                "health": self.health(),
                "registry": self.registry.stats(),
                "drift": self.drift.stats(),
                # The serving panel's shape — the worker-pool manager
                # (serve/pool.py) reads n_max off a worker's /stats to
                # pre-export AOT artifacts at the width the fleet
                # actually serves.
                "panel": {
                    "n_days": int(len(self.dataset.dates)),
                    "n_max": int(self.dataset.n_max),
                },
            }


class TickScheduler:
    """Cross-tick continuous batching for the threaded HTTP front
    (ISSUE 15): concurrent client requests land in ONE queue; a single
    scheduler thread drains it into `handle_batch` ticks. Queue-depth
    aware — a backlog of `max_tick_batch` dispatches immediately (under
    load, the previous tick's dispatch wall IS the batching window:
    everything that queued while it ran fuses into the next tick),
    while a shallow queue holds the batch open up to `tick_ms` for late
    arrivals. That trades p50 at low load for fused-dispatch QPS at
    high load — the knob pair lives in the plan row's "serve" block
    (`Plan.serve_tick_ms`/`serve_max_tick_batch`, raced by
    `autotune_plan.py --serve`).

    Thread contract: `submit` is called from any number of HTTP handler
    threads and blocks until the scheduler thread answered every
    request of that submission; response order mirrors request order.
    The scheduler thread is the ONLY caller of `handle_batch`, so the
    daemon's single-tick invariant holds exactly as under the
    single-threaded front. `close()` drains the queue and joins the
    thread — pending submissions are answered (ok:false) rather than
    left blocked forever."""

    def __init__(self, daemon: ScoringDaemon, tick_ms: float = 2.0,
                 max_tick_batch: int = 64):
        self.daemon = daemon
        self.tick_s = max(0.0, float(tick_ms)) / 1e3
        self.max_tick_batch = max(1, int(max_tick_batch))
        # One explicit queue lock (graftlint JGL009) with the arrival
        # condition layered on it: submit() runs on HTTP handler
        # threads, the scheduler loop on its own — every queue/counter
        # mutation below holds _lock.
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # [request, result_list, slot_index, submission, queue_token]
        # pending items; the token is an open `serve_queue` span
        # (timeline_span_begin) the scheduler loop closes when the
        # request is pulled into a tick — the sanctioned cross-thread
        # begin/end pairing (graftlint JGL013).
        self._q: deque = deque()
        self._closing = False
        self.ticks = 0
        self.scheduled = 0
        self._qseq = 0             # queue-span id counter (under _lock)
        self.fused_ticks = 0       # ticks that carried > 1 request
        self.max_queue_depth = 0
        # Non-daemon thread, joined in close(): its handle_batch calls
        # write the timeline stream, and a torn mid-write exit is
        # exactly what JGL011 exists to prevent.
        self._thread = threading.Thread(
            target=self._loop, name="serve-tick-scheduler")
        self._thread.start()

    # ---- client side -----------------------------------------------------

    def submit(self, requests: list) -> list:
        """Enqueue one client submission; block until every request in
        it is answered; return the responses in request order. Parse
        errors answer in place without entering the queue (the
        `_with_parse_errors` contract)."""
        results: list = [None] * len(requests)
        pending = 0
        done = threading.Event()
        sub = {"left": 0, "done": done}
        with self._lock:
            if self._closing:
                return [{"id": None, "ok": False,
                         "error": "daemon is shutting down"}
                        for _ in requests]
            for i, r in enumerate(requests):
                if isinstance(r, dict) and "_parse_error" in r:
                    results[i] = {"id": None, "ok": False,
                                  "error": r["_parse_error"]}
                    continue
                # Trace plane: a traced request's queue wait is its own
                # span, opened here on the HTTP thread and closed by
                # the scheduler loop when the tick picks it up. The
                # request is re-parented under the queue span (a copy —
                # the caller's dict is not mutated) so the daemon's
                # tick/dispatch/response spans chain below it.
                qtok = None
                ctx = wire_ctx(r) if self.daemon.trace_enabled else None
                if ctx is not None:
                    self._qseq += 1
                    qspan = f"{ctx['span_id']}.q{self._qseq}"
                    r = dict(r)
                    r["trace"] = {"trace_id": ctx["trace_id"],
                                  "span_id": qspan}
                    qtok = timeline_span_begin(
                        "serve_queue", cat="serve", resource="scheduler",
                        trace=ctx["trace_id"], span=qspan,
                        parent=ctx["span_id"])
                self._q.append([r, results, i, sub, qtok])
                pending += 1
            sub["left"] = pending
            self.scheduled += pending
            self.max_queue_depth = max(self.max_queue_depth,
                                       len(self._q))
            if pending:
                self._cv.notify_all()
        # Timed wait in a liveness loop (JGL012): close() answers every
        # leftover, so the only way `done` never fires is the scheduler
        # thread dying mid-flight — in which case an untimed wait would
        # park this client forever. Check the thread each second and
        # answer the stranded slots with an explicit error instead.
        while pending and not done.wait(1.0):
            if self._thread.is_alive():
                continue
            with self._lock:
                for i in range(len(results)):
                    if results[i] is None:
                        results[i] = {
                            "id": None, "ok": False,
                            "error": "scheduler thread died before "
                                     "answering"}
            break
        return results

    # ---- scheduler thread ------------------------------------------------

    def _next_batch(self):
        """Block until work exists, then apply the depth-aware window:
        a full batch dispatches immediately; an under-full one waits up
        to `tick_s` for late arrivals. Returns None only at close."""
        with self._lock:
            while not self._q and not self._closing:
                self._cv.wait(0.25)
            if not self._q:
                return None
            if len(self._q) < self.max_tick_batch and self.tick_s > 0:
                deadline = time.monotonic() + self.tick_s
                while len(self._q) < self.max_tick_batch \
                        and not self._closing:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
            n = min(len(self._q), self.max_tick_batch)
            batch = [self._q.popleft() for _ in range(n)]
            self.ticks += 1
            if n > 1:
                self.fused_ticks += 1
            return batch

    def _answer(self, batch, responses) -> None:
        finished = []
        with self._lock:
            for (req, results, i, sub, _qtok), resp in zip(batch,
                                                           responses):
                results[i] = resp
                sub["left"] -= 1
                if sub["left"] == 0:
                    finished.append(sub["done"])
        for ev in finished:
            ev.set()

    def _loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            # Close the queue-wait spans submit() opened: the wait ends
            # the moment the tick claims the request (outside _lock —
            # span emission writes the metrics stream).
            for item in batch:
                timeline_span_end(item[4])
                item[4] = None
            try:
                responses = self.daemon.handle_batch(
                    [item[0] for item in batch])
            except Exception as e:
                # The never-kill-the-process contract, scheduler
                # edition: a tick that explodes answers ITS requests
                # and the loop lives on.
                responses = [{"id": None, "ok": False,
                              "error": f"tick failed: {e}"}
                             for _ in batch]
            self._answer(batch, responses)

    # ---- lifecycle -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "tick_ms": round(self.tick_s * 1e3, 3),
                "max_tick_batch": self.max_tick_batch,
                "ticks": self.ticks,
                "scheduled": self.scheduled,
                "fused_ticks": self.fused_ticks,
                "max_queue_depth": self.max_queue_depth,
                "queued": len(self._q),
            }

    def close(self) -> None:
        """Stop accepting work, let the scheduler finish the queue,
        join the thread. Idempotent."""
        with self._lock:
            self._closing = True
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout=60)
        # Anything still queued after the join answers instead of
        # leaving its submitter blocked on a dead scheduler.
        leftovers = []
        with self._lock:
            while self._q:
                leftovers.append(self._q.popleft())
        if leftovers:
            for item in leftovers:
                # Never leak a queue span: requests the shutdown
                # answered without a tick close as cancelled.
                timeline_span_end(item[4], outcome="cancelled")
                item[4] = None
            self._answer(leftovers,
                         [{"id": None, "ok": False,
                           "error": "daemon is shutting down"}
                          for _ in leftovers])


# ---------------------------------------------------------------------------
# Drivers
# ---------------------------------------------------------------------------


def _parse_line(line: str) -> list:
    """One JSONL line -> a list of request dicts (an array is an
    explicit batch). A parse failure yields one error-carrying dict the
    daemon turns into an {"ok": false} response."""
    try:
        obj = json.loads(line)
    except ValueError as e:
        return [{"_parse_error": f"bad JSON: {e}"}]
    return obj if isinstance(obj, list) else [obj]


def _with_parse_errors(daemon: ScoringDaemon, requests: list) -> list:
    ok, responses_at = [], {}
    for i, r in enumerate(requests):
        if isinstance(r, dict) and "_parse_error" in r:
            responses_at[i] = {"id": None, "ok": False,
                               "error": r["_parse_error"]}
        else:
            ok.append((i, r))
    answered = daemon.handle_batch([r for _, r in ok])
    for (i, _), resp in zip(ok, answered):
        responses_at[i] = resp
    return [responses_at[i] for i in range(len(requests))]


@contextlib.contextmanager
def _drain_on_sigterm(daemon: ScoringDaemon):
    """Install a SIGTERM handler in the SET-FLAG-AND-RETURN shape
    (graftlint JGL010) and yield the flag: the handler only sets a
    `threading.Event`; the serving loop polls it and performs the
    actual drain (`daemon.request_drain()` — a timeline write that
    takes the metrics-stream lock) in main-line code. CPython runs
    signal handlers between bytecodes of the interrupted frame, so a
    handler that logged directly could re-enter the very lock the
    interrupted `MetricsLogger.log` call holds and deadlock the
    process on its way down. Restores the previous handler on exit; a
    non-main thread (HTTP tests drive the server from a worker) cannot
    install handlers and serves with an Event nothing ever sets."""
    import signal

    term = threading.Event()

    def on_term(signum, frame):
        term.set()  # nothing else: no logging, no locks, no I/O

    try:
        prev = signal.signal(signal.SIGTERM, on_term)
    except ValueError:  # not the main thread — no handler, no drain
        yield term
        return
    try:
        yield term
    finally:
        signal.signal(signal.SIGTERM, prev)


def _stdin_ticks(inp, tick_s: float, max_batch: int, stop=None):
    """Yield lists of raw lines, one list per tick. On a selectable
    stream, lines arriving within `tick_s` of each other coalesce into
    one tick (up to `max_batch`); otherwise (StringIO tests) each line
    is its own tick. Reads the RAW fd exclusively — mixing readline
    with select would strand data in Python's buffer. `stop` (a
    callable) is polled on idle so a drain request ends the loop
    instead of blocking in select forever."""
    try:
        fd = inp.fileno()
    except (AttributeError, OSError, ValueError):
        for line in inp:
            if line.strip():
                yield [line]
        return
    import select

    buf = b""
    pending: list = []
    eof = False
    while True:
        while b"\n" in buf and len(pending) < max_batch:
            line, buf = buf.split(b"\n", 1)
            if line.strip():
                pending.append(line.decode(errors="replace"))
        if pending and len(pending) >= max_batch:
            yield pending
            pending = []
            continue
        if eof:
            if buf.strip():
                pending.append(buf.decode(errors="replace"))
                buf = b""
            if pending:
                yield pending
            return
        try:
            # Bounded idle wait when a stop callback exists: SIGTERM
            # interrupts nothing (PEP 475 retries select), so the drain
            # check needs a periodic wake-up.
            idle = 0.25 if stop is not None else None
            ready, _, _ = select.select(
                [fd], [], [], tick_s if pending else idle)
        except OSError:  # fd closed under us
            eof = True
            continue
        if not ready:
            if pending:
                yield pending
                pending = []
            elif stop is not None and stop():
                return
            continue
        data = os.read(fd, 65536)
        if not data:
            eof = True
        else:
            buf += data


def serve_stdin(daemon: ScoringDaemon, inp, out,
                tick_s: float = 0.02, max_batch: int = 64) -> int:
    """JSONL request/response loop until EOF, a shutdown cmd, or a
    SIGTERM drain (the in-flight tick is finished and answered first).
    Returns the number of requests answered."""
    answered = 0
    with _drain_on_sigterm(daemon) as term:

        def stop() -> bool:
            # Polled on idle by the tick loop: the SIGTERM flag is
            # promoted to a real drain HERE, in main-line code, where
            # taking the timeline lock is safe.
            if term.is_set():
                daemon.request_drain()
            return daemon.closing

        for lines in _stdin_ticks(inp, tick_s, max_batch, stop=stop):
            if term.is_set():
                daemon.request_drain()
            requests = [r for line in lines for r in _parse_line(line)]
            for resp in _with_parse_errors(daemon, requests):
                out.write(json.dumps(resp) + "\n")
                answered += 1
            out.flush()
            if daemon.closing:
                break
    return answered


def serve_batch_file(daemon: ScoringDaemon, path: str, out,
                     max_batch: int = 64) -> int:
    """Score a JSONL request file as maximally-fused ticks; write JSONL
    responses to `out`. Returns the number answered."""
    with open(path) as fh:
        lines = [ln for ln in fh if ln.strip()]
    requests = [r for line in lines for r in _parse_line(line)]
    answered = 0
    for i in range(0, len(requests), max_batch):
        for resp in _with_parse_errors(daemon,
                                       requests[i:i + max_batch]):
            out.write(json.dumps(resp) + "\n")
            answered += 1
    out.flush()
    return answered


def _serve_runstream(handler) -> None:
    """`GET /runstream?since=<byte offset>` — the fleet collector's
    transport (obs/collect.py), shared by the worker front here and the
    router: serve this process's RUN.jsonl tail from `since`, cut at
    the last newline (obs/live.py `tail_bytes` — a torn final line is
    never served), with the resume offset in `X-Runstream-Next`. A
    process with no metrics stream answers an empty payload rather than
    erroring: collection degrades, requests don't."""
    from urllib.parse import parse_qs, urlparse

    from factorvae_tpu.obs.live import tail_bytes
    from factorvae_tpu.utils.logging import current_timeline

    q = parse_qs(urlparse(handler.path).query)
    try:
        since = int(q.get("since", ["0"])[0])
    except ValueError:
        since = 0
    tl = current_timeline()
    jsonl = getattr(getattr(tl, "logger", None), "jsonl_path", None)
    payload, nxt = tail_bytes(jsonl, since) if jsonl else (b"", 0)
    handler.send_response(200)
    handler.send_header("Content-Type", "application/x-ndjson")
    handler.send_header("Content-Length", str(len(payload)))
    handler.send_header("X-Runstream-Next", str(nxt))
    handler.end_headers()
    handler.wfile.write(payload)


def serve_http(daemon: ScoringDaemon, port: int,
               host: str = "127.0.0.1",
               scheduler: Optional[TickScheduler] = None):
    """Minimal stdlib HTTP front: POST /score (object or array body),
    GET /stats, /models, /healthz, /metrics, POST /profile, POST
    /admit (walk-forward rollover: candidate admission + fidelity gate
    + zero-downtime alias flip — see ScoringDaemon.admit).
    Single-threaded by design — jax dispatch is the bottleneck and
    wants no concurrency. Blocks until a shutdown request arrives or
    SIGTERM requests a drain (the in-flight request finishes, then the
    loop exits so the timeline flushes).

    With a `scheduler` (TickScheduler — the worker-pool fleet mode and
    `--scheduler`), the front switches to ThreadingHTTPServer and
    routes /score through the cross-tick continuous-batching queue:
    concurrent clients' requests fuse into shared `handle_batch` ticks
    while the scheduler thread stays the only dispatcher (the daemon's
    single-tick invariant holds; every other endpoint reads under the
    existing tick/registry locks). Without one, behavior is unchanged
    from the single-threaded front — byte-identical responses.

    `/healthz` reports the sliding-window health (ScoringDaemon.health):
    200 while ok/degraded, 503 once failing or draining — the signal a
    load balancer keys eviction on. `/metrics` is Prometheus text
    exposition (obs/metrics.py: latency histogram, registry/breaker/
    health gauges, compile taxonomy, score-drift monitors). `/stats`
    and `/models` carry the daemon's `run_meta` provenance so scraped
    snapshots are ledger-attributable. `POST /profile`
    ({"action": "start"|"stop", "log_dir"?}) drives an on-demand
    `jax.profiler` capture (utils/profiling.py); "stop" answers with
    the `trace_summary` device-time breakdown."""
    from http.server import (
        BaseHTTPRequestHandler,
        HTTPServer,
        ThreadingHTTPServer,
    )

    class Handler(BaseHTTPRequestHandler):
        # Keep-alive on the THREADED front only (every response sends
        # Content-Length, so HTTP/1.1 is safe there; the router holds
        # persistent connections to cut per-forward TCP setup). The
        # single-threaded front stays HTTP/1.0: one keep-alive client
        # would monopolize its only accept loop.
        protocol_version = "HTTP/1.1" if scheduler is not None \
            else "HTTP/1.0"

        def _send(self, code: int, payload) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str,
                       content_type: str) -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path == "/healthz":
                health = daemon.health()
                # Clock-alignment echo (obs/collect.py): this process's
                # timeline clock, stamped as late as possible so the
                # prober's RTT midpoint estimate is tight. None without
                # a timeline — the prober just skips the sample.
                from factorvae_tpu.utils.logging import timeline_now

                health["mono"] = timeline_now()
                self._send(200 if health["ok"] else 503, health)
            elif self.path.startswith("/runstream"):
                _serve_runstream(self)
            elif self.path == "/stats":
                payload = daemon.stats()
                if scheduler is not None:
                    payload["scheduler"] = scheduler.stats()
                self._send(200, payload)
            elif self.path == "/models":
                self._send(200, {
                    "run_meta": daemon.run_meta,
                    "models": daemon.registry.stats()["entries"]})
            elif self.path == "/metrics":
                from factorvae_tpu.obs.metrics import (
                    CONTENT_TYPE,
                    daemon_metrics,
                )

                self._send_text(200, daemon_metrics(daemon),
                                CONTENT_TYPE)
            else:
                self._send(404, {"ok": False,
                                 "error": f"unknown path {self.path}"})

        def _profile(self, req: dict) -> None:
            from factorvae_tpu.utils.profiling import (
                ProfilerError,
                start_profile,
                stop_profile,
            )

            action = (req or {}).get("action")
            try:
                if action == "start":
                    log_dir = start_profile((req or {}).get("log_dir"))
                    self._send(200, {"ok": True, "action": "start",
                                     "log_dir": log_dir})
                elif action == "stop":
                    self._send(200, {"ok": True, "action": "stop",
                                     **stop_profile()})
                else:
                    self._send(400, {
                        "ok": False,
                        "error": "POST /profile wants {\"action\": "
                                 "\"start\"|\"stop\"} (optional "
                                 "\"log_dir\" on start)"})
            except ProfilerError as e:
                self._send(409, {"ok": False, "error": str(e)})

        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path not in ("/score", "/profile", "/admit"):
                self._send(404, {"ok": False,
                                 "error": f"unknown path {self.path}"})
                return
            n = int(self.headers.get("Content-Length") or 0)
            requests = _parse_line(self.rfile.read(n).decode())
            # Trace adoption (obs/trace.py): a fleet hop's context
            # arrives in the X-Factorvae-Trace header; requests that
            # don't already carry a `trace` field (the router injects
            # per-request contexts into the body too) inherit it — the
            # path remote-join workers join a trace through.
            if daemon.trace_enabled:
                hdr = parse_header(self.headers.get(TRACE_HEADER))
                if hdr is not None:
                    for r in requests:
                        if isinstance(r, dict) and "trace" not in r:
                            r["trace"] = hdr
            if self.path == "/profile":
                req = requests[0] if requests else {}
                self._profile(req if isinstance(req, dict) else {})
                return
            if self.path == "/admit":
                # Rollover control surface (ISSUE 14): gate scoring
                # runs outside the tick lock inside admit(); only the
                # alias flip serializes with ticks.
                req = requests[0] if requests else {}
                if not (isinstance(req, dict)
                        and isinstance(req.get("path"), str)):
                    self._send(400, {
                        "ok": False,
                        "error": "POST /admit wants {\"path\": "
                                 "\"<checkpoint dir>\", \"alias\": "
                                 "\"<serving alias>\"} (optional "
                                 "holdout_days, min_margin, "
                                 "drift_threshold, precision)"})
                    return
                try:
                    self._send(200, daemon.admit(
                        req["path"], req.get("alias", "prod"),
                        holdout_days=req.get("holdout_days"),
                        min_margin=float(req.get("min_margin", 0.0) or 0),
                        drift_threshold=req.get("drift_threshold"),
                        precision=req.get("precision"),
                        trace=req.get("trace")))
                except Exception as e:
                    # A failed admission never kills the daemon — the
                    # incumbent keeps serving and the caller gets the
                    # actionable message.
                    self._send(200, {"ok": False, "error": str(e)})
                return
            if scheduler is not None:
                # Fleet mode: the continuous-batching queue fuses this
                # client's requests with every other in-flight
                # client's; the scheduler thread is the one dispatcher.
                responses = scheduler.submit(requests)
            else:
                responses = _with_parse_errors(daemon, requests)
            # An empty array body gets an empty array back — never an
            # IndexError-dropped connection.
            self._send(200, responses if len(responses) != 1
                       else responses[0])

        def log_message(self, fmt, *args):  # quiet: stdout is sacred
            from factorvae_tpu.utils.logging import timeline_event

            timeline_event("http", cat="serve", resource="serve",
                           line=fmt % args)

    server_cls = HTTPServer if scheduler is None else ThreadingHTTPServer
    try:
        server = server_cls((host, port), Handler)
    except Exception:
        # A failed bind (port in use) must still join the scheduler's
        # non-daemon thread, or the process would survive its own
        # startup failure forever.
        if scheduler is not None:
            scheduler.close()
        raise
    # Bounded accept wait: handle_request returns after `timeout` with
    # no connection, so a SIGTERM drain ends the loop within one tick
    # instead of blocking in accept forever.
    server.timeout = 0.25
    with _drain_on_sigterm(daemon) as term:
        try:
            while not daemon.closing:
                if term.is_set():
                    # main-line promotion of the handler's flag: the
                    # in-flight request already finished (we are
                    # between handle_request calls), so drain and exit
                    daemon.request_drain()
                    break
                server.handle_request()
        finally:
            if scheduler is not None:
                # Drain the batching queue and join the scheduler
                # thread BEFORE the metrics stream tears down: pending
                # submissions answer, nothing exits mid-write.
                scheduler.close()
            server.server_close()
    return server
