"""Worker-fleet manager: N scoring daemons behind one control plane.

ROADMAP item 3 / ISSUE 15 — the horizontal half of the serving story.
One `ScoringDaemon` process tops out at one dispatch path; this module
turns it into a FLEET: the pool spawns N full PR-8 daemons (each with
its own warm registry, breaker table and `/metrics`), keeps them
healthy, and gives the router (serve/router.py) a live worker table to
route over.

**Zero-compile worker cold start.** Every worker shares ONE persistent
XLA compilation cache directory (`plan.setup_compilation_cache`): the
first worker builds each scoring program once, and worker N+1
deserializes — its `/metrics` scrapes `compile == 0,
compile_cached > 0` (the PR-10 warm-restart contract, extended from
restarts to fleet joins; pinned in tests/test_pool.py). On top, the
pool PRE-EXPORTS every admitted checkpoint into a disk **AOT artifact
store** (`AotStore`: `eval/export_aot.py` container v1, one artifact
per serving alias, atomic tmp+rename, digest-keyed freshness): a
respawned worker admits the artifacts instead of re-loading
checkpoints, a cold start that involves no flax, no orbax and no trace
at all.

**Lifecycle.** `start()` brings worker 0 up first (it warms the shared
cache), pre-exports the AOT store at the fleet's measured panel width
(read off worker 0's `/stats`), then raises the rest of the fleet
warm. A watcher thread polls each worker: process death -> respawn
from the AOT store (same port — the router's worker table stays
stable) and replay of any fan-out admits; `/healthz` scrape ->
ok/degraded/failing state the router's candidate selection keys on.
`request_drain()`/`stop()` fan SIGTERM out so every worker performs
its own graceful drain (the daemon's documented SIGTERM shape), then
reap. The chaos class `kill_worker` (request = worker index) SIGKILLs
a worker from the watcher tick — `bench.py --chaos` times the
router-reroute + respawn MTTR.

**Rolling admit fan-out.** `admit_fanout(payload)` first refreshes the
AOT store from the candidate checkpoint, then POSTs `/admit` to each
worker IN SEQUENCE — a walk-forward promotion reaches every worker
holding the alias, one zero-downtime alias flip at a time, and
respawned workers replay the same admissions so a crash never
resurrects yesterday's incumbent (docs/walkforward.md).

**Multi-host (ISSUE 17).** The worker table is no longer only local
subprocesses: a REMOTE worker registers over HTTP (the router's
`POST /register` with its host, port and capability digest —
`adopt_remote`), and the `AotStore` doubles as a CONTENT-ADDRESSED
artifact service (`manifest()` / `capability_digest()` /
`blob_path(sha256)`, served by the router as `GET /artifacts` +
`GET /artifact/<sha256>`) so a cold host joins with zero traces —
only digest-verified artifact downloads (serve/remote.py) into the
same warm path respawns use. `launch_remote` spawns a joining agent
on localhost (the simulated-host mode bench/chaos drive);
externally-started agents register the same way and are adopted
without a process handle. `scale_up`/`scale_down` give the
SLO-driven autoscaler (serve/autoscale.py) its actuators, and
`rolling_upgrade` drains+respawns the fleet one worker at a time
(new code, same artifacts — the PR-13 rollover discipline applied to
processes). The chaos class `kill_remote_worker` (request = worker
index) SIGKILLs a pool-launched agent from the watcher tick; recovery
is the router's reroute plus the agent's full cold re-join.

Locking: `self._lock` guards the worker table, counters and the admit
log. Network scrapes, subprocess spawns and AOT exports all run
OUTSIDE it — a slow worker must not stall the router's
`healthy_ids()` read. The watcher thread writes no files (spawn log
handles are opened in `_spawn`, which `start()` also calls
synchronously) and is joined on every stop path.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Sequence

from factorvae_tpu.chaos import fault as chaos_fault
from factorvae_tpu.utils.logging import timeline_event, timeline_now


class PoolError(RuntimeError):
    """Pool-level failure with a one-line actionable message."""


def http_json(url: str, payload: Optional[dict] = None,
              timeout: float = 30.0):
    """One JSON request/response round trip (POST when `payload` is
    given, GET otherwise). HTTP error bodies that carry JSON (the
    daemon's 503 health answer, the router's shed response) parse and
    return instead of raising — only transport-level failures raise."""
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(url, data=data, method=(
        "POST" if data is not None else "GET"))
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode() or "null")
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        try:
            return json.loads(body)
        except ValueError:
            raise PoolError(
                f"{url} answered HTTP {e.code}: {body[:200]}") from None


def http_text(url: str, timeout: float = 30.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def http_bytes(url: str, timeout: float = 600.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def file_sha256(path: str) -> str:
    """Streamed sha256 of a file — the content address an artifact is
    served and verified under (GET /artifact/<sha256>)."""
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


# ---------------------------------------------------------------------------
# AOT artifact store
# ---------------------------------------------------------------------------


class AotStore:
    """Disk store of serving artifacts, one per alias: `<root>/<alias>`
    is a v1 AOT container (eval/export_aot.py) whose basename doubles
    as the registry alias a worker admits it under — exactly the alias
    the equivalent checkpoint admission would have produced, so
    requests route identically to a checkpoint-backed and an
    artifact-backed fleet. A `<alias>.meta.json` sidecar records the
    exported weights' digest so an unchanged checkpoint re-exports
    nothing (the export's one trace per call is the cost being
    skipped).

    The store is also CONTENT-ADDRESSED (ISSUE 17): the sidecar
    records the artifact file's sha256, `manifest()` lists every
    alias with its content address, `capability_digest()` collapses
    the manifest into one fleet-identity digest (what a registering
    remote worker must present), and `blob_path(sha256)` resolves a
    content address back to bytes — the router serves exactly these
    as `GET /artifacts` + `GET /artifact/<sha256>`."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        # (alias, mtime, size) -> sha256: recomputing a content
        # address per scrape would re-read every artifact.
        self._sha_cache: dict = {}
        self._sha_lock = threading.Lock()

    def path_for(self, alias: str) -> str:
        return os.path.join(self.root, alias)

    def has(self, alias: str) -> bool:
        return os.path.isfile(self.path_for(alias))

    def aliases(self) -> List[str]:
        return sorted(n for n in os.listdir(self.root)
                      if not n.endswith(".meta.json")
                      and os.path.isfile(os.path.join(self.root, n)))

    def export_checkpoint(self, path: str, n_max: int,
                          alias: Optional[str] = None) -> str:
        """Export one weights-only checkpoint directory as an f32
        serving artifact at cross-section width `n_max`; returns the
        artifact path. Freshness is judged by the params digest — the
        same identity the registry's re-admission version-bump uses —
        so the rollover path re-exports exactly when the bytes
        changed. The write is atomic (tmp + rename): a killed export
        never leaves a torn artifact a respawn could admit."""
        from factorvae_tpu.eval.export_aot import export_prediction
        from factorvae_tpu.models.factorvae import load_model
        from factorvae_tpu.serve.registry import (
            _params_digest,
            checkpoint_config,
        )

        path = os.path.abspath(path)
        alias = alias or os.path.basename(path)
        config = checkpoint_config(path)
        _, params = load_model(config, checkpoint_path=path, n_max=1)
        digest = _params_digest(params)
        meta_path = self.path_for(alias) + ".meta.json"
        out = self.path_for(alias)
        try:
            with open(meta_path) as fh:
                prior = json.load(fh)
        except (OSError, ValueError):
            prior = {}
        if (prior.get("digest") == digest
                and prior.get("n_max") == int(n_max)
                and os.path.isfile(out)):
            return out
        blob = export_prediction(params, config, n_max=int(n_max),
                                 stochastic=False)
        import hashlib

        tmp = out + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, out)
        tmp_meta = meta_path + ".tmp"
        with open(tmp_meta, "w") as fh:
            json.dump({"digest": digest, "n_max": int(n_max),
                       "source": path,
                       "sha256": hashlib.sha256(blob).hexdigest()}, fh)
        os.replace(tmp_meta, meta_path)
        timeline_event("aot_export", cat="serve", resource="pool",
                       alias=alias, n_max=int(n_max), bytes=len(blob))
        return out

    def adopt_artifact(self, path: str,
                       alias: Optional[str] = None) -> str:
        """Copy an existing artifact FILE into the store under its
        alias (the `--model m.aot` admission path needs no export)."""
        import shutil

        path = os.path.abspath(path)
        alias = alias or os.path.basename(path)
        out = self.path_for(alias)
        if os.path.abspath(out) != path:
            tmp = out + ".tmp"
            shutil.copyfile(path, tmp)
            os.replace(tmp, out)
        return out

    # ---- content addressing (ISSUE 17) -----------------------------------

    def sha256_for(self, alias: str) -> str:
        """The alias' content address, cached by (mtime, size) and
        persisted into the meta sidecar so a restarted control plane
        never re-hashes an unchanged artifact."""
        path = self.path_for(alias)
        st = os.stat(path)
        key = (alias, st.st_mtime_ns, st.st_size)
        with self._sha_lock:
            sha = self._sha_cache.get(key)
        if sha:
            return sha
        meta_path = path + ".meta.json"
        meta: dict = {}
        try:
            with open(meta_path) as fh:
                meta = json.load(fh)
        except (OSError, ValueError):
            meta = {}
        sha = meta.get("sha256")
        meta_fresh = False
        try:
            meta_fresh = (os.stat(meta_path).st_mtime_ns
                          >= st.st_mtime_ns)
        except OSError:
            pass
        if not (sha and meta_fresh):
            sha = file_sha256(path)
            tmp = meta_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({**meta, "sha256": sha}, fh)
            os.replace(tmp, meta_path)
        with self._sha_lock:
            self._sha_cache[key] = sha
        return sha

    def manifest(self) -> List[dict]:
        """Every alias with its content address (+ size and the
        exported n_max when the sidecar knows it) — the body of the
        router's `GET /artifacts`."""
        out = []
        for alias in self.aliases():
            path = self.path_for(alias)
            try:
                meta = {}
                try:
                    with open(path + ".meta.json") as fh:
                        meta = json.load(fh)
                except (OSError, ValueError):
                    meta = {}
                out.append({"alias": alias,
                            "sha256": self.sha256_for(alias),
                            "bytes": os.path.getsize(path),
                            "n_max": meta.get("n_max")})
            except OSError:
                continue   # torn mid-replace: next scrape sees it
        return out

    def capability_digest(self) -> str:
        """One digest over the sorted (alias, sha256) pairs — the
        fleet's artifact-set identity. A registering remote worker
        presents the digest of what IT serves; a mismatch means it
        materialized a different artifact set and must re-sync, not
        join."""
        import hashlib

        lines = sorted(f"{m['alias']} {m['sha256']}"
                       for m in self.manifest())
        return hashlib.sha256("\n".join(lines).encode()).hexdigest()

    def blob_path(self, sha256: str) -> Optional[str]:
        """Resolve a content address to an artifact path (None when no
        alias hashes to it) — the router's `GET /artifact/<sha256>`."""
        for alias in self.aliases():
            try:
                if self.sha256_for(alias) == sha256:
                    return self.path_for(alias)
            except OSError:
                continue
        return None


# ---------------------------------------------------------------------------
# worker handle + pool
# ---------------------------------------------------------------------------


class Worker:
    """One worker slot. Field mutation happens under the pool's lock;
    the subprocess handle itself is only driven by the pool
    (spawn/terminate/kill/poll).

    `kind` is "local" (a daemon subprocess the pool spawned) or
    "remote" (a worker that REGISTERED over HTTP — ISSUE 17). A
    remote slot routes by `host:port` like any other; its `proc` is
    the joining AGENT process when the pool launched it
    (`launch_remote` — killable, respawnable, the simulated-host
    mode) and None when the host joined on its own (health scrapes
    are then the only liveness signal, and death deregisters instead
    of respawning)."""

    def __init__(self, index: int, port: int, log_path: str,
                 host: str = "127.0.0.1", kind: str = "local"):
        self.index = index
        self.kind = kind          # "local" | "remote"
        self.wid = (f"w{index}" if kind == "local" else f"r{index}")
        self.host = host
        self.port = port
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.cmd: Optional[list] = None  # remote agent respawn cmd
        self.capability: Optional[str] = None  # registered digest
        self.state = "starting"   # starting|ok|degraded|failing|dead
                                  # (+ draining|upgrading: hands-off)
        self.restarts = 0
        self.fails = 0            # consecutive scrape failures
        self.last_health: Optional[dict] = None
        self.admits_replayed = 0
        self.respawn_source = None  # "aot_store" | "specs" |
                                    # "artifact_service" on respawn

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def describe(self) -> dict:
        return {
            "worker_id": self.wid, "kind": self.kind,
            "host": self.host, "port": self.port, "url": self.url,
            "state": self.state,
            "pid": self.proc.pid if self.proc else None,
            "restarts": self.restarts,
            "respawn_source": self.respawn_source,
            "capability": self.capability,
            "healthz": f"{self.url}/healthz",
            "metrics": f"{self.url}/metrics",
            "stats": f"{self.url}/stats",
            "health": self.last_health,
            "log": self.log_path,
        }


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class WorkerPool:
    """Spawn/heal/drain N `python -m factorvae_tpu.serve` workers.

    `model_specs` are the daemon's `--model` arguments (checkpoint
    dirs or artifact files); `dataset_args` the panel arguments
    (`["--dataset", p]` or `["--synthetic", "D,S"]`); `extra_args`
    pass through verbatim (precision, deadlines, ...). `cache_dir` is
    the SHARED persistent XLA compile cache every worker points at —
    the zero-compile cold-start transport; `store_dir` the AOT
    artifact store respawns admit from. `tick_ms`/`max_tick_batch`
    configure each worker's continuous-batching scheduler (None =
    leave the worker's own plan-governed resolution alone)."""

    #: consecutive health-scrape failures before a live process is
    #: treated as failing (routing stops; the process may still be
    #: compiling its warmup — only death triggers a respawn)
    SCRAPE_FAILS_FAILING = 3

    def __init__(self, model_specs: Sequence[str],
                 dataset_args: Sequence[str],
                 n_workers: int,
                 cache_dir: str,
                 store_dir: str,
                 work_dir: Optional[str] = None,
                 warmup: bool = True,
                 extra_args: Sequence[str] = (),
                 tick_ms: Optional[float] = None,
                 max_tick_batch: Optional[int] = None,
                 metrics_base: Optional[str] = None,
                 health_interval_s: float = 0.5,
                 respawn: bool = True,
                 start_timeout_s: float = 600.0,
                 single_thread_xla: bool = True,
                 env: Optional[dict] = None):
        if n_workers < 1:
            raise PoolError("a pool needs at least 1 worker")
        self.model_specs = [os.path.abspath(m) for m in model_specs]
        self.dataset_args = list(dataset_args)
        self.cache_dir = os.path.abspath(cache_dir)
        self.store = AotStore(store_dir)
        import tempfile

        self.work_dir = os.path.abspath(
            work_dir or tempfile.mkdtemp(prefix="serve_pool_"))
        os.makedirs(self.work_dir, exist_ok=True)
        self.warmup = bool(warmup)
        self.extra_args = list(extra_args)
        self.tick_ms = tick_ms
        self.max_tick_batch = max_tick_batch
        # Per-worker RUN streams ON by default (under work_dir): the
        # compile-record taxonomy a worker's /metrics exposes only
        # counts LOGGED records (obs/watchdog.py), and the fleet
        # cold-start contract — worker N+1 scrapes compile==0,
        # compile_cached>0 — is pinned off exactly that scrape.
        self.metrics_base = metrics_base or os.path.join(
            self.work_dir, "RUN.jsonl")
        self.health_interval_s = float(health_interval_s)
        self.respawn = bool(respawn)
        self.start_timeout_s = float(start_timeout_s)
        worker_env = dict(os.environ if env is None else env)
        # Workers spawn with cwd=work_dir: make THIS checkout's
        # package importable regardless of where the pool was started.
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        worker_env["PYTHONPATH"] = repo + os.pathsep + \
            worker_env.get("PYTHONPATH", "")
        if single_thread_xla:
            # One worker per core is the fleet's scaling model on CPU
            # hosts: each worker's XLA runs single-threaded so N
            # workers divide the machine instead of thrashing each
            # other's intra-op thread pools (measured on this rig: the
            # multi-threaded eigen pool LOSES on serving-sized ops
            # even at N=1). CPU-backend flags only — a TPU worker
            # ignores them.
            flags = worker_env.get("XLA_FLAGS", "")
            if "xla_cpu_multi_thread_eigen" not in flags:
                worker_env["XLA_FLAGS"] = (
                    flags + " --xla_cpu_multi_thread_eigen=false "
                    "intra_op_parallelism_threads=1").strip()
        # Built locally, assigned once, read-only afterwards (the
        # watcher thread's respawn path reads it).
        self.env = worker_env
        self._lock = threading.Lock()
        self.workers: List[Worker] = [
            Worker(i, free_port(),
                   os.path.join(self.work_dir, f"w{i}.log"))
            for i in range(int(n_workers))]
        self.n_max: Optional[int] = None
        self.respawns = 0
        self.kills = 0            # chaos kill_worker firings
        self.remote_kills = 0     # chaos kill_remote_worker firings
        self.remote_adopts = 0    # /register adoptions (ISSUE 17)
        self.upgrades = 0         # rolling-upgrade worker cycles
        self._next_index = int(n_workers)
        # The URL remote agents should (re)join through; set by the
        # CLI/bench once the router is listening. launch_remote needs
        # it explicitly otherwise.
        self.router_url: Optional[str] = None
        self._admit_log: List[dict] = []
        self._draining = False
        self._watcher: Optional[threading.Thread] = None

    # ---- spawning --------------------------------------------------------

    def _worker_cmd(self, w: Worker, models: Sequence[str]) -> list:
        cmd = [sys.executable, "-m", "factorvae_tpu.serve"]
        for m in models:
            cmd += ["--model", m]
        cmd += list(self.dataset_args)
        cmd += ["--http", str(w.port), "--compile_cache", self.cache_dir,
                "--scheduler"]
        if self.warmup:
            cmd += ["--warmup"]
        if self.tick_ms is not None:
            cmd += ["--tick_ms", str(float(self.tick_ms))]
        if self.max_tick_batch is not None:
            cmd += ["--max_batch", str(int(self.max_tick_batch))]
        if self.metrics_base:
            base, ext = os.path.splitext(self.metrics_base)
            cmd += ["--metrics_jsonl", f"{base}_{w.wid}{ext or '.jsonl'}"]
        cmd += self.extra_args
        return cmd

    def _respawn_models(self) -> tuple:
        """(models, source): the AOT store's artifacts when it covers
        every alias (the zero-trace cold start), else the original
        specs (the store may not exist yet on a very early death)."""
        aliases = [os.path.basename(m) for m in self.model_specs]
        if all(self.store.has(a) for a in aliases):
            return [self.store.path_for(a) for a in aliases], "aot_store"
        return list(self.model_specs), "specs"

    def _spawn(self, w: Worker, models: Sequence[str]) -> None:
        """Start (or restart) one LOCAL worker process; the handle and
        state land under the lock, the spawn itself runs outside it."""
        self._spawn_cmd(w, self._worker_cmd(w, models))

    def _spawn_cmd(self, w: Worker, cmd: Sequence[str]) -> None:
        cmd = list(cmd)
        log = open(w.log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                    env=self.env, cwd=self.work_dir)
        finally:
            log.close()   # the child holds its own descriptor
        with self._lock:
            w.proc = proc
            w.state = "starting"
            w.fails = 0
            w.admits_replayed = 0

    def _wait_healthy(self, workers: Sequence[Worker],
                      timeout_s: Optional[float] = None) -> None:
        deadline = time.monotonic() + (timeout_s
                                       or self.start_timeout_s)
        remaining = list(workers)
        while remaining and time.monotonic() < deadline:
            still = []
            for w in remaining:
                if w.proc is not None and w.proc.poll() is not None:
                    tail = self.worker_log_tail(w)
                    raise PoolError(
                        f"worker {w.wid} died during startup "
                        f"(rc={w.proc.returncode}); log tail:\n{tail}")
                try:
                    health = http_json(w.url + "/healthz", timeout=2.0)
                except (OSError, ValueError, PoolError):
                    # not listening yet (startup compiles): keep polling
                    still.append(w)
                    continue
                with self._lock:
                    w.last_health = health
                    w.state = "ok" if health.get("ok") else "failing"
            remaining = still
            if remaining:
                time.sleep(0.2)
        if remaining:
            raise PoolError(
                f"worker(s) {', '.join(w.wid for w in remaining)} "
                f"never answered /healthz within "
                f"{timeout_s or self.start_timeout_s:.0f}s "
                f"(logs under {self.work_dir})")

    def worker_log_tail(self, w: Worker, n: int = 2000) -> str:
        try:
            with open(w.log_path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                fh.seek(max(0, fh.tell() - n))
                return fh.read().decode(errors="replace")
        except OSError:
            return "<no log>"

    def start(self) -> None:
        """Bring the fleet up: worker 0 first (it pays the compiles
        into the shared cache), then the AOT pre-export at the
        measured panel width, then the rest of the fleet — warm by
        construction."""
        with self._lock:   # snapshot: scale_up appends from threads
            ws = list(self.workers)
        self._spawn(ws[0], self.model_specs)
        self._wait_healthy(ws[:1])
        stats = http_json(ws[0].url + "/stats", timeout=30.0)
        self.n_max = int((stats.get("panel") or {}).get("n_max") or 0)
        self.pre_export()
        for w in ws[1:]:
            self._spawn(w, self.model_specs)
        if len(ws) > 1:
            self._wait_healthy(ws[1:])
        self._watcher = threading.Thread(
            target=self._watch, name="pool-watcher", daemon=True)
        self._watcher.start()

    def pre_export(self) -> List[str]:
        """Populate the AOT store from the admitted model specs (one
        artifact per alias; checkpoint dirs export, artifact files
        copy in). Failures are logged, not fatal — the store is a
        respawn accelerator, the original specs remain the fallback."""
        done = []
        for spec in self.model_specs:
            try:
                if os.path.isdir(spec):
                    if not self.n_max:
                        raise PoolError(
                            "panel width unknown; start() reads it "
                            "off worker 0's /stats before exporting")
                    done.append(self.store.export_checkpoint(
                        spec, self.n_max))
                else:
                    done.append(self.store.adopt_artifact(spec))
            except Exception as e:
                timeline_event("aot_export_failed", cat="serve",
                               resource="pool", spec=spec,
                               error=str(e))
        return done

    # ---- health / routing view -------------------------------------------

    def healthy_ids(self) -> List[str]:
        with self._lock:
            return [w.wid for w in self.workers
                    if w.state in ("ok", "degraded")]

    def worker(self, wid: str) -> Worker:
        with self._lock:
            for w in self.workers:
                if w.wid == wid:
                    return w
        raise PoolError(f"unknown worker {wid!r}")

    def note_failure(self, wid: str) -> None:
        """Router-observed forward failure: stop routing to the worker
        until the watcher's next scrape clears it (or its death is
        confirmed and the respawn path takes over)."""
        with self._lock:
            for w in self.workers:
                if w.wid == wid:
                    w.fails += 1
                    if w.fails >= 1 and w.state in ("ok", "degraded"):
                        w.state = "failing"
                    return

    def stats(self) -> dict:
        with self._lock:
            return {
                "workers": [w.describe() for w in self.workers],
                "healthy": sum(1 for w in self.workers
                               if w.state in ("ok", "degraded")),
                "remote": sum(1 for w in self.workers
                              if w.kind == "remote"),
                "respawns": self.respawns,
                "kills": self.kills,
                "remote_kills": self.remote_kills,
                "remote_adopts": self.remote_adopts,
                "upgrades": self.upgrades,
                "admits_fanned_out": len(self._admit_log),
                "aot_store": self.store.root,
                "compile_cache": self.cache_dir,
                "n_max": self.n_max,
                "draining": self._draining,
            }

    # ---- multi-host: registration / scaling / upgrade (ISSUE 17) ---------

    def adopt_remote(self, host: str, port: int,
                     capability: Optional[str] = None) -> Worker:
        """Adopt a worker that registered over HTTP (`POST /register`).
        The capability digest it presents must match the store's —
        a worker serving a different artifact set would answer
        requests with the wrong model bytes, the one failure mode
        routing can never detect. Registration is idempotent by
        (host, port): a respawned agent re-registering on the same
        address HEALS its slot instead of growing the table."""
        expect = self.store.capability_digest()
        if capability is not None and expect \
                and capability != expect:
            raise PoolError(
                f"remote worker {host}:{port} presented capability "
                f"digest {capability[:12]}… but the fleet serves "
                f"{expect[:12]}… — it materialized a different "
                f"artifact set; re-sync from GET /artifacts and "
                f"register again")
        with self._lock:
            w = next((x for x in self.workers
                      if x.host == host and x.port == int(port)), None)
            if w is not None:
                rejoin = w.state == "dead"
                if rejoin:
                    w.restarts += 1
                w.capability = capability
                w.fails = 0
                w.state = "starting"
                # A joining agent materialized the CURRENT store —
                # admit_fanout refreshes the store before any fan-out,
                # so the downloads already carry every promotion.
                w.admits_replayed = len(self._admit_log)
            else:
                rejoin = False
                idx = self._next_index
                self._next_index += 1
                w = Worker(idx, int(port),
                           os.path.join(self.work_dir, f"r{idx}.log"),
                           host=host, kind="remote")
                w.capability = capability
                w.admits_replayed = len(self._admit_log)
                self.workers.append(w)
                self.remote_adopts += 1
        # Registration arrives from an agent that is already serving:
        # one immediate scrape makes it routable now instead of one
        # watcher interval later — and doubles as the remote join's
        # FIRST clock probe, so its stream is alignable (obs/collect)
        # as soon as it is routable.
        try:
            probe_t0 = timeline_now()
            health = http_json(w.url + "/healthz", timeout=2.0)
            probe_t1 = timeline_now()
        except (OSError, ValueError, PoolError):
            health = None
        with self._lock:
            if health is not None:
                w.last_health = health
                w.state = "ok" if health.get("ok") else "failing"
        if health is not None:
            self._log_clock_probe(w, health, probe_t0, probe_t1)
        timeline_event("remote_adopt", cat="serve", resource="pool",
                       worker=w.wid, host=host, port=int(port),
                       rejoin=rejoin, state=w.state)
        return w

    def deregister(self, wid: str) -> dict:
        """Graceful leave (`POST /deregister`): the slot drops out of
        routing and off the table; a pool-launched agent process is
        terminated (its drain finishes in-flight work)."""
        w = self.worker(wid)
        with self._lock:
            w.state = "draining"
        proc = w.proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30)
        with self._lock:
            w.state = "dead"
            if w in self.workers:
                self.workers.remove(w)
        timeline_event("remote_deregister", cat="serve",
                       resource="pool", worker=wid)
        return {"ok": True, "worker": wid}

    def launch_remote(self, router_url: Optional[str] = None,
                      port: Optional[int] = None,
                      extra_args: Sequence[str] = (),
                      wait_healthy: bool = False) -> Worker:
        """Spawn a JOINING agent on localhost (the simulated-host mode
        bench/chaos/autoscale drive): `python -m factorvae_tpu.serve
        --join <router>` downloads the artifact set from the content-
        addressed store, verifies every digest, serves it, and
        registers itself back — the identical protocol a real remote
        host speaks. The slot is created up front so the watcher owns
        the agent process (kill -> respawn -> cold re-join)."""
        router_url = router_url or self.router_url
        if not router_url:
            raise PoolError(
                "launch_remote needs the router's URL (set "
                "pool.router_url once the router listens, or pass "
                "router_url=)")
        with self._lock:
            idx = self._next_index
            self._next_index += 1
        w = Worker(idx, int(port or free_port()),
                   os.path.join(self.work_dir, f"r{idx}.log"),
                   kind="remote")
        cmd = [sys.executable, "-m", "factorvae_tpu.serve",
               "--join", router_url, "--http", str(w.port),
               "--compile_cache", self.cache_dir,
               "--aot_store",
               os.path.join(self.work_dir, f"r{idx}_store"),
               "--scheduler"]
        if self.warmup:
            cmd += ["--warmup"]
        if self.metrics_base:
            base, ext = os.path.splitext(self.metrics_base)
            cmd += ["--metrics_jsonl",
                    f"{base}_{w.wid}{ext or '.jsonl'}"]
        cmd += list(extra_args)
        w.cmd = cmd
        with self._lock:
            self.workers.append(w)
        self._spawn_cmd(w, cmd)
        timeline_event("remote_launch", cat="serve", resource="pool",
                       worker=w.wid, port=w.port)
        if wait_healthy:
            self._wait_healthy([w])
        return w

    def artifact_manifest(self) -> dict:
        """Everything a cold host needs to join (`GET /artifacts`):
        the content-addressed artifact list, the fleet's capability
        digest, and the panel/worker arguments the agents mirror."""
        return {"ok": True,
                "artifacts": self.store.manifest(),
                "capability_digest": self.store.capability_digest(),
                "dataset_args": list(self.dataset_args),
                "extra_args": list(self.extra_args),
                "n_max": self.n_max}

    def scale_up(self, timeout_s: Optional[float] = None
                 ) -> Optional[Worker]:
        """Autoscaler actuator: one more worker. A remote fleet
        (router_url set) grows by launching a joining agent; a local
        fleet by spawning a daemon warm off the AOT store + shared
        cache. Blocks until the newcomer answers /healthz — the
        control loop's natural cooldown."""
        with self._lock:
            if self._draining:
                return None
        if self.router_url:
            w = self.launch_remote(wait_healthy=False)
        else:
            with self._lock:
                idx = self._next_index
                self._next_index += 1
            w = Worker(idx, free_port(),
                       os.path.join(self.work_dir, f"w{idx}.log"))
            with self._lock:
                self.workers.append(w)
            models, source = self._respawn_models()
            self._spawn(w, models)
            with self._lock:
                w.respawn_source = source
        self._wait_healthy([w], timeout_s or self.start_timeout_s)
        with self._lock:
            n = len(self.workers)
        timeline_event("scale_up", cat="serve", resource="pool",
                       worker=w.wid, kind=w.kind, workers=n)
        return w

    def scale_down(self, wid: Optional[str] = None
                   ) -> Optional[Worker]:
        """Autoscaler actuator: retire one worker (newest first;
        worker 0 never — it anchors n_max and the warm cache).
        Retiring is drain-shaped: the slot leaves routing, the
        process SIGTERMs (its daemon finishes in-flight work), the
        row leaves the table."""
        with self._lock:
            # Only workers whose PROCESS this pool owns are
            # candidates: "retiring" an externally joined agent
            # (proc None — its host owns it) frees no resources, it
            # just orphans live serving capacity out of the routing
            # table. External capacity leaves via deregister.
            cands = [w for w in self.workers
                     if w.index != 0 and w.proc is not None
                     and w.state not in
                     ("dead", "draining", "upgrading")]
            if not cands:
                return None
            w = (next((x for x in cands if x.wid == wid), None)
                 if wid else cands[-1])
            if w is None:
                return None
            w.state = "draining"
        proc = w.proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30)
        with self._lock:
            w.state = "dead"
            if w in self.workers:
                self.workers.remove(w)
            n = len(self.workers)
        timeline_event("scale_down", cat="serve", resource="pool",
                       worker=w.wid, workers=n)
        return w

    def rolling_upgrade(self,
                        timeout_s: Optional[float] = None) -> dict:
        """Drain/join choreography (new code, same artifacts): one
        worker at a time leaves routing ("upgrading" — the watcher
        keeps hands off), SIGTERMs (the daemon's graceful drain
        finishes in-flight ticks, so nothing drops), respawns from
        the SAME artifacts under whatever code is now on disk, and
        must answer /healthz before the next worker starts — the
        PR-13 rollover discipline applied to processes. Externally
        joined remotes are skipped with an actionable note (their
        host owns their process)."""
        with self._lock:
            snapshot = [w for w in self.workers if w.state != "dead"]
        results = []
        for w in snapshot:
            if w.proc is None:
                results.append({
                    "worker": w.wid, "ok": False,
                    "error": "externally joined remote worker; "
                             "upgrade its agent from its own host"})
                continue
            t0 = time.monotonic()
            with self._lock:
                w.state = "upgrading"
            proc = w.proc
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=30)
            if w.kind == "remote":
                self._spawn_cmd(w, w.cmd)
                source = "artifact_service"
            else:
                models, source = self._respawn_models()
                self._spawn(w, models)
            try:
                self._wait_healthy(
                    [w], timeout_s or self.start_timeout_s)
            except PoolError as e:
                results.append({"worker": w.wid, "ok": False,
                                "error": str(e)})
                # stop the roll: a fleet that cannot raise the new
                # code must keep the rest of its capacity serving
                break
            with self._lock:
                w.restarts += 1
                w.respawn_source = source
                self.upgrades += 1
            wall = time.monotonic() - t0
            results.append({"worker": w.wid, "ok": True,
                            "wall_s": round(wall, 3)})
            timeline_event("worker_upgraded", cat="serve",
                           resource="pool", worker=w.wid,
                           wall_s=round(wall, 3), source=source)
        ok = all(r.get("ok") for r in results) and bool(results)
        return {"ok": ok, "workers": results}

    # ---- rolling admit fan-out -------------------------------------------

    def admit_fanout(self, payload: dict,
                     timeout: float = 600.0) -> dict:
        """Rolling `/admit` across the fleet: refresh the AOT store
        from the candidate checkpoint first (a respawn after this
        promotion must serve the NEW bytes), then admit worker by
        worker — each performs its own fidelity gate + zero-downtime
        alias flip. The admission is recorded so respawned workers
        replay it. Returns per-worker responses; `ok` is the AND."""
        payload = dict(payload)
        path = payload.get("path")
        if isinstance(path, str) and os.path.isdir(path) and self.n_max:
            try:
                self.store.export_checkpoint(path, self.n_max)
            except Exception as e:
                timeline_event("aot_export_failed", cat="serve",
                               resource="pool", spec=path,
                               error=str(e))
        with self._lock:
            self._admit_log.append(payload)
            targets = [(w.wid, w.url) for w in self.workers]
        results = []
        for wid, url in targets:
            try:
                resp = http_json(url + "/admit", payload,
                                 timeout=timeout)
            except Exception as e:
                resp = {"ok": False, "error": str(e)}
            results.append({"worker": wid, **(resp or {})})
        with self._lock:
            for w in self.workers:
                # live workers just got it; respawns replay from here
                w.admits_replayed = len(self._admit_log)
        ok = all(r.get("ok") for r in results)
        timeline_event("admit_fanout", cat="serve", resource="pool",
                       alias=payload.get("alias"), ok=ok,
                       workers=len(results))
        return {"ok": ok, "alias": payload.get("alias", "prod"),
                "workers": results}

    def _replay_admits(self, w: Worker) -> None:
        """Post-respawn catch-up: the worker restarted from startup
        specs/artifacts; any fan-out admissions since then replay in
        order so its aliases land on the same generation as the rest
        of the fleet."""
        with self._lock:
            todo = self._admit_log[w.admits_replayed:]
            already = w.admits_replayed
        for i, payload in enumerate(todo):
            try:
                http_json(w.url + "/admit", payload, timeout=600.0)
            except Exception as e:
                timeline_event("admit_replay_failed", cat="serve",
                               resource="pool", worker=w.wid,
                               error=str(e))
                break
            with self._lock:
                w.admits_replayed = already + i + 1

    # ---- the watcher -----------------------------------------------------

    def _watch(self) -> None:
        """Respawn-on-death + health scraping, one bounded pass per
        interval. Runs until stop(); joined there (and writes no files
        itself), so process exit never tears its work."""
        while True:
            with self._lock:
                if self._draining:
                    return
                snapshot = list(self.workers)
            for w in snapshot:
                self._watch_one(w)
            time.sleep(self.health_interval_s)

    def _watch_one(self, w: Worker) -> None:
        with self._lock:
            proc, state = w.proc, w.state
            draining = self._draining
        if draining or state in ("draining", "upgrading"):
            # scale_down/rolling_upgrade own this slot right now: a
            # watcher respawn would resurrect a worker mid-drain.
            return
        if proc is not None:
            # Chaos injection points (request = worker index): SIGKILL
            # the process mid-tick. kill_worker exercises the local
            # respawn-from-AOT-store; kill_remote_worker kills a
            # pool-launched AGENT (the simulated host dying) whose
            # recovery is the full cold re-join — artifact downloads
            # off the content-addressed store + re-registration.
            kind = ("kill_worker" if w.kind == "local"
                    else "kill_remote_worker")
            if chaos_fault(kind, request=w.index) is not None:
                proc.kill()
                proc.wait(timeout=30)
                with self._lock:
                    if w.kind == "local":
                        self.kills += 1
                    else:
                        self.remote_kills += 1
                timeline_event(f"chaos_{kind}", cat="recovery",
                               resource="pool", worker=w.wid)
            if proc.poll() is not None:
                with self._lock:
                    w.state = "dead"
                    w.last_health = None
                    do_respawn = self.respawn and not self._draining
                    if do_respawn:
                        self.respawns += 1
                timeline_event("worker_dead", cat="recovery",
                               resource="pool", worker=w.wid,
                               rc=proc.returncode, respawn=do_respawn)
                if not do_respawn:
                    return
                if w.kind == "remote":
                    # The agent re-joins cold: it re-downloads the
                    # artifact set from the content-addressed store
                    # and re-registers on the same host:port (the
                    # slot heals rather than growing the table).
                    self._spawn_cmd(w, w.cmd)
                    source = "artifact_service"
                else:
                    models, source = self._respawn_models()
                    self._spawn(w, models)
                with self._lock:
                    w.restarts += 1
                    w.respawn_source = source
                timeline_event("worker_respawn", cat="recovery",
                               resource="pool", worker=w.wid,
                               source=source)
                return
        try:
            probe_t0 = timeline_now()
            health = http_json(w.url + "/healthz", timeout=2.0)
            probe_t1 = timeline_now()
        except (OSError, ValueError, PoolError):
            # unreachable/slow: strikes accrue toward "failing"; an
            # externally joined remote (no process to poll) is
            # declared dead after a second round of strikes — its
            # only way back is to re-register.
            with self._lock:
                w.fails += 1
                if (w.fails >= self.SCRAPE_FAILS_FAILING
                        and w.state != "starting"):
                    w.state = "failing"
                if (w.proc is None and w.kind == "remote"
                        and w.fails >= 2 * self.SCRAPE_FAILS_FAILING):
                    w.state = "dead"
                    w.last_health = None
            return
        self._log_clock_probe(w, health, probe_t0, probe_t1)
        status = str(health.get("status", "failing"))
        with self._lock:
            w.fails = 0
            w.last_health = health
            was_starting = state == "starting"
            w.state = status if status in (
                "ok", "degraded", "failing") else "failing"
            needs_replay = (w.restarts > 0 and w.state == "ok"
                            and w.admits_replayed < len(self._admit_log))
        if was_starting and w.restarts > 0:
            timeline_event("worker_recovered", cat="recovery",
                           resource="pool", worker=w.wid,
                           restarts=w.restarts)
        if needs_replay:
            self._replay_admits(w)

    @staticmethod
    def _log_clock_probe(w: Worker, health: dict,
                         t0: Optional[float],
                         t1: Optional[float]) -> None:
        """One clock-alignment sample into THIS process's stream: the
        worker's /healthz echoed its timeline clock (`mono`, seconds
        on ITS origin) and `t0`/`t1` bracket the scrape on OURS. The
        fleet collector (obs/collect.py) turns these `clock_probe`
        marks into per-worker offsets NTP-style — the health watcher
        is already polling every worker on an interval, so alignment
        costs zero extra round trips."""
        mono = health.get("mono") if isinstance(health, dict) else None
        if (t0 is None or t1 is None
                or not isinstance(mono, (int, float))
                or isinstance(mono, bool)):
            return
        timeline_event("clock_probe", cat="serve", resource="pool",
                       worker=w.wid, remote_mono=float(mono),
                       local_t0=t0, local_t1=t1)

    # ---- scrapes for the router ------------------------------------------

    def scrape_metrics(self, w: Worker, timeout: float = 10.0) -> str:
        return http_text(w.url + "/metrics", timeout=timeout)

    # ---- shutdown --------------------------------------------------------

    def stop(self, drain_timeout_s: float = 30.0) -> None:
        """SIGTERM fan-out drain: every worker finishes its in-flight
        tick, flushes its streams and exits (the daemon's documented
        drain); stragglers are killed after the timeout. The watcher
        is stopped FIRST so a draining worker is never respawned.
        Idempotent."""
        with self._lock:
            self._draining = True
        if self._watcher is not None and self._watcher.is_alive():
            # The watcher emits timeline records; it is joined on
            # every stop path so process exit never tears its writes
            # (graftlint JGL011). First attempt bounded — the watcher
            # may be blocked in an admit replay against a worker we
            # are about to kill.
            self._watcher.join(timeout=max(10.0,
                                           self.health_interval_s * 4))
        with self._lock:
            procs = [(w, w.proc) for w in self.workers
                     if w.proc is not None]
        for _, proc in procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + drain_timeout_s
        for w, proc in procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30)
            with self._lock:
                w.state = "dead"
        if self._watcher is not None:
            # Dead workers reset any HTTP call the watcher was blocked
            # on; the second join must land. A watcher that is STILL
            # alive stays referenced so a later stop() can re-join —
            # never orphaned while claimed joined.
            if self._watcher.is_alive():
                self._watcher.join(timeout=30)
            if not self._watcher.is_alive():
                self._watcher = None
        timeline_event("pool_stop", cat="serve", resource="pool",
                       workers=len(procs))
