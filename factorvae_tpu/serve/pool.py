"""Worker-fleet manager: N scoring daemons behind one control plane.

ROADMAP item 3 / ISSUE 15 — the horizontal half of the serving story.
One `ScoringDaemon` process tops out at one dispatch path; this module
turns it into a FLEET: the pool spawns N full PR-8 daemons (each with
its own warm registry, breaker table and `/metrics`), keeps them
healthy, and gives the router (serve/router.py) a live worker table to
route over.

**Zero-compile worker cold start.** Every worker shares ONE persistent
XLA compilation cache directory (`plan.setup_compilation_cache`): the
first worker builds each scoring program once, and worker N+1
deserializes — its `/metrics` scrapes `compile == 0,
compile_cached > 0` (the PR-10 warm-restart contract, extended from
restarts to fleet joins; pinned in tests/test_pool.py). On top, the
pool PRE-EXPORTS every admitted checkpoint into a disk **AOT artifact
store** (`AotStore`: `eval/export_aot.py` container v1, one artifact
per serving alias, atomic tmp+rename, digest-keyed freshness): a
respawned worker admits the artifacts instead of re-loading
checkpoints, a cold start that involves no flax, no orbax and no trace
at all.

**Lifecycle.** `start()` brings worker 0 up first (it warms the shared
cache), pre-exports the AOT store at the fleet's measured panel width
(read off worker 0's `/stats`), then raises the rest of the fleet
warm. A watcher thread polls each worker: process death -> respawn
from the AOT store (same port — the router's worker table stays
stable) and replay of any fan-out admits; `/healthz` scrape ->
ok/degraded/failing state the router's candidate selection keys on.
`request_drain()`/`stop()` fan SIGTERM out so every worker performs
its own graceful drain (the daemon's documented SIGTERM shape), then
reap. The chaos class `kill_worker` (request = worker index) SIGKILLs
a worker from the watcher tick — `bench.py --chaos` times the
router-reroute + respawn MTTR.

**Rolling admit fan-out.** `admit_fanout(payload)` first refreshes the
AOT store from the candidate checkpoint, then POSTs `/admit` to each
worker IN SEQUENCE — a walk-forward promotion reaches every worker
holding the alias, one zero-downtime alias flip at a time, and
respawned workers replay the same admissions so a crash never
resurrects yesterday's incumbent (docs/walkforward.md).

Locking: `self._lock` guards the worker table, counters and the admit
log. Network scrapes, subprocess spawns and AOT exports all run
OUTSIDE it — a slow worker must not stall the router's
`healthy_ids()` read. The watcher thread writes no files (spawn log
handles are opened in `_spawn`, which `start()` also calls
synchronously) and is joined on every stop path.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Sequence

from factorvae_tpu.chaos import fault as chaos_fault
from factorvae_tpu.utils.logging import timeline_event


class PoolError(RuntimeError):
    """Pool-level failure with a one-line actionable message."""


def http_json(url: str, payload: Optional[dict] = None,
              timeout: float = 30.0):
    """One JSON request/response round trip (POST when `payload` is
    given, GET otherwise). HTTP error bodies that carry JSON (the
    daemon's 503 health answer, the router's shed response) parse and
    return instead of raising — only transport-level failures raise."""
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(url, data=data, method=(
        "POST" if data is not None else "GET"))
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode() or "null")
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        try:
            return json.loads(body)
        except ValueError:
            raise PoolError(
                f"{url} answered HTTP {e.code}: {body[:200]}") from None


def http_text(url: str, timeout: float = 30.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


# ---------------------------------------------------------------------------
# AOT artifact store
# ---------------------------------------------------------------------------


class AotStore:
    """Disk store of serving artifacts, one per alias: `<root>/<alias>`
    is a v1 AOT container (eval/export_aot.py) whose basename doubles
    as the registry alias a worker admits it under — exactly the alias
    the equivalent checkpoint admission would have produced, so
    requests route identically to a checkpoint-backed and an
    artifact-backed fleet. A `<alias>.meta.json` sidecar records the
    exported weights' digest so an unchanged checkpoint re-exports
    nothing (the export's one trace per call is the cost being
    skipped)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def path_for(self, alias: str) -> str:
        return os.path.join(self.root, alias)

    def has(self, alias: str) -> bool:
        return os.path.isfile(self.path_for(alias))

    def aliases(self) -> List[str]:
        return sorted(n for n in os.listdir(self.root)
                      if not n.endswith(".meta.json")
                      and os.path.isfile(os.path.join(self.root, n)))

    def export_checkpoint(self, path: str, n_max: int,
                          alias: Optional[str] = None) -> str:
        """Export one weights-only checkpoint directory as an f32
        serving artifact at cross-section width `n_max`; returns the
        artifact path. Freshness is judged by the params digest — the
        same identity the registry's re-admission version-bump uses —
        so the rollover path re-exports exactly when the bytes
        changed. The write is atomic (tmp + rename): a killed export
        never leaves a torn artifact a respawn could admit."""
        from factorvae_tpu.eval.export_aot import export_prediction
        from factorvae_tpu.models.factorvae import load_model
        from factorvae_tpu.serve.registry import (
            _params_digest,
            checkpoint_config,
        )

        path = os.path.abspath(path)
        alias = alias or os.path.basename(path)
        config = checkpoint_config(path)
        _, params = load_model(config, checkpoint_path=path, n_max=1)
        digest = _params_digest(params)
        meta_path = self.path_for(alias) + ".meta.json"
        out = self.path_for(alias)
        try:
            with open(meta_path) as fh:
                prior = json.load(fh)
        except (OSError, ValueError):
            prior = {}
        if (prior.get("digest") == digest
                and prior.get("n_max") == int(n_max)
                and os.path.isfile(out)):
            return out
        blob = export_prediction(params, config, n_max=int(n_max),
                                 stochastic=False)
        tmp = out + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, out)
        tmp_meta = meta_path + ".tmp"
        with open(tmp_meta, "w") as fh:
            json.dump({"digest": digest, "n_max": int(n_max),
                       "source": path}, fh)
        os.replace(tmp_meta, meta_path)
        timeline_event("aot_export", cat="serve", resource="pool",
                       alias=alias, n_max=int(n_max), bytes=len(blob))
        return out

    def adopt_artifact(self, path: str,
                       alias: Optional[str] = None) -> str:
        """Copy an existing artifact FILE into the store under its
        alias (the `--model m.aot` admission path needs no export)."""
        import shutil

        path = os.path.abspath(path)
        alias = alias or os.path.basename(path)
        out = self.path_for(alias)
        if os.path.abspath(out) != path:
            tmp = out + ".tmp"
            shutil.copyfile(path, tmp)
            os.replace(tmp, out)
        return out


# ---------------------------------------------------------------------------
# worker handle + pool
# ---------------------------------------------------------------------------


class Worker:
    """One worker process slot. Field mutation happens under the
    pool's lock; the subprocess handle itself is only driven by the
    pool (spawn/terminate/kill/poll)."""

    def __init__(self, index: int, port: int, log_path: str):
        self.index = index
        self.wid = f"w{index}"
        self.port = port
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.state = "starting"   # starting|ok|degraded|failing|dead
        self.restarts = 0
        self.fails = 0            # consecutive scrape failures
        self.last_health: Optional[dict] = None
        self.admits_replayed = 0
        self.respawn_source = None  # "aot_store" | "specs" on respawn

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def describe(self) -> dict:
        return {
            "worker_id": self.wid, "port": self.port, "url": self.url,
            "state": self.state,
            "pid": self.proc.pid if self.proc else None,
            "restarts": self.restarts,
            "respawn_source": self.respawn_source,
            "healthz": f"{self.url}/healthz",
            "metrics": f"{self.url}/metrics",
            "stats": f"{self.url}/stats",
            "health": self.last_health,
            "log": self.log_path,
        }


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class WorkerPool:
    """Spawn/heal/drain N `python -m factorvae_tpu.serve` workers.

    `model_specs` are the daemon's `--model` arguments (checkpoint
    dirs or artifact files); `dataset_args` the panel arguments
    (`["--dataset", p]` or `["--synthetic", "D,S"]`); `extra_args`
    pass through verbatim (precision, deadlines, ...). `cache_dir` is
    the SHARED persistent XLA compile cache every worker points at —
    the zero-compile cold-start transport; `store_dir` the AOT
    artifact store respawns admit from. `tick_ms`/`max_tick_batch`
    configure each worker's continuous-batching scheduler (None =
    leave the worker's own plan-governed resolution alone)."""

    #: consecutive health-scrape failures before a live process is
    #: treated as failing (routing stops; the process may still be
    #: compiling its warmup — only death triggers a respawn)
    SCRAPE_FAILS_FAILING = 3

    def __init__(self, model_specs: Sequence[str],
                 dataset_args: Sequence[str],
                 n_workers: int,
                 cache_dir: str,
                 store_dir: str,
                 work_dir: Optional[str] = None,
                 warmup: bool = True,
                 extra_args: Sequence[str] = (),
                 tick_ms: Optional[float] = None,
                 max_tick_batch: Optional[int] = None,
                 metrics_base: Optional[str] = None,
                 health_interval_s: float = 0.5,
                 respawn: bool = True,
                 start_timeout_s: float = 600.0,
                 single_thread_xla: bool = True,
                 env: Optional[dict] = None):
        if n_workers < 1:
            raise PoolError("a pool needs at least 1 worker")
        self.model_specs = [os.path.abspath(m) for m in model_specs]
        self.dataset_args = list(dataset_args)
        self.cache_dir = os.path.abspath(cache_dir)
        self.store = AotStore(store_dir)
        import tempfile

        self.work_dir = os.path.abspath(
            work_dir or tempfile.mkdtemp(prefix="serve_pool_"))
        os.makedirs(self.work_dir, exist_ok=True)
        self.warmup = bool(warmup)
        self.extra_args = list(extra_args)
        self.tick_ms = tick_ms
        self.max_tick_batch = max_tick_batch
        # Per-worker RUN streams ON by default (under work_dir): the
        # compile-record taxonomy a worker's /metrics exposes only
        # counts LOGGED records (obs/watchdog.py), and the fleet
        # cold-start contract — worker N+1 scrapes compile==0,
        # compile_cached>0 — is pinned off exactly that scrape.
        self.metrics_base = metrics_base or os.path.join(
            self.work_dir, "RUN.jsonl")
        self.health_interval_s = float(health_interval_s)
        self.respawn = bool(respawn)
        self.start_timeout_s = float(start_timeout_s)
        worker_env = dict(os.environ if env is None else env)
        # Workers spawn with cwd=work_dir: make THIS checkout's
        # package importable regardless of where the pool was started.
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        worker_env["PYTHONPATH"] = repo + os.pathsep + \
            worker_env.get("PYTHONPATH", "")
        if single_thread_xla:
            # One worker per core is the fleet's scaling model on CPU
            # hosts: each worker's XLA runs single-threaded so N
            # workers divide the machine instead of thrashing each
            # other's intra-op thread pools (measured on this rig: the
            # multi-threaded eigen pool LOSES on serving-sized ops
            # even at N=1). CPU-backend flags only — a TPU worker
            # ignores them.
            flags = worker_env.get("XLA_FLAGS", "")
            if "xla_cpu_multi_thread_eigen" not in flags:
                worker_env["XLA_FLAGS"] = (
                    flags + " --xla_cpu_multi_thread_eigen=false "
                    "intra_op_parallelism_threads=1").strip()
        # Built locally, assigned once, read-only afterwards (the
        # watcher thread's respawn path reads it).
        self.env = worker_env
        self._lock = threading.Lock()
        self.workers: List[Worker] = [
            Worker(i, free_port(),
                   os.path.join(self.work_dir, f"w{i}.log"))
            for i in range(int(n_workers))]
        self.n_max: Optional[int] = None
        self.respawns = 0
        self.kills = 0            # chaos kill_worker firings
        self._admit_log: List[dict] = []
        self._draining = False
        self._watcher: Optional[threading.Thread] = None

    # ---- spawning --------------------------------------------------------

    def _worker_cmd(self, w: Worker, models: Sequence[str]) -> list:
        cmd = [sys.executable, "-m", "factorvae_tpu.serve"]
        for m in models:
            cmd += ["--model", m]
        cmd += list(self.dataset_args)
        cmd += ["--http", str(w.port), "--compile_cache", self.cache_dir,
                "--scheduler"]
        if self.warmup:
            cmd += ["--warmup"]
        if self.tick_ms is not None:
            cmd += ["--tick_ms", str(float(self.tick_ms))]
        if self.max_tick_batch is not None:
            cmd += ["--max_batch", str(int(self.max_tick_batch))]
        if self.metrics_base:
            base, ext = os.path.splitext(self.metrics_base)
            cmd += ["--metrics_jsonl", f"{base}_{w.wid}{ext or '.jsonl'}"]
        cmd += self.extra_args
        return cmd

    def _respawn_models(self) -> tuple:
        """(models, source): the AOT store's artifacts when it covers
        every alias (the zero-trace cold start), else the original
        specs (the store may not exist yet on a very early death)."""
        aliases = [os.path.basename(m) for m in self.model_specs]
        if all(self.store.has(a) for a in aliases):
            return [self.store.path_for(a) for a in aliases], "aot_store"
        return list(self.model_specs), "specs"

    def _spawn(self, w: Worker, models: Sequence[str]) -> None:
        """Start (or restart) one worker process; the handle and state
        land under the lock, the spawn itself runs outside it."""
        cmd = self._worker_cmd(w, models)
        log = open(w.log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                    env=self.env, cwd=self.work_dir)
        finally:
            log.close()   # the child holds its own descriptor
        with self._lock:
            w.proc = proc
            w.state = "starting"
            w.fails = 0
            w.admits_replayed = 0

    def _wait_healthy(self, workers: Sequence[Worker],
                      timeout_s: Optional[float] = None) -> None:
        deadline = time.monotonic() + (timeout_s
                                       or self.start_timeout_s)
        remaining = list(workers)
        while remaining and time.monotonic() < deadline:
            still = []
            for w in remaining:
                if w.proc is not None and w.proc.poll() is not None:
                    tail = self.worker_log_tail(w)
                    raise PoolError(
                        f"worker {w.wid} died during startup "
                        f"(rc={w.proc.returncode}); log tail:\n{tail}")
                try:
                    health = http_json(w.url + "/healthz", timeout=2.0)
                except (OSError, ValueError, PoolError):
                    # not listening yet (startup compiles): keep polling
                    still.append(w)
                    continue
                with self._lock:
                    w.last_health = health
                    w.state = "ok" if health.get("ok") else "failing"
            remaining = still
            if remaining:
                time.sleep(0.2)
        if remaining:
            raise PoolError(
                f"worker(s) {', '.join(w.wid for w in remaining)} "
                f"never answered /healthz within "
                f"{timeout_s or self.start_timeout_s:.0f}s "
                f"(logs under {self.work_dir})")

    def worker_log_tail(self, w: Worker, n: int = 2000) -> str:
        try:
            with open(w.log_path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                fh.seek(max(0, fh.tell() - n))
                return fh.read().decode(errors="replace")
        except OSError:
            return "<no log>"

    def start(self) -> None:
        """Bring the fleet up: worker 0 first (it pays the compiles
        into the shared cache), then the AOT pre-export at the
        measured panel width, then the rest of the fleet — warm by
        construction."""
        self._spawn(self.workers[0], self.model_specs)
        self._wait_healthy(self.workers[:1])
        stats = http_json(self.workers[0].url + "/stats", timeout=30.0)
        self.n_max = int((stats.get("panel") or {}).get("n_max") or 0)
        self.pre_export()
        for w in self.workers[1:]:
            self._spawn(w, self.model_specs)
        if len(self.workers) > 1:
            self._wait_healthy(self.workers[1:])
        self._watcher = threading.Thread(
            target=self._watch, name="pool-watcher", daemon=True)
        self._watcher.start()

    def pre_export(self) -> List[str]:
        """Populate the AOT store from the admitted model specs (one
        artifact per alias; checkpoint dirs export, artifact files
        copy in). Failures are logged, not fatal — the store is a
        respawn accelerator, the original specs remain the fallback."""
        done = []
        for spec in self.model_specs:
            try:
                if os.path.isdir(spec):
                    if not self.n_max:
                        raise PoolError(
                            "panel width unknown; start() reads it "
                            "off worker 0's /stats before exporting")
                    done.append(self.store.export_checkpoint(
                        spec, self.n_max))
                else:
                    done.append(self.store.adopt_artifact(spec))
            except Exception as e:
                timeline_event("aot_export_failed", cat="serve",
                               resource="pool", spec=spec,
                               error=str(e))
        return done

    # ---- health / routing view -------------------------------------------

    def healthy_ids(self) -> List[str]:
        with self._lock:
            return [w.wid for w in self.workers
                    if w.state in ("ok", "degraded")]

    def worker(self, wid: str) -> Worker:
        with self._lock:
            for w in self.workers:
                if w.wid == wid:
                    return w
        raise PoolError(f"unknown worker {wid!r}")

    def note_failure(self, wid: str) -> None:
        """Router-observed forward failure: stop routing to the worker
        until the watcher's next scrape clears it (or its death is
        confirmed and the respawn path takes over)."""
        with self._lock:
            for w in self.workers:
                if w.wid == wid:
                    w.fails += 1
                    if w.fails >= 1 and w.state in ("ok", "degraded"):
                        w.state = "failing"
                    return

    def stats(self) -> dict:
        with self._lock:
            return {
                "workers": [w.describe() for w in self.workers],
                "healthy": sum(1 for w in self.workers
                               if w.state in ("ok", "degraded")),
                "respawns": self.respawns,
                "kills": self.kills,
                "admits_fanned_out": len(self._admit_log),
                "aot_store": self.store.root,
                "compile_cache": self.cache_dir,
                "n_max": self.n_max,
                "draining": self._draining,
            }

    # ---- rolling admit fan-out -------------------------------------------

    def admit_fanout(self, payload: dict,
                     timeout: float = 600.0) -> dict:
        """Rolling `/admit` across the fleet: refresh the AOT store
        from the candidate checkpoint first (a respawn after this
        promotion must serve the NEW bytes), then admit worker by
        worker — each performs its own fidelity gate + zero-downtime
        alias flip. The admission is recorded so respawned workers
        replay it. Returns per-worker responses; `ok` is the AND."""
        payload = dict(payload)
        path = payload.get("path")
        if isinstance(path, str) and os.path.isdir(path) and self.n_max:
            try:
                self.store.export_checkpoint(path, self.n_max)
            except Exception as e:
                timeline_event("aot_export_failed", cat="serve",
                               resource="pool", spec=path,
                               error=str(e))
        with self._lock:
            self._admit_log.append(payload)
            targets = [(w.wid, w.url) for w in self.workers]
        results = []
        for wid, url in targets:
            try:
                resp = http_json(url + "/admit", payload,
                                 timeout=timeout)
            except Exception as e:
                resp = {"ok": False, "error": str(e)}
            results.append({"worker": wid, **(resp or {})})
        with self._lock:
            for w in self.workers:
                # live workers just got it; respawns replay from here
                w.admits_replayed = len(self._admit_log)
        ok = all(r.get("ok") for r in results)
        timeline_event("admit_fanout", cat="serve", resource="pool",
                       alias=payload.get("alias"), ok=ok,
                       workers=len(results))
        return {"ok": ok, "alias": payload.get("alias", "prod"),
                "workers": results}

    def _replay_admits(self, w: Worker) -> None:
        """Post-respawn catch-up: the worker restarted from startup
        specs/artifacts; any fan-out admissions since then replay in
        order so its aliases land on the same generation as the rest
        of the fleet."""
        with self._lock:
            todo = self._admit_log[w.admits_replayed:]
            already = w.admits_replayed
        for i, payload in enumerate(todo):
            try:
                http_json(w.url + "/admit", payload, timeout=600.0)
            except Exception as e:
                timeline_event("admit_replay_failed", cat="serve",
                               resource="pool", worker=w.wid,
                               error=str(e))
                break
            with self._lock:
                w.admits_replayed = already + i + 1

    # ---- the watcher -----------------------------------------------------

    def _watch(self) -> None:
        """Respawn-on-death + health scraping, one bounded pass per
        interval. Runs until stop(); joined there (and writes no files
        itself), so process exit never tears its work."""
        while True:
            with self._lock:
                if self._draining:
                    return
                snapshot = list(self.workers)
            for w in snapshot:
                self._watch_one(w)
            time.sleep(self.health_interval_s)

    def _watch_one(self, w: Worker) -> None:
        with self._lock:
            proc, state = w.proc, w.state
            draining = self._draining
        if proc is None or draining:
            return
        # Chaos injection point (request = worker index): SIGKILL the
        # worker mid-tick; the recovery exercised is the router's
        # reroute plus THIS watcher's respawn-from-AOT-store.
        if chaos_fault("kill_worker", request=w.index) is not None:
            proc.kill()
            proc.wait(timeout=30)
            with self._lock:
                self.kills += 1
            timeline_event("chaos_kill_worker", cat="recovery",
                           resource="pool", worker=w.wid)
        if proc.poll() is not None:
            with self._lock:
                w.state = "dead"
                w.last_health = None
                do_respawn = self.respawn and not self._draining
                if do_respawn:
                    self.respawns += 1
            timeline_event("worker_dead", cat="recovery",
                           resource="pool", worker=w.wid,
                           rc=proc.returncode, respawn=do_respawn)
            if not do_respawn:
                return
            models, source = self._respawn_models()
            self._spawn(w, models)
            with self._lock:
                w.restarts += 1
                w.respawn_source = source
            timeline_event("worker_respawn", cat="recovery",
                           resource="pool", worker=w.wid,
                           source=source)
            return
        try:
            health = http_json(w.url + "/healthz", timeout=2.0)
        except (OSError, ValueError, PoolError):
            # unreachable/slow: strikes accrue toward "failing"
            with self._lock:
                w.fails += 1
                if (w.fails >= self.SCRAPE_FAILS_FAILING
                        and w.state != "starting"):
                    w.state = "failing"
            return
        status = str(health.get("status", "failing"))
        with self._lock:
            w.fails = 0
            w.last_health = health
            was_starting = state == "starting"
            w.state = status if status in (
                "ok", "degraded", "failing") else "failing"
            needs_replay = (w.restarts > 0 and w.state == "ok"
                            and w.admits_replayed < len(self._admit_log))
        if was_starting and w.restarts > 0:
            timeline_event("worker_recovered", cat="recovery",
                           resource="pool", worker=w.wid,
                           restarts=w.restarts)
        if needs_replay:
            self._replay_admits(w)

    # ---- scrapes for the router ------------------------------------------

    def scrape_metrics(self, w: Worker, timeout: float = 10.0) -> str:
        return http_text(w.url + "/metrics", timeout=timeout)

    # ---- shutdown --------------------------------------------------------

    def stop(self, drain_timeout_s: float = 30.0) -> None:
        """SIGTERM fan-out drain: every worker finishes its in-flight
        tick, flushes its streams and exits (the daemon's documented
        drain); stragglers are killed after the timeout. The watcher
        is stopped FIRST so a draining worker is never respawned.
        Idempotent."""
        with self._lock:
            self._draining = True
        if self._watcher is not None and self._watcher.is_alive():
            # The watcher emits timeline records; it is joined on
            # every stop path so process exit never tears its writes
            # (graftlint JGL011). First attempt bounded — the watcher
            # may be blocked in an admit replay against a worker we
            # are about to kill.
            self._watcher.join(timeout=max(10.0,
                                           self.health_interval_s * 4))
        with self._lock:
            procs = [(w, w.proc) for w in self.workers
                     if w.proc is not None]
        for _, proc in procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + drain_timeout_s
        for w, proc in procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30)
            with self._lock:
                w.state = "dead"
        if self._watcher is not None:
            # Dead workers reset any HTTP call the watcher was blocked
            # on; the second join must land. A watcher that is STILL
            # alive stays referenced so a later stop() can re-join —
            # never orphaned while claimed joined.
            if self._watcher.is_alive():
                self._watcher.join(timeout=30)
            if not self._watcher.is_alive():
                self._watcher = None
        timeline_event("pool_stop", cat="serve", resource="pool",
                       workers=len(procs))
