"""Production scoring service (ISSUE 8): warm AOT model registry +
long-lived scoring daemon.

    python -m factorvae_tpu.serve --model best_models/<name> ...

See docs/serving.md for the registry keying, the precision ladder's
guarantees, the request/response schema and the latency envelope;
`bench.py --serve` measures p50/p99/QPS on this machine.
"""

from factorvae_tpu.serve.daemon import (
    ScoringDaemon,
    serve_batch_file,
    serve_http,
    serve_stdin,
)
from factorvae_tpu.serve.registry import (
    Entry,
    ModelRegistry,
    RegistryError,
    checkpoint_config,
    precision_config,
)

__all__ = [
    "Entry",
    "ModelRegistry",
    "RegistryError",
    "ScoringDaemon",
    "checkpoint_config",
    "precision_config",
    "serve_batch_file",
    "serve_http",
    "serve_stdin",
]
