"""Production scoring service (ISSUE 8/15): warm AOT model registry +
long-lived scoring daemon, scaled horizontally by a router +
worker-fleet tier.

    python -m factorvae_tpu.serve --model best_models/<name> ...
    python -m factorvae_tpu.serve ... --workers 4 --router_port 8800

See docs/serving.md for the registry keying, the precision ladder's
guarantees, the request/response schema, the scale-out tier's
routing/stickiness/shed rules and the latency envelope;
`bench.py --serve [--workers 1,2,4]` measures p50/p99/QPS (and the
scaling curve) on this machine.
"""

from factorvae_tpu.serve.daemon import (
    ScoringDaemon,
    TickScheduler,
    serve_batch_file,
    serve_http,
    serve_stdin,
)
from factorvae_tpu.serve.pool import AotStore, PoolError, WorkerPool
from factorvae_tpu.serve.router import Router, rendezvous_order
from factorvae_tpu.serve.registry import (
    Entry,
    ModelRegistry,
    RegistryError,
    checkpoint_config,
    precision_config,
)

__all__ = [
    "AotStore",
    "Entry",
    "ModelRegistry",
    "PoolError",
    "RegistryError",
    "Router",
    "ScoringDaemon",
    "TickScheduler",
    "WorkerPool",
    "checkpoint_config",
    "precision_config",
    "rendezvous_order",
    "serve_batch_file",
    "serve_http",
    "serve_stdin",
]
