"""SLO-driven worker autoscaling over the router's own signals.

The control loop (ISSUE 17) that turns `--autoscale MAX` into fleet
size: every `interval_s` it reads the router's `autoscale_signals()` —
queue depth, observed p99 vs the declared `--slo_ms`, healthy worker
count — and decides **up**, **down**, or nothing. No new measurement
machinery: the signals are the counters the router already keeps (and
`/metrics` already exports via `obs.metrics.autoscale_families`), so
any scale decision can be replayed off a scrape.

Policy, deliberately boring:

- **Scale UP** when the fleet is pressured: observed p99 above the
  declared SLO, queue depth above `queue_high_per_worker x healthy`,
  or fewer healthy workers than `min_workers` (a death the watcher
  hasn't healed yet). Pressure must hold for `up_after` CONSECUTIVE
  ticks — hysteresis, so one slow compile doesn't double the fleet.
- **Scale DOWN** when idle: queue depth at/under `queue_low` AND p99
  comfortably inside the SLO (under half, when one is declared) for
  `down_after` consecutive ticks. Down is slower than up on purpose —
  flapping costs cold joins.
- **Bounds**: never below `min_workers`, never above `max_workers`;
  `cooldown_s` after any action before the next (scale_up already
  blocks on the new worker turning healthy, a natural cooldown on
  top).

`decide()` is pure — signals in, verdict out — so the hysteresis and
bound logic unit-tests without a fleet (tests/test_remote.py). The
actuation (`pool.scale_up` / `pool.scale_down`) grows remote workers
when the pool has a `router_url` (joining agents that bootstrap off
the artifact service), local ones otherwise.

Threading: one daemon thread, `Event.wait(interval)` paced, joined on
`stop()` — the watcher-thread discipline from serve/pool.py. The loop
calls pool/router methods that take their own locks and holds none of
its own while actuating.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from factorvae_tpu.utils.logging import timeline_event


class AutoScaler:
    """Scale `pool` between `min_workers` and `max_workers` from the
    router's signals. `start()`/`stop()` run the loop on an internal
    thread; `tick()` runs one read-decide-act round inline (tests, and
    the bench's deterministic drives)."""

    def __init__(self, pool, router, min_workers: int = 1,
                 max_workers: int = 4, slo_ms: float = 0.0,
                 interval_s: float = 1.0, up_after: int = 2,
                 down_after: int = 6, cooldown_s: float = 5.0,
                 queue_high_per_worker: int = 4, queue_low: int = 1):
        self.pool = pool
        self.router = router
        self.min_workers = max(1, int(min_workers))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.slo_ms = float(slo_ms)
        self.interval_s = float(interval_s)
        self.up_after = max(1, int(up_after))
        self.down_after = max(1, int(down_after))
        self.cooldown_s = float(cooldown_s)
        self.queue_high_per_worker = int(queue_high_per_worker)
        self.queue_low = int(queue_low)
        # hysteresis state: consecutive pressured / idle ticks, and
        # ticks remaining in the post-action cooldown. One lock
        # serializes every counter write/composite read — decide()
        # runs on the loop thread while describe()//metric_families()
        # scrape from the router's request threads.
        self._lock = threading.Lock()
        self._above = 0
        self._below = 0
        self._cooldown_ticks = 0
        self.ticks = 0
        self.ups = 0
        self.downs = 0
        self.last_decision: Optional[str] = None
        self.last_reason: str = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- policy (pure) ---------------------------------------------------

    def _pressure(self, sig: dict) -> Tuple[bool, List[str]]:
        """Is the fleet pressured this tick, and why."""
        why = []
        healthy = int(sig.get("workers_healthy") or 0)
        queue = int(sig.get("queue_depth") or 0)
        p99 = sig.get("p99_ms")
        slo = float(sig.get("slo_ms") or self.slo_ms or 0.0)
        if healthy < self.min_workers:
            why.append(f"healthy {healthy} < min {self.min_workers}")
        if queue > self.queue_high_per_worker * max(1, healthy):
            why.append(f"queue {queue} > "
                       f"{self.queue_high_per_worker}/worker")
        if slo > 0 and p99 is not None and p99 > slo:
            why.append(f"p99 {p99:.1f}ms > SLO {slo:g}ms")
        return bool(why), why

    def _idle(self, sig: dict) -> bool:
        queue = int(sig.get("queue_depth") or 0)
        p99 = sig.get("p99_ms")
        slo = float(sig.get("slo_ms") or self.slo_ms or 0.0)
        if queue > self.queue_low:
            return False
        if slo > 0 and p99 is not None and p99 > 0.5 * slo:
            return False
        return True

    def decide(self, sig: dict) -> Optional[str]:
        """One tick of policy: 'up', 'down', or None. Pure in `sig`
        (plus the instance's hysteresis counters) — no pool, no
        router, no clock — so the policy unit-tests standalone."""
        with self._lock:
            self.ticks += 1
            if self._cooldown_ticks > 0:
                self._cooldown_ticks -= 1
                self.last_decision = None
                self.last_reason = "cooldown"
                return None
            total = int(sig.get("workers_total") or 0)
            pressured, why = self._pressure(sig)
            if pressured:
                self._above += 1
                self._below = 0
            elif self._idle(sig):
                self._below += 1
                self._above = 0
            else:
                self._above = self._below = 0
            if (self._above >= self.up_after
                    and total < self.max_workers):
                self._above = self._below = 0
                self._cooldown_ticks = self._cooldown_ratio()
                self.last_decision = "up"
                self.last_reason = "; ".join(why)
                return "up"
            if (self._below >= self.down_after
                    and total > self.min_workers):
                self._above = self._below = 0
                self._cooldown_ticks = self._cooldown_ratio()
                self.last_decision = "down"
                self.last_reason = (f"idle: queue <= "
                                    f"{self.queue_low} for "
                                    f"{self.down_after} ticks")
                return "down"
            self.last_decision = None
            self.last_reason = "; ".join(why) if pressured else ""
            return None

    def _cooldown_ratio(self) -> int:
        if self.interval_s <= 0:
            return 0
        return max(0, int(round(self.cooldown_s / self.interval_s)))

    # ---- actuation -------------------------------------------------------

    def tick(self) -> Optional[str]:
        """One read-decide-act round. Returns the action taken."""
        sig = self.router.autoscale_signals()
        verdict = self.decide(sig)
        if verdict is None:
            return None
        try:
            if verdict == "up":
                w = self.pool.scale_up()
                if w is not None:
                    with self._lock:
                        self.ups += 1
            else:
                wid = self.pool.scale_down()
                if wid is not None:
                    with self._lock:
                        self.downs += 1
        except Exception as e:
            timeline_event("autoscale_failed", cat="serve",
                           resource="autoscaler", action=verdict,
                           error=str(e)[:200])
            return None
        timeline_event("autoscale", cat="serve",
                       resource="autoscaler", action=verdict,
                       reason=self.last_reason,
                       queue=sig.get("queue_depth"),
                       p99_ms=sig.get("p99_ms"),
                       healthy=sig.get("workers_healthy"))
        return verdict

    # ---- loop ------------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()  # graftlint: disable=JGL009 threading.Event is itself the synchronization primitive (internally locked); this re-arm runs strictly before Thread.start() below, and stop() joins the loop thread before any restart — no concurrent wait() can exist here
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler")
        self._thread.daemon = True
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout=60)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:
                # the loop outlives any one bad tick — a scrape
                # hiccup must not kill autoscaling for the run
                timeline_event("autoscale_tick_error", cat="serve",
                               resource="autoscaler",
                               error=str(e)[:200])

    # ---- telemetry -------------------------------------------------------

    def describe(self) -> dict:
        with self._lock:
            return {"min_workers": self.min_workers,
                    "max_workers": self.max_workers,
                    "slo_ms": self.slo_ms,
                    "interval_s": self.interval_s,
                    "ticks": self.ticks,
                    "ups": self.ups, "downs": self.downs,
                    "last_decision": self.last_decision,
                    "last_reason": self.last_reason,
                    "pressured_ticks": self._above,
                    "idle_ticks": self._below,
                    "cooldown_ticks": self._cooldown_ticks}

    def metric_families(self):
        """Exposition families for the router's /metrics merge."""
        from factorvae_tpu.obs.metrics import PREFIX, metric_line

        with self._lock:
            ups, downs = self.ups, self.downs
        p = f"{PREFIX}_router_autoscale"
        return [
            (f"{p}_ups_total", "counter",
             "autoscaler scale-up actions",
             [metric_line(f"{p}_ups_total", ups)]),
            (f"{p}_downs_total", "counter",
             "autoscaler scale-down actions",
             [metric_line(f"{p}_downs_total", downs)]),
            (f"{p}_max_workers", "gauge",
             "autoscaler worker-count ceiling",
             [metric_line(f"{p}_max_workers", self.max_workers)]),
            (f"{p}_min_workers", "gauge",
             "autoscaler worker-count floor",
             [metric_line(f"{p}_min_workers", self.min_workers)]),
        ]
