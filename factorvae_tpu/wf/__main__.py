"""Walk-forward driver: the nightly loop as one resumable command.

    # bootstrap a synthetic rig and run 3 crash-safe nightly cycles,
    # serving HTTP throughout (zero-downtime rollover)
    python -m factorvae_tpu.wf --run_dir ./wf_run --cycles 3 \
        --force_refit --epochs 4 --http 8787 --metrics_jsonl RUN_WF.jsonl

    # killed at ANY stage? the same command resumes the open cycle
    # idempotently off the cycle journal (<run>_wf.json)
    python -m factorvae_tpu.wf --run_dir ./wf_run --cycles 3 ...

The driver owns the full triple: a `PanelStore` (bootstrapped from
--dataset or a synthetic panel), a STREAM-residency `PanelDataset`
(appended days are picked up in place — no reload, no retrace), a
`ModelRegistry` + `ScoringDaemon` (optionally fronted by HTTP on
--http while cycles run), and a `WalkForwardOperator` journaling every
stage. Incoming days come from --incoming PICKLE files (one per cycle,
reference schema) or are synthesized deterministically per target
generation — determinism is what makes a killed append resumable.

Startup chatter goes to STDERR; the JSON cycle summaries go to STDOUT
(one line per cycle).
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.wf",
        description="closed-loop walk-forward operator: drift-triggered "
                    "retrain + zero-downtime rollover "
                    "(docs/walkforward.md)")
    p.add_argument("--run_dir", required=True,
                   help="operator workspace: journal, incumbent/"
                        "candidate checkpoints, default store location")
    p.add_argument("--store", default=None,
                   help="panel store directory (default: "
                        "<run_dir>/store)")
    p.add_argument("--dataset", default=None,
                   help="bootstrap the store from this reference-schema "
                        "pickle when the store does not exist yet")
    p.add_argument("--incoming", action="append", default=[],
                   metavar="PICKLE",
                   help="per-cycle incoming panel pickle (repeatable, "
                        "consumed in order); without it, incoming days "
                        "are synthesized deterministically")
    p.add_argument("--cycles", type=int, default=1,
                   help="nightly cycles to run (resuming an open cycle "
                        "counts as its own cycle)")
    p.add_argument("--new_days", type=int, default=2,
                   help="synthetic incoming days per cycle")
    p.add_argument("--alias", default="prod",
                   help="serving alias the rollover flips")
    p.add_argument("--epochs", type=int, default=None,
                   help="bootstrap + refit epochs (default: the config "
                        "schedule)")
    p.add_argument("--force_refit", action="store_true",
                   help="retrain every cycle (the nightly cadence) "
                        "instead of only on drift triggers")
    p.add_argument("--cold_ab", action="store_true",
                   help="race a cold-start fit against the warm start "
                        "each refit (holdout Rank-IC decides the "
                        "candidate)")
    p.add_argument("--min_margin", type=float, default=0.0,
                   help="fidelity gate slack: promote when candidate "
                        "Rank-IC >= incumbent - margin")
    p.add_argument("--drift_threshold", type=float, default=0.5,
                   help="day-over-day rank-correlation floor; served "
                        "correlations below it trigger a refit (set "
                        "per model at each promotion)")
    p.add_argument("--holdout_days", type=int, default=1,
                   help="newest labeled days held out for the fidelity "
                        "gate and the warm/cold A/B")
    p.add_argument("--window_days", type=int, default=0,
                   help="rolling train window in days (0 = expanding)")
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="serve scoring HTTP on 127.0.0.1:PORT on a "
                        "background thread while cycles run (the "
                        "zero-downtime demonstration)")
    p.add_argument("--seed", type=int, default=0,
                   help="model seed + synthetic feed seed base")
    # synthetic rig shapes (bootstrap only; a real --dataset wins)
    p.add_argument("--init_days", type=int, default=32)
    p.add_argument("--stocks", type=int, default=12)
    p.add_argument("--features", type=int, default=6)
    p.add_argument("--hidden", type=int, default=8)
    p.add_argument("--factors", type=int, default=4)
    p.add_argument("--portfolios", type=int, default=8)
    p.add_argument("--seq_len", type=int, default=5)
    p.add_argument("--metrics_jsonl", default=None,
                   help="RUN.jsonl stream for wf stage spans + train "
                        "epochs + serve spans (render: python -m "
                        "factorvae_tpu.obs.timeline)")
    p.add_argument("--compile_cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache dir (default: "
                        "$FACTORVAE_COMPILE_CACHE; 'off' disables) — a "
                        "resumed nightly run deserializes its programs "
                        "instead of recompiling")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    # Persistent compile cache BEFORE jax warms up: a nightly resume
    # (the crash-recovery path) deserializes yesterday's programs.
    from factorvae_tpu import plan as planlib

    planlib.setup_compilation_cache(args.compile_cache)

    import os
    import threading

    from factorvae_tpu.config import (
        Config,
        DataConfig,
        ModelConfig,
        TrainConfig,
    )
    from factorvae_tpu.data import PanelDataset, PanelStore
    from factorvae_tpu.data.append import AppendError
    from factorvae_tpu.data.synthetic import (
        continuation_panel,
        synthetic_panel_dense,
    )
    from factorvae_tpu.serve.daemon import ScoringDaemon, serve_http
    from factorvae_tpu.serve.registry import ModelRegistry
    from factorvae_tpu.utils.logging import (
        MetricsLogger,
        Timeline,
        install_timeline,
    )
    from factorvae_tpu.wf.journal import CycleJournal, JournalError
    from factorvae_tpu.wf.operator import (
        WalkForwardError,
        WalkForwardOperator,
    )

    run_dir = os.path.abspath(args.run_dir)
    os.makedirs(run_dir, exist_ok=True)
    store_dir = os.path.abspath(args.store or
                                os.path.join(run_dir, "store"))

    logger = MetricsLogger(jsonl_path=args.metrics_jsonl, echo=False,
                           run_name="walkforward")
    prev_tl = install_timeline(Timeline(logger)) \
        if args.metrics_jsonl else None
    http_thread = None
    daemon = None
    try:
        # ---- store -------------------------------------------------------
        try:
            store = PanelStore(store_dir)
        except AppendError:
            store = None
        if store is None or store.generation == 0:
            # Missing, or EMPTY (a create() killed between its manifest
            # commit and the seed-slab append): (re)seed it — create
            # adopts the empty store, so the crash window resumes.
            if args.dataset:
                from factorvae_tpu.data import build_panel, load_frame

                seed_panel = build_panel(load_frame(args.dataset))
            else:
                seed_panel = synthetic_panel_dense(
                    num_days=args.init_days,
                    num_instruments=args.stocks,
                    num_features=args.features, seed=args.seed)
            store = PanelStore.create(store_dir, seed_panel)
            print(f"[wf] created store {store_dir}: "
                  f"{store.num_days}d x {len(store.instruments)} "
                  f"instruments", file=sys.stderr)

        dataset = PanelDataset(store.load_panel(),
                               seq_len=args.seq_len,
                               residency="stream")

        # ---- config ------------------------------------------------------
        cfg = Config(
            model=ModelConfig(
                num_features=dataset.panel.num_features,
                hidden_size=args.hidden, num_factors=args.factors,
                num_portfolios=args.portfolios, seq_len=args.seq_len,
                stochastic_inference=False),
            data=DataConfig(seq_len=args.seq_len, start_time=None,
                            fit_end_time=None, val_start_time=None,
                            val_end_time=None,
                            panel_residency="stream"),
            train=TrainConfig(
                seed=args.seed, run_name="walkforward",
                **({"num_epochs": args.epochs} if args.epochs else {})))

        # ---- serving plane ----------------------------------------------
        registry = ModelRegistry()
        daemon = ScoringDaemon(registry, dataset, stochastic=False,
                               seed=args.seed,
                               drift_threshold=args.drift_threshold)
        if args.http is not None:
            # Non-daemon thread + join on exit: the serving loop owns
            # timeline writes, and the drain below ends it within one
            # accept tick.
            http_thread = threading.Thread(
                target=serve_http, args=(daemon, args.http),
                name="wf-http")
            http_thread.start()
            print(f"[wf] serving http://127.0.0.1:{args.http}/score "
                  f"during cycles", file=sys.stderr)

        journal = CycleJournal(os.path.join(
            run_dir, f"{cfg.train.run_name}_wf.json"))
        if journal.recovered_from_backup:
            print("[wf] journal main document was damaged; resumed "
                  "from .bak (one stage may re-run)", file=sys.stderr)
        op = WalkForwardOperator(
            store, dataset, daemon, cfg, run_dir, alias=args.alias,
            journal=journal, refit_epochs=args.epochs,
            cold_ab=args.cold_ab, force_refit=args.force_refit,
            min_margin=args.min_margin,
            drift_threshold=args.drift_threshold,
            holdout_days=args.holdout_days,
            window_days=args.window_days, logger=logger)

        key = op.ensure_incumbent(epochs=args.epochs)
        print(f"[wf] incumbent {key[:12]} behind alias "
              f"{args.alias!r}", file=sys.stderr)

        # ---- cycles ------------------------------------------------------
        incoming_files = list(args.incoming)
        for _ in range(max(1, args.cycles)):
            cycle_id = op.next_cycle_id()
            gen = int(cycle_id[1:])
            if incoming_files:
                from factorvae_tpu.data import build_panel, load_frame

                piece = build_panel(load_frame(incoming_files.pop(0)))
            else:
                # Deterministic per target generation: a resumed run
                # regenerates the exact bytes the killed run appended
                # (the idempotent-append contract). Generation g's
                # days start after slab g-1's end — whether or not
                # slab g already committed before the crash.
                import pandas as pd

                if store.generation >= gen:
                    prev_end = pd.Timestamp(
                        store.slabs[gen - 2]["end"])
                else:
                    prev_end = store.end_date
                piece = continuation_panel(
                    store.instruments, prev_end, args.new_days,
                    store.num_columns - 1,
                    seed=args.seed * 100003 + gen)
            summary = op.run_cycle(piece)
            print(json.dumps(summary))
            sys.stdout.flush()
        return 0
    except (AppendError, JournalError, WalkForwardError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        if daemon is not None and http_thread is not None:
            daemon.request_drain()
            http_thread.join(timeout=10)
        if prev_tl is not None or args.metrics_jsonl:
            install_timeline(prev_tl)
        logger.finish()


if __name__ == "__main__":
    sys.exit(main())
