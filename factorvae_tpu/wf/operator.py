"""The closed-loop walk-forward operator (ROADMAP item 2, ISSUE 14).

One nightly cycle, run as an idempotent journaled state machine over
subsystems the repo already has:

    append   incremental panel append (data/append.py PanelStore;
             slab sha256-validated before commit) + in-place serving
             pickup (ScoringDaemon.extend_dataset -> stream-residency
             PanelDataset.extend_days: no full reload, no device
             transfer, no scoring retrace)
    judge    the incumbent scores the new day(s) THROUGH the daemon,
             feeding obs/drift.py's day-over-day rank-correlation
             chain; drift past the model's ACTIVE threshold is
             promoted from alert to *trigger* (scheduled refit) —
             `force_refit` makes every cycle retrain (the nightly
             cadence), and a serving failure on the new day triggers
             too (a sick incumbent is its own reason to refit)
    refit    warm-started from the incumbent's checkpoint via the
             existing Checkpointer (params into a fresh optimizer +
             schedule), trained on the appended panel's rolling
             window; a cold-start fit is raced as an A/B when
             `cold_ab` is on, judged by holdout Rank-IC
    promote  `ScoringDaemon.admit` (POST /admit): candidate admitted
             into the live registry under its config hash, fidelity
             gate (candidate vs incumbent Rank-IC on the holdout day,
             by masked_spearman) decides; losers are retired and
             logged, winners flip the serving alias under the tick
             lock — in-flight requests complete on the incumbent,
             zero requests drop
    verify   the first served score from the promoted model closes
             the cycle

Every stage transition persists to the torn-write-tolerant cycle
journal (wf/journal.py, `<run>_wf.json`, atomic rename), so a SIGKILL
at ANY boundary resumes idempotently: committed stages replay from
their recorded results, the uncommitted stage re-runs — the append is
slab-idempotent, the refit resumes bitwise from the candidate's own
checkpoints, the promotion re-admits the same bytes (a refresh, not a
generation bump) and re-derives the same deterministic verdict. The
chaos classes `kill_mid_append` / `corrupt_append_slab` /
`kill_mid_refit` / `kill_between_admit_and_drain` /
`fidelity_gate_reject` pin exactly these windows (bench.py --chaos
times their MTTR).

Bitwise discipline: a no-fault cycle's refit parameters are BITWISE a
plain `warm_refit` call on the appended panel — the operator adds
journaling around the fit, never arithmetic inside it
(tests/test_wf.py pins it).
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import time
from typing import List, Optional

import numpy as np

from factorvae_tpu.config import Config
from factorvae_tpu.data.append import PanelStore
from factorvae_tpu.data.panel import Panel
from factorvae_tpu.obs.trace import child, root_ctx, span_fields
from factorvae_tpu.utils.logging import MetricsLogger, timeline_span
from factorvae_tpu.wf.journal import CycleJournal


class WalkForwardError(RuntimeError):
    """Operator-level failure with a one-line actionable message."""


# ---------------------------------------------------------------------------
# refit primitives (module-level so tests can pin the operator's refit
# bitwise against a plain call)
# ---------------------------------------------------------------------------


def holdout_day_indices(dataset, n: int = 1) -> List[int]:
    """The newest `n` day indices with rankable labels — the SHARED
    holdout definition (`eval.metrics.labeled_holdout_days`) the
    fidelity gate also judges on, with the operator's error."""
    from factorvae_tpu.eval.metrics import labeled_holdout_days

    days = labeled_holdout_days(dataset, n)
    if not days:
        raise WalkForwardError(
            "no day with >=3 finite labels in the panel; the fidelity "
            "gate cannot judge Rank-IC — check the label column")
    return days


def warm_refit(config: Config, dataset, warm_params=None,
               resume: bool = False,
               logger: Optional[MetricsLogger] = None):
    """One refit fit: a fresh Trainer over `dataset`, started from
    `warm_params` (fresh optimizer state + schedule — the params are
    yesterday's, the optimization is today's), or cold when None.

    `resume=True` continues from the config's OWN checkpoints when any
    exist (the killed-mid-refit path: the per-epoch full-state
    checkpoints the fit writes make the continuation bitwise — the
    established PR-4 resume contract); with none on disk it falls back
    to the warm/cold start, so a kill before the first checkpoint is a
    plain re-run.

    Returns (state, fit_info, best_weights_dir)."""
    from factorvae_tpu.train.checkpoint import Checkpointer
    from factorvae_tpu.train.trainer import Trainer

    trainer = Trainer(config, dataset, logger=logger)
    has_ckpt = False
    if resume and config.train.checkpoint_every:
        ck_dir = os.path.join(
            config.train.save_dir, config.checkpoint_name() + "_ckpt")
        ck = Checkpointer(ck_dir, keep=config.train.keep_checkpoints,
                          async_save=config.train.async_checkpointing)
        try:
            has_ckpt = ck.latest_step() is not None
        finally:
            ck.close()
    if has_ckpt:
        state, info = trainer.fit(resume=True)
    else:
        start = trainer.init_state()
        if warm_params is not None:
            start = start.replace(params=warm_params)
        state, info = trainer.fit(state=start)
    weights = os.path.join(config.train.save_dir,
                           config.checkpoint_name())
    return state, info, weights


def refit_rank_ic(params, config: Config, dataset,
                  days: List[int], seed: int = 0) -> float:
    """Holdout Rank-IC of a refit candidate's params (deterministic
    scores; the same masked_spearman judge the promotion gate uses)."""
    from factorvae_tpu.eval.metrics import panel_rank_ic
    from factorvae_tpu.eval.predict import predict_panel

    days = np.asarray(days, np.int64)
    scores = predict_panel(params, config, dataset, days,
                           stochastic=False, seed=seed)
    return panel_rank_ic(scores, dataset.day_labels(days),
                         dataset.valid[days])


# ---------------------------------------------------------------------------
# the operator
# ---------------------------------------------------------------------------


class WalkForwardOperator:
    """Runs nightly cycles against a live (store, dataset, daemon)
    triple. The daemon may be serving traffic from another thread the
    whole time — every mutation the operator performs on shared state
    goes through the daemon's tick lock (extend_dataset, admit)."""

    def __init__(self, store: PanelStore, dataset, daemon,
                 config: Config, run_dir: str,
                 alias: str = "prod",
                 journal: Optional[CycleJournal] = None,
                 refit_epochs: Optional[int] = None,
                 cold_ab: bool = False,
                 force_refit: bool = False,
                 min_margin: float = 0.0,
                 drift_threshold: Optional[float] = None,
                 holdout_days: int = 1,
                 window_days: int = 0,
                 keep_cycles: int = 2,
                 logger: Optional[MetricsLogger] = None):
        self.store = store
        self.dataset = dataset
        self.daemon = daemon
        self.config = config
        self.run_dir = os.path.abspath(run_dir)
        self.alias = alias
        self.journal = journal or CycleJournal(os.path.join(
            self.run_dir, f"{config.train.run_name}_wf.json"))
        self.refit_epochs = refit_epochs
        self.cold_ab = bool(cold_ab)
        self.force_refit = bool(force_refit)
        self.min_margin = float(min_margin)
        self.drift_threshold = drift_threshold
        self.holdout_days = max(1, int(holdout_days))
        self.window_days = max(0, int(window_days))
        self.keep_cycles = max(1, int(keep_cycles))
        self.logger = logger or MetricsLogger(echo=False)
        # The in-flight stage's trace context (ISSUE 20): set by
        # run_cycle's stage() wrapper, read by the stages that cross
        # into the serving plane (judge/promote/verify) so daemon
        # requests and admissions join the cycle's trace tree.
        self._stage_ctx: Optional[dict] = None

    # ---- cycle identity / configs ----------------------------------------

    def next_cycle_id(self) -> str:
        """The resume target's id, else the next generation's. Cycle N
        appends the store's Nth slab, so the id is derivable before
        AND after the append committed (the driver regenerates the
        same deterministic incoming either way)."""
        cur = self.journal.open_cycle()
        if cur is not None:
            return cur["id"]
        return f"c{self.store.generation + 1:05d}"

    def cycle_dir(self, cycle_id: str) -> str:
        return os.path.join(self.run_dir, "cycles", cycle_id)

    def _candidate_config(self, cycle_id: str,
                          cold: bool = False) -> Config:
        """The refit Config: same architecture, per-cycle save_dir (its
        own config hash — candidate and incumbent coexist in the
        registry for the gate), splits re-anchored on the APPENDED
        panel: train up to the holdout, validate on the holdout tail,
        optional rolling `window_days` lower bound."""
        ds = self.dataset
        hold = holdout_day_indices(ds, self.holdout_days)
        fit_end = str(ds.dates[hold[0] - 1].date()) if hold[0] > 0 \
            else None
        start = self.config.data.start_time
        if self.window_days:
            lo = max(0, hold[0] - self.window_days)
            start = str(ds.dates[lo].date())
        save_dir = self.cycle_dir(cycle_id)
        if cold:
            save_dir = os.path.join(save_dir, "cold")
        train_kw = dict(save_dir=save_dir, checkpoint_every=1)
        if self.refit_epochs is not None:
            train_kw["num_epochs"] = int(self.refit_epochs)
        return dataclasses.replace(
            self.config,
            data=dataclasses.replace(
                self.config.data, start_time=start,
                fit_end_time=fit_end,
                val_start_time=str(ds.dates[hold[0]].date()),
                val_end_time=None),
            train=dataclasses.replace(self.config.train, **train_kw))

    # ---- bootstrap -------------------------------------------------------

    def ensure_incumbent(self, epochs: Optional[int] = None) -> str:
        """Make sure a model serves behind the alias: re-admit the
        journaled incumbent (a fresh process after a crash), else
        bootstrap-train one on the current panel and admit it
        unconditionally. Returns the serving key."""
        from factorvae_tpu.serve.registry import RegistryError

        try:
            return self.daemon.registry.resolve_key(self.alias)
        except RegistryError:
            pass  # nothing behind the alias yet: admit or bootstrap
        path = self.journal.get_meta("incumbent_path")
        if path and os.path.isdir(path):
            resp = self.daemon.admit(path, self.alias,
                                     drift_threshold=self.drift_threshold)
            return resp["model"]
        cfg = dataclasses.replace(
            self.config,
            train=dataclasses.replace(
                self.config.train,
                save_dir=os.path.join(self.run_dir, "incumbent"),
                checkpoint_every=1,
                **({"num_epochs": int(epochs)} if epochs else {})))
        self.logger.log("wf_bootstrap", run=cfg.train.run_name,
                        epochs=cfg.train.num_epochs)
        with timeline_span("wf_bootstrap", cat="wf", resource="wf"):
            _, _, weights = warm_refit(cfg, self.dataset,
                                       warm_params=None, resume=True,
                                       logger=self.logger)
        resp = self.daemon.admit(weights, self.alias,
                                 drift_threshold=self.drift_threshold)
        self.journal.set_meta("incumbent_path", weights)
        return resp["model"]

    # ---- stages ----------------------------------------------------------

    def _trace_field(self) -> Optional[dict]:
        """The wire trace context of the in-flight stage ({"trace_id",
        "span_id"}) — what a daemon request's `trace` field or an
        admit's `trace=` carries so the serving plane's spans graft
        under this stage in the cycle tree. None outside run_cycle."""
        ctx = self._stage_ctx
        if ctx is None:
            return None
        return {"trace_id": ctx["trace_id"],
                "span_id": ctx["span_id"]}

    def _stage_append(self, incoming: Panel) -> dict:
        rec = self.store.append_panel(incoming)
        # Serving-side pickup, serialized with ticks; idempotent when
        # the resumed dataset (rebuilt from the post-append store)
        # already holds the days.
        self.daemon.extend_dataset(incoming)
        return dict(rec, n_days_total=int(len(self.dataset.dates)))

    def _stage_judge(self, incoming: Panel) -> dict:
        """Serve the day BEFORE the append plus each appended day with
        the incumbent (through the daemon — the drift monitor's
        day-over-day chain advances exactly as production traffic
        would advance it), then read the drift verdict. Deterministic
        on re-run: the same days served in the same order rebuild the
        same chain even in a fresh post-crash process."""
        ds = self.dataset
        dates = ds.dates
        first_new = int(dates.get_indexer([incoming.dates[0]])[0])
        if first_new < 0:
            raise WalkForwardError(
                f"judge: appended day {incoming.dates[0].date()} is "
                f"not in the serving panel — the append stage did not "
                f"commit; resume the cycle")
        days = [d for d in range(first_new - 1, len(dates))
                if d >= 0]
        inc_key = self.daemon.registry.resolve_key(self.alias)
        tf = self._trace_field()
        failures = 0
        for day in days:
            req = {"model": self.alias, "day": day}
            if tf is not None:
                req["trace"] = tf
            resp = self.daemon.handle(req)
            if not resp.get("ok"):
                failures += 1
        drift = self.daemon.drift.stats().get(inc_key, {})
        corr = drift.get("last_rank_corr")
        threshold = self.daemon.drift.threshold_for(inc_key)
        drifting = bool(self.daemon.drift.drifting(inc_key))
        trigger = bool(self.force_refit or drifting or failures)
        reasons = [r for r, hit in (
            ("force_refit", self.force_refit),
            ("score_drift", drifting),
            ("serving_failures", failures > 0)) if hit]
        return {"trigger": trigger,
                "reason": "+".join(reasons) or "no_drift",
                "rank_corr": corr, "threshold": threshold,
                "incumbent": inc_key, "days_served": len(days),
                "failures": failures}

    def _warm_params(self, template_state):
        """The incumbent's params as the warm start, restored from its
        full-state checkpoint via the existing Checkpointer when one
        exists (the documented warm-start source), else the serving
        entry's in-memory tree."""
        from factorvae_tpu.train.checkpoint import Checkpointer

        entry = self.daemon.registry.get(self.alias)
        ck_dir = (entry.source_path or "") + "_ckpt"
        if entry.source_path and os.path.isdir(ck_dir):
            ck = Checkpointer(ck_dir, async_save=False)
            try:
                state, _ = ck.restore(template_state)
                return state.params
            finally:
                ck.close()
        if entry.params is None:
            raise WalkForwardError(
                f"incumbent {entry.key} has neither a full-state "
                f"checkpoint at {ck_dir} nor in-memory params to "
                f"warm-start from")
        return entry.params

    def _stage_refit(self, cycle_id: str) -> dict:
        from factorvae_tpu import chaos
        from factorvae_tpu.train.trainer import Trainer

        cand_cfg = self._candidate_config(cycle_id)
        fresh = not self.journal.marked("refit_started")
        if fresh:
            # Wipe-then-mark: a kill between the two re-wipes (no-op);
            # the mark only ever covers THIS cycle's artifacts, so a
            # marked resume never adopts a previous cycle's
            # checkpoints.
            shutil.rmtree(self.cycle_dir(cycle_id), ignore_errors=True)
            self.journal.mark("refit_started")
        if chaos.fault("kill_mid_refit", step=0) is not None:
            chaos.ops.kill_now()
        template = Trainer(cand_cfg, self.dataset,
                           logger=self.logger).init_state()
        warm_params = self._warm_params(template)
        hold = holdout_day_indices(self.dataset, self.holdout_days)
        with timeline_span("wf_refit_warm", cat="wf", resource="wf"):
            state, info, weights = warm_refit(
                cand_cfg, self.dataset, warm_params=warm_params,
                resume=not fresh, logger=self.logger)
        result = {
            "holdout_days": hold,
            "warm": {
                "best_val": float(info["best_val"]),
                "rank_ic": refit_rank_ic(state.params, cand_cfg,
                                         self.dataset, hold),
                "path": weights,
                "epochs": len(info["history"]),
            },
            "cold": None, "winner": "warm",
        }
        if self.cold_ab:
            cold_cfg = self._candidate_config(cycle_id, cold=True)
            with timeline_span("wf_refit_cold", cat="wf",
                               resource="wf"):
                cstate, cinfo, cweights = warm_refit(
                    cold_cfg, self.dataset, warm_params=None,
                    resume=not fresh, logger=self.logger)
            result["cold"] = {
                "best_val": float(cinfo["best_val"]),
                "rank_ic": refit_rank_ic(cstate.params, cold_cfg,
                                         self.dataset, hold),
                "path": cweights,
                "epochs": len(cinfo["history"]),
            }
            warm_ic = result["warm"]["rank_ic"]
            cold_ic = result["cold"]["rank_ic"]
            # Cold must STRICTLY beat warm on finite ICs to take the
            # candidacy — warm is the walk-forward default.
            if (np.isfinite(cold_ic)
                    and (not np.isfinite(warm_ic)
                         or cold_ic > warm_ic)):
                result["winner"] = "cold"
        result["path"] = result[result["winner"]]["path"]
        if chaos.fault("kill_mid_refit", step=1) is not None:
            chaos.ops.kill_now()
        return result

    def _stage_promote(self, refit: dict) -> dict:
        resp = self.daemon.admit(
            refit["path"], self.alias,
            holdout_days=refit.get("holdout_days"),
            min_margin=self.min_margin,
            drift_threshold=self.drift_threshold,
            trace=self._trace_field())
        if resp.get("promoted"):
            self.journal.set_meta("incumbent_path", refit["path"])
        keep = ("promoted", "model", "incumbent", "reason",
                "candidate_rank_ic", "incumbent_rank_ic", "alias",
                "generation")
        return {k: resp[k] for k in keep if k in resp}

    def _stage_verify(self) -> dict:
        """First served score from whatever now stands behind the
        alias — the cycle is closed by the SERVING plane answering,
        not by the operator believing its own bookkeeping."""
        day = int(self.dataset.split_days(None, None)[-1])
        req = {"model": self.alias, "day": day}
        tf = self._trace_field()
        if tf is not None:
            req["trace"] = tf
        resp = self.daemon.handle(req)
        if not resp.get("ok"):
            raise WalkForwardError(
                f"verify: serving the newest day failed "
                f"({resp.get('error')}); the cycle stays open — fix "
                f"the daemon and resume")
        return {"day": day,
                "date": str(self.dataset.dates[day].date()),
                "model": resp["model"], "n": resp["n"],
                "latency_ms": resp.get("latency_ms")}

    # ---- the cycle -------------------------------------------------------

    def run_cycle(self, incoming: Panel) -> dict:
        """Run (or resume) one cycle over `incoming` (the new days).
        Returns a summary with per-stage results and walls; committed
        stages replay their journaled results without re-running."""
        cycle_id = self.next_cycle_id()
        # Cycle-scoped trace root (ISSUE 20): trace id `wf-<cycle>` —
        # derived from the journal's deterministic cycle counter, so a
        # resumed cycle rejoins the SAME trace. Every stage span is a
        # child, and the stages that cross into the serving plane
        # carry the stage's context onto their requests/admissions —
        # one cycle renders as ONE tree spanning operator and daemon.
        trace_root = root_ctx(f"wf-{cycle_id}", "cycle")
        self.journal.begin_cycle(
            cycle_id, start=str(incoming.dates[0].date()),
            end=str(incoming.dates[-1].date()),
            days=int(incoming.num_days))
        walls = {}
        ran = {}

        def stage(name, fn, *args):
            done = self.journal.committed(name)
            if done is not None:
                ran[name] = False
                return done
            t0 = time.perf_counter()
            self._stage_ctx = child(trace_root, name)
            try:
                with timeline_span(f"wf_{name}", cat="wf",
                                   resource="wf", cycle=cycle_id,
                                   **span_fields(self._stage_ctx)):
                    result = fn(*args)
            finally:
                self._stage_ctx = None
            walls[name] = round(time.perf_counter() - t0, 4)
            ran[name] = True
            self.logger.log("wf_stage", cycle=cycle_id, stage=name,
                            wall_s=walls[name], **{
                                k: v for k, v in result.items()
                                if isinstance(v, (int, float, str,
                                                  bool, type(None)))})
            return self.journal.commit(name, dict(result,
                                                  wall_s=walls[name]))

        with timeline_span("wf_cycle", cat="wf", resource="wf",
                           cycle=cycle_id,
                           **span_fields(trace_root)):
            append = stage("append", self._stage_append, incoming)
            judge = stage("judge", self._stage_judge, incoming)
            if judge["trigger"]:
                refit = stage("refit", self._stage_refit, cycle_id)
                promote = stage("promote", self._stage_promote,
                                refit)
            else:
                refit = stage("refit", lambda: {"skipped": True})
                promote = stage("promote",
                                lambda: {"skipped": True,
                                         "promoted": False})
            verify = stage("verify", self._stage_verify)
        self.journal.finish_cycle()
        self._cleanup_cycles()
        summary = {
            "cycle": cycle_id,
            "triggered": bool(judge["trigger"]),
            "promoted": bool(promote.get("promoted")),
            "stages": {"append": append, "judge": judge,
                       "refit": refit, "promote": promote,
                       "verify": verify},
            "walls": walls, "ran": ran,
        }
        if ran.get("refit") and ran.get("verify") \
                and not refit.get("skipped"):
            # refit start -> first served score from the rolled-over
            # model, the bench.py --walkforward headline
            summary["refit_to_serve_s"] = round(
                sum(walls.get(s, 0.0)
                    for s in ("refit", "promote", "verify")), 4)
        self.logger.log("wf_cycle", **{
            k: v for k, v in summary.items()
            if isinstance(v, (int, float, str, bool, type(None)))})
        return summary

    def _cleanup_cycles(self) -> None:
        """Opportunistically drop old per-cycle candidate workspaces,
        keeping the newest `keep_cycles` plus anything the journaled
        incumbent path still lives in."""
        root = os.path.join(self.run_dir, "cycles")
        try:
            dirs = sorted(d for d in os.listdir(root)
                          if os.path.isdir(os.path.join(root, d)))
        except OSError:
            return
        incumbent = self.journal.get_meta("incumbent_path") or ""
        for d in dirs[:-self.keep_cycles]:
            full = os.path.join(root, d)
            if incumbent.startswith(full + os.sep):
                continue
            shutil.rmtree(full, ignore_errors=True)
