"""Torn-write-tolerant cycle journal for the walk-forward operator.

One JSON document (`<run>_wf.json`) records every cycle's stage
commits, so a walk-forward run killed at ANY boundary resumes
idempotently: a committed stage's recorded result is reused verbatim,
an uncommitted stage re-runs (every stage is built to be re-runnable —
see wf/operator.py).

Durability discipline:

- Every save is tmp-write + fsync + **atomic rename**: readers never
  see a half-written journal, a kill mid-save leaves the previous
  committed document in place.
- Before each rename the PREVIOUS committed document is copied to
  `<path>.bak`, so even external damage to the main file (the
  `torn_jsonl`-style byte corruption the chaos harness injects at
  other streams) degrades to "resume from the previous commit" — one
  stage re-runs — instead of an unreadable run.
- A journal whose main AND backup documents both fail to parse raises
  `JournalError` with a one-line actionable message; the operator
  never guesses at cycle state.

Schema (docs/walkforward.md):

    {"version": 1,
     "meta": {"incumbent_path": ...},          # operator facts
     "cycles": [
        {"id": "c00002", "done": false,
         "facts": {...},                       # begin_cycle kwargs
         "marks": {"refit_started": true},     # sub-stage markers
         "stages": {"append": {...}, "judge": {...}, ...}}]}
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

#: cycle stages, in execution order (wf/operator.py runs them in this
#: order and commits each exactly once per cycle)
STAGES = ("append", "judge", "refit", "promote", "verify")


class JournalError(RuntimeError):
    """Unusable journal state, with a one-line actionable message."""


class CycleJournal:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        # Transient, per-process: NEVER stored in the document (a
        # persisted flag would mark the journal damaged forever).
        self._recovered = False
        self._doc = self._load()

    # ---- durability ------------------------------------------------------

    def _load(self) -> dict:
        try:
            with open(self.path) as fh:
                return json.load(fh)
        except FileNotFoundError:
            return {"version": 1, "meta": {}, "cycles": []}
        except ValueError:
            pass
        # Main document torn/corrupt: fall back to the previous commit.
        try:
            with open(self.path + ".bak") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            raise JournalError(
                f"cycle journal {self.path} is unreadable and no "
                f"usable {os.path.basename(self.path)}.bak exists; "
                f"move the damaged file aside to start a fresh run, or "
                f"restore the journal from backup") from None
        doc.setdefault("meta", {})
        self._recovered = True
        return doc

    def _save(self) -> None:
        if os.path.exists(self.path):
            # Keep the previous committed document reachable: read the
            # bytes that are on disk NOW and land them as .bak via the
            # same atomic-rename discipline.
            with open(self.path, "rb") as fh:
                prev = fh.read()
            bak_tmp = self.path + ".bak.tmp"
            with open(bak_tmp, "wb") as fh:
                fh.write(prev)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(bak_tmp, self.path + ".bak")
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._doc, fh, indent=1)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)

    # ---- run-level facts -------------------------------------------------

    @property
    def recovered_from_backup(self) -> bool:
        """True only in the process that actually fell back to .bak —
        the next (healthy) load reports False again."""
        return self._recovered

    def get_meta(self, key: str, default: Any = None) -> Any:
        return self._doc.get("meta", {}).get(key, default)

    def set_meta(self, key: str, value: Any) -> None:
        self._doc.setdefault("meta", {})[key] = value
        self._save()

    # ---- cycles ----------------------------------------------------------

    def current(self) -> Optional[dict]:
        """The newest cycle record, or None on a fresh journal."""
        cycles = self._doc["cycles"]
        return cycles[-1] if cycles else None

    def open_cycle(self) -> Optional[dict]:
        """The newest cycle IF it is still in flight (the resume
        target), else None."""
        cur = self.current()
        return cur if cur is not None and not cur.get("done") else None

    def begin_cycle(self, cycle_id: str, **facts) -> dict:
        """Open a cycle (idempotent: re-beginning the open cycle with
        the same id resumes it; a different id while one is open is a
        driver bug and raises)."""
        cur = self.open_cycle()
        if cur is not None:
            if cur["id"] != cycle_id:
                raise JournalError(
                    f"cycle {cur['id']} is still open in {self.path} "
                    f"but the driver asked to begin {cycle_id!r}; "
                    f"finish or abandon the open cycle first")
            return cur
        cur = {"id": str(cycle_id), "done": False, "facts": dict(facts),
               "marks": {}, "stages": {},
               "started": round(time.time(), 3)}
        self._doc["cycles"].append(cur)
        self._save()
        return cur

    def committed(self, stage: str) -> Optional[dict]:
        """The open cycle's committed result for `stage`, or None."""
        if stage not in STAGES:
            raise JournalError(
                f"unknown stage {stage!r} (stages: {', '.join(STAGES)})")
        cur = self.open_cycle()
        return None if cur is None else cur["stages"].get(stage)

    def commit(self, stage: str, result: dict) -> dict:
        """Commit one stage's result to the open cycle (atomic rename;
        re-running a committed stage is the operator bug this API makes
        impossible to miss)."""
        if stage not in STAGES:
            raise JournalError(
                f"unknown stage {stage!r} (stages: {', '.join(STAGES)})")
        cur = self.open_cycle()
        if cur is None:
            raise JournalError(
                f"no open cycle in {self.path} to commit "
                f"stage {stage!r} to")
        if stage in cur["stages"]:
            raise JournalError(
                f"stage {stage!r} of cycle {cur['id']} is already "
                f"committed; committed stages are immutable")
        cur["stages"][stage] = dict(result, _ts=round(time.time(), 3))
        self._save()
        return cur["stages"][stage]

    def mark(self, key: str, value: Any = True) -> None:
        """Sub-stage marker on the open cycle (e.g. `refit_started`:
        set AFTER the candidate workspace is wiped, so a resume can
        tell a crashed refit-in-progress from a never-started one)."""
        cur = self.open_cycle()
        if cur is None:
            raise JournalError(
                f"no open cycle in {self.path} to mark {key!r} on")
        cur.setdefault("marks", {})[key] = value
        self._save()

    def marked(self, key: str) -> Any:
        cur = self.open_cycle()
        return None if cur is None else cur.get("marks", {}).get(key)

    def finish_cycle(self) -> dict:
        cur = self.open_cycle()
        if cur is None:
            raise JournalError(f"no open cycle in {self.path} to finish")
        missing = [s for s in STAGES if s not in cur["stages"]]
        if missing:
            raise JournalError(
                f"cycle {cur['id']} cannot finish with uncommitted "
                f"stage(s): {', '.join(missing)}")
        cur["done"] = True
        cur["finished"] = round(time.time(), 3)
        self._save()
        return cur

    def cycles(self) -> list:
        return list(self._doc["cycles"])
