"""Closed-loop walk-forward production (ROADMAP item 2, ISSUE 14).

`wf.operator.WalkForwardOperator` runs the nightly
append -> judge -> refit -> promote -> verify cycle as an idempotent
journaled state machine over the repo's existing subsystems;
`python -m factorvae_tpu.wf` is the self-contained driver
(docs/walkforward.md).
"""

from factorvae_tpu.wf.journal import STAGES, CycleJournal, JournalError
from factorvae_tpu.wf.operator import (
    WalkForwardError,
    WalkForwardOperator,
    holdout_day_indices,
    refit_rank_ic,
    warm_refit,
)

__all__ = [
    "STAGES",
    "CycleJournal",
    "JournalError",
    "WalkForwardError",
    "WalkForwardOperator",
    "holdout_day_indices",
    "refit_rank_ic",
    "warm_refit",
]
