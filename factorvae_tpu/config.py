"""Typed configuration for the whole framework.

Replaces the reference's argparse namespace (main.py:90-114), the
`DataArgument` dataclass (utils.py:19-53) and the `test_args` dataclass
(utils.py:95-111) with a single serializable config tree that covers
model / data / training / mesh, and that is embedded into checkpoints and
score filenames.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Union


@dataclass(frozen=True)
class ModelConfig:
    """Architecture hyper-parameters.

    Mirrors the knobs of the reference model assembly (main.py:27-33):
    ``num_latent`` -> ``num_features`` (C), ``hidden_size`` (H),
    ``num_factor`` -> ``num_factors`` (K), ``num_portfolio`` ->
    ``num_portfolios`` (M).
    """

    num_features: int = 158      # C: Alpha158 features  (main.py:95 --num_latent)
    hidden_size: int = 64        # H                     (main.py:100)
    num_factors: int = 96        # K                     (main.py:99)
    num_portfolios: int = 128    # M                     (main.py:96)
    seq_len: int = 20            # T: look-back window   (main.py:98)
    gru_layers: int = 1          # reference uses a 1-layer GRU (module.py:20)
    dropout_rate: float = 0.1    # attention-score dropout (module.py:132)
    leaky_relu_slope: float = 0.01  # torch nn.LeakyReLU default
    # Reconstruction loss. 'mse' is reference-faithful (module.py:261:
    # F.mse_loss on ONE reparameterized sample). 'nll' is the paper's
    # Gaussian negative log-likelihood (BASELINE.json north star); both are
    # provided, flag-selected, so parity can be measured against 'mse'.
    recon_loss: str = "mse"
    # KL scale: loss = recon + kl_weight * KL. 1.0 is reference-faithful
    # (the unweighted sum of module.py:268, where the KL is itself a SUM
    # over K while the MSE is a mean over N). A tuning knob for the
    # parity sweeps (VERDICT r2 #6): at large K the summed KL dominates
    # the gradient signal. The reported `kl` metric stays unweighted.
    kl_weight: float = 1.0
    # Reference-faithful inference draws a reparameterized sample even in
    # `prediction()` (module.py:123). `stochastic_inference=False` uses the
    # distribution mean instead (deterministic scores).
    stochastic_inference: bool = True
    # Compute dtype for the heavy linear algebra ("float32" | "bfloat16").
    # Parameters, softmax/softplus statistics and losses stay float32.
    # The bare-library default is float32 (exact torch-oracle numerics);
    # every CLI path and preset sets bfloat16, the measured-best TPU
    # configuration (PERF.md) — pass --no-bf16 to opt out. TRAINING at
    # bfloat16 resolves through the mixed-precision master-weight path
    # (train.compute_dtype / docs/precision.md), never a naive
    # whole-model cast: f32 params + loss scaling + overflow-skip.
    compute_dtype: str = "float32"
    # Use torch-style U(+-1/sqrt(fan_in)) initializers so training dynamics
    # match the reference's scale. False -> flax defaults (lecun_normal).
    torch_init: bool = True
    # Cross-day flattening (VERDICT r2 #2): run the day-independent
    # per-stock segment (extractor, alpha/beta heads, portfolio/key/value
    # projections) on the flattened (B*N, ...) block so the MXU sees one
    # tall matmul per op instead of B row-starved ones. False keeps the
    # per-day nn.vmap lift; outputs are identical either way (same param
    # tree; deterministic paths bitwise-comparable up to fp reassociation).
    flatten_days: bool = True
    # Fused Pallas kernel for the K-head cross-section attention
    # (ops/pallas/attention.py + attention_grad.py; differentiable, fused
    # dropout). "auto" (default since r3, VERDICT r2 #3) = per-shape
    # choice from the measured on-chip race (ops/pallas/select.py) —
    # XLA einsum wherever the kernel did not win, and always off-TPU.
    # False = force the XLA path; True = force the kernel.
    use_pallas_attention: Union[bool, str] = "auto"
    # Fused Pallas GRU recurrence (ops/pallas/gru.py; custom-VJP BPTT,
    # single-layer path). False | True | "auto" as above; lax.scan is
    # the reference path.
    use_pallas_gru: Union[bool, str] = "auto"

    @property
    def dtype(self):
        import jax.numpy as jnp

        return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[self.compute_dtype]


@dataclass(frozen=True)
class DataConfig:
    """Data-split configuration (reference utils.py:19-53 DataArgument)."""

    dataset_path: str = "./data/csi_data.pkl"
    start_time: str = "2009-01-01"       # main.py:103
    fit_end_time: str = "2017-12-31"     # main.py:104
    val_start_time: str = "2018-01-01"   # main.py:105
    val_end_time: str = "2018-12-31"     # main.py:106
    end_time: str = "2020-12-31"         # main.py:107
    seq_len: int = 20
    normalize: bool = True
    select_feature: Optional[Sequence[str]] = None
    # Cross-section padding size (N_max). None -> inferred from the panel
    # (max instruments per day, rounded up to `pad_multiple`).
    max_stocks: Optional[int] = None
    # Round N_max up to a multiple of this for TPU-friendly tiling (the MXU
    # operates on 128-lane tiles) and for even sharding over a 'stock' axis.
    pad_multiple: int = 8
    # Panel residency (plan.panel_residency): "hbm" ships the whole
    # (N_max, D, C+1) panel to the device once (today's path); "stream"
    # keeps it host-resident and double-buffers prefetched day-chunk
    # batches onto the device (data/stream.py) — bitwise-equal training/
    # scoring with O(2 chunks) device residency instead of O(D).
    panel_residency: str = "hbm"
    # Stream chunk size in DAYS per host->device transfer (the planner's
    # raced knob; docs/streaming.md has the HBM-budget math).
    stream_chunk_days: int = 32


@dataclass(frozen=True)
class TrainConfig:
    """Optimization configuration (reference main.py:52,60-61,92-93)."""

    num_epochs: int = 30
    lr: float = 1e-4
    seed: int = 42
    # Number of trading days whose gradients are averaged per optimizer
    # update. 1 is reference-faithful (one day = one SGD step,
    # train_model.py:17-32). >1 enables day-level data parallelism: with a
    # d-device mesh each device takes days_per_step/d days and gradients are
    # all-reduced over ICI.
    days_per_step: int = 1
    # Cosine schedule over total update count (main.py:52,61).
    cosine_schedule: bool = True
    run_name: str = "VAE-Revision2"
    save_dir: str = "./best_models"
    wandb: bool = False
    # Checkpoint every N epochs for fault tolerance (0 = best-val only,
    # which is all the reference ever saved; main.py:73-80).
    checkpoint_every: int = 1
    keep_checkpoints: int = 3
    # Async checkpointing (train/checkpoint.py): save() snapshots to host
    # and serializes on a background thread, overlapping the next epoch;
    # False restores the old blocking save (bitwise-identical artifacts
    # either way — tested).
    async_checkpointing: bool = True
    # On-device training-health probes (obs/probes.py): grad/update/param
    # global norms, non-finite counters and factor-posterior spread
    # compiled into the epoch-scan aux — zero extra dispatches, measured
    # overhead tracked by `bench.py --obs`. Off by default: the off path
    # is BITWISE the pre-observatory trace (tests/test_obs.py). CLI
    # `--obs`; a measured plan row can switch it via its "obs" block.
    obs_probes: bool = False
    # In-graph all-finite gate (train/loop.py): every optimizer update
    # is applied through a jnp.where select keyed on "all gradient
    # elements finite", so ONE poisoned step (NaN/inf grads — hardware
    # flakes, the k60 posterior-KL degenerate regime) skips its update
    # instead of destroying the params; per-seed on fleets (one bad
    # lane skips alone). With the gate compiled in and no fault firing
    # the select always takes the updated branch, so params/metrics
    # stay BITWISE the ungated path (tests/test_chaos.py); the epoch
    # metric `skipped_steps` counts skips. docs/robustness.md.
    finite_guard: bool = True
    # Host-side escalation (docs/robustness.md): after `recover_after`
    # CONSECUTIVE bad epochs (non-finite train loss, or any steps
    # skipped by the finite guard) the serial Trainer rolls back to the
    # last checkpoint written before the bad streak, scales the peak lr
    # by `recover_lr_backoff`, and re-runs — at most
    # `recover_max_rollbacks` times per fit, each logged as a
    # `recovery` event + `recovery_rollback` timeline mark. 0 disables.
    # FleetTrainer rolls back only the bad lanes (no lr change: the
    # optimizer is shared across lanes) and continues forward.
    recover_after: int = 2
    recover_lr_backoff: float = 0.5
    recover_max_rollbacks: int = 2
    # Training compute dtype ("float32" | "bfloat16" | None). None (the
    # default) inherits `model.compute_dtype`, so a bf16 model now
    # TRAINS through the mixed-precision path instead of the old naive
    # whole-model cast: params and opt_state stay float32 (master
    # weights — checkpoints and best-weight artifacts keep the serial
    # f32 format), one explicit bf16 cast of the param tree feeds the
    # forward/backward, and the loss is dynamically scaled (knobs
    # below). "float32" forces the exact pre-mixed trace regardless of
    # the model dtype — the bitwise training oracle the fidelity floor
    # in `autotune_plan.py --train_precision` is judged against.
    # Resolution + validation: train/state.resolve_train_dtype.
    compute_dtype: Optional[str] = None
    # Dynamic loss-scaling knobs (mixed builds only — a float32 trace
    # never references them). The loss is multiplied by the running
    # scale before the backward pass and the grads divided after it;
    # a non-finite grad tree skips the update through the SAME select
    # as finite_guard (the step counts into `skipped_steps`), multiplies
    # the scale by `loss_scale_backoff` (clamped at `loss_scale_floor`),
    # and `loss_scale_growth_interval` consecutive good steps multiply
    # it by `loss_scale_growth`.
    loss_scale_init: float = 32768.0
    loss_scale_growth: float = 2.0
    loss_scale_backoff: float = 0.5
    loss_scale_growth_interval: int = 200
    loss_scale_floor: float = 1.0
    # Rematerialization policy for the epoch-scan backward pass
    # ("none" | "dots" | "full", train/loop.py). "none" is the exact
    # pre-remat graph; "dots" wraps the day loss in jax.checkpoint
    # keeping matmul results (recompute the cheap elementwise chain);
    # "full" recomputes everything. Peak-HBM win measured per jit by
    # `bench.py --mixed` via obs.compile.capture_compile. Plan-raced
    # since PR 19: `autotune_plan.py --remat` persists a winning rung
    # (incl. rungs that win by admitting a doubled days_per_step) into
    # the plan row, and apply_plan sets this knob from it.
    remat: str = "none"


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout.

    Axes: 'data' shards trading days (gradient all-reduce over ICI);
    'stock' shards the cross-section (masked-softmax/portfolio reductions
    become psum collectives) — the TPU analogue of sequence/context
    parallelism for this model family, where the long axis is the stock
    universe, not time (SURVEY.md §5).
    """

    data_axis: int = -1   # -1: use all remaining devices
    stock_axis: int = 1

    def shape(self, n_devices: int) -> tuple:
        stock = max(1, self.stock_axis)
        if n_devices % stock != 0:
            raise ValueError(f"{n_devices} devices not divisible by stock axis {stock}")
        data = self.data_axis if self.data_axis > 0 else n_devices // stock
        if data * stock != n_devices:
            raise ValueError(
                f"mesh {data}x{stock} != {n_devices} devices")
        return (data, stock)


@dataclass(frozen=True)
class Config:
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)

    # ---- serialization ----------------------------------------------------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self, **kw: Any) -> str:
        return json.dumps(self.to_dict(), **kw)

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        def _load(tp, sub):
            known = {f.name for f in dataclasses.fields(tp)}
            return tp(**{k: v for k, v in (sub or {}).items() if k in known})

        return cls(
            model=_load(ModelConfig, d.get("model")),
            data=_load(DataConfig, d.get("data")),
            train=_load(TrainConfig, d.get("train")),
            mesh=_load(MeshConfig, d.get("mesh")),
        )

    @classmethod
    def from_json(cls, s: str) -> "Config":
        return cls.from_dict(json.loads(s))

    def checkpoint_name(self) -> str:
        """Parameter-encoding checkpoint name.

        Same scheme as the reference filename
        ``{run_name}_factor_{K}_hdn_{H}_port_{M}_seed_{seed}`` (main.py:78).
        """
        return (
            f"{self.train.run_name}_factor_{self.model.num_factors}"
            f"_hdn_{self.model.hidden_size}_port_{self.model.num_portfolios}"
            f"_seed_{self.train.seed}"
        )

    def score_name(self) -> str:
        """Score-CSV naming scheme from the reference scores/readme.md:2-8:
        ``{run_name}_{num_factor}_{normalize}_{select_feature}_{num_latent}_{hidden_size}``.
        """
        sel = (
            "None"
            if self.data.select_feature is None
            else str(len(self.data.select_feature))
        )
        return (
            f"{self.train.run_name}_{self.model.num_factors}_{self.data.normalize}"
            f"_{sel}_{self.model.num_features}_{self.model.hidden_size}"
        )
