"""Seed- and CONFIG-parallel fleet training: S independent models in one
program.

The round-2 trace shows the chip ~93% idle at MFU 7.1%: every FactorVAE
matmul is launch/tile-bound because the contraction dims (158/64/96)
under-fill the 128x128 MXU (PERF.md), while the evaluation protocol
(statistical parity across seeds, eval/sweep.py) needs MANY independent
trainings that the serial path runs one after another — each paying its
own compile, dispatch tail and scoring pass. Batching S seeds into one
jitted program fattens every matmul S-fold with ZERO cross-model
communication: the `TrainState` is stacked along a leading seed axis
(vmapped init -> stacked params/opt_state/rng) and the existing
`train_epoch` / `eval_epoch` scan bodies (train/loop.py) are vmapped
over that axis with the HBM panel held broadcast — one copy, not S.

Semantics contract (tests/test_fleet.py):
- Each seed's trajectory is the INDEPENDENT trajectory its solo run
  produces: per-seed init keys, per-seed threaded RNG, per-seed shuffled
  day order per epoch, per-seed eval keys. vmap reassociates the matmul
  reductions, so S>1 rows match their solo runs at f32 tolerance, not
  bitwise.
- S=1 is the equality oracle: the fleet compiles the UN-vmapped epoch
  functions (vmap buys nothing at S=1 and its batched-dot reassociation
  would break the bitwise contract), so a single-seed fleet reproduces
  the serial `Trainer` bit-for-bit — params, metrics, best-val
  selection.
- Best-validation tracking runs ON DEVICE per seed: best epochs differ
  across seeds, so after every epoch a `jnp.where`-select snapshots the
  improved seeds' params into the stacked best-params buffer (the
  device-side analogue of trainer.py's `improved` branch).
- Checkpoints unstack per seed under the SAME per-seed names the serial
  path writes (`Config.checkpoint_name()` encodes the seed), so
  `seed_sweep`'s best-val selection rule and resume semantics are
  preserved: a fleet-trained sweep leaves artifacts a serial run (or a
  serial resume) can consume.

Hyper-fleet (ISSUE 12): the seed axis generalizes to a CONFIG axis.
``lane_configs`` hands each lane its own Config, where per-lane SCALAR
hyperparameters — ``train.lr`` and ``model.kl_weight`` — become f32
runtime inputs of one compiled program ((S,) vectors riding the stacked
state's axis; train/loop.py `hyper_step_size`, state.py
`make_hyper_optimizer`), so a whole (lr x kl_weight) sweep shares ONE
compile and every lane's artifacts land under its own lane-config names.
Shape-changing variants (K/H) do NOT ride this axis — `eval/sweep.py
grid_sweep` buckets them into per-shape programs, the same way the serve
daemon buckets requests by (arch, dtype, days).

Hyper bitwise discipline (tests/test_hyper.py):
- Lanes whose scalars are ALL IDENTICAL fold to the exact pre-hyper
  trace: the scalars are rebaked into the base config and the PR-2 path
  compiles, so a homogeneous "hyper" fleet IS the seed fleet (and S=1 IS
  the serial Trainer) — bitwise by construction, pinned.
- A heterogeneous lane is BITWISE lane i of a same-width homogeneous
  hyper fleet pinned at that lane's config: the runtime-scalar threading
  adds ZERO numeric drift on top of the established vmap semantics.
  Against the serial Trainer at that config it inherits the PR-2 fleet's
  f32 tolerance (vmap batches the matmuls; reassociation, not hyper, is
  the gap — S>1 seed lanes have never been bitwise vs solo).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from factorvae_tpu.config import Config
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.models.factorvae import day_forward
from factorvae_tpu.parallel import compose, partition
from factorvae_tpu.parallel.sharding import (
    chunk_placement,
    make_batch_constraint,
    order_sharding,
    panel_shardings,
    replicated,
    shard_dataset,
)
from factorvae_tpu.train.checkpoint import (
    Checkpointer,
    CheckpointIntegrityError,
    save_params,
)
from factorvae_tpu.train.loop import concat_auxes, make_step_fns
from factorvae_tpu.train.state import (
    TrainState,
    create_train_state,
    learning_rate_at,
    make_hyper_optimizer,
    make_optimizer,
    resolve_train_dtype,
)
from factorvae_tpu.utils.logging import (
    MetricsLogger,
    timeline_event,
    timeline_span,
)


#: per-lane Config fields a hyper fleet may vary — lr/kl_weight ride the
#: stacked program as runtime scalars; seed is the established lane axis;
#: run_name/save_dir only rename the per-lane artifacts (grid_sweep tags
#: each point's run_name so same-seed lanes can't collide on disk).
LANE_TRAIN_FIELDS = frozenset({"lr", "seed", "run_name", "save_dir"})
LANE_MODEL_FIELDS = frozenset({"kl_weight"})


def validate_lane_configs(base: Config, lane_configs: Sequence[Config]):
    """Reject a lane set one compiled program cannot carry: every field
    OUTSIDE the lane-varying sets must equal the base config's — a K/H
    (shape) variant belongs in a different shape bucket (grid_sweep),
    not on the lane axis — and every lane must write distinct artifacts
    (`checkpoint_name()` collision = same run_name+seed racing two
    scalar configs into one directory)."""
    for i, c in enumerate(lane_configs):
        for f in dataclasses.fields(c.model):
            if f.name in LANE_MODEL_FIELDS:
                continue
            if getattr(c.model, f.name) != getattr(base.model, f.name):
                raise ValueError(
                    f"lane {i} varies model.{f.name}: shape/arch fields "
                    "cannot ride the lane axis of one compiled program — "
                    "bucket per shape (eval.sweep.grid_sweep) instead")
        for f in dataclasses.fields(c.train):
            if f.name in LANE_TRAIN_FIELDS:
                continue
            if getattr(c.train, f.name) != getattr(base.train, f.name):
                if f.name == "compute_dtype":
                    raise ValueError(
                        f"lane {i} varies train.compute_dtype: the "
                        "compute dtype changes the TRACE (cast + "
                        "loss-scale graph), so it buckets like a shape "
                        "— grid_sweep races f32 vs bf16 as separate "
                        "shape buckets, not lanes")
                raise ValueError(
                    f"lane {i} varies train.{f.name}: only "
                    f"{sorted(LANE_TRAIN_FIELDS)} may differ per lane")
        if c.data != base.data:
            raise ValueError(
                f"lane {i} varies the data config: lanes share one "
                "panel/splits by construction")
    names = [(c.train.save_dir, c.checkpoint_name()) for c in lane_configs]
    dup = {n for n in names if names.count(n) > 1}
    if dup:
        raise ValueError(
            "lane checkpoint paths collide (same save_dir+run_name+seed "
            f"across different lane configs): {sorted(dup)}; tag each "
            "lane's run_name or save_dir (grid_sweep tags run_name per "
            "point)")


def lane_label(cfg: Config, hyper: bool) -> str:
    """Short human label for one lane, used by obs.report/obs.live flag
    details and the Prometheus `lane_config` label: the config that
    diverged, not just the lane index."""
    if not hyper:
        return f"seed={cfg.train.seed}"
    from factorvae_tpu.utils.logging import config_hash

    return (f"seed={cfg.train.seed} lr={cfg.train.lr:g} "
            f"klw={cfg.model.kl_weight:g} "
            f"cfg={config_hash(cfg.to_dict())[:8]}")


def stack_states(states: Sequence[TrainState]) -> TrainState:
    """Stack per-seed TrainStates along a new leading seed axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def unstack_state(fleet_state, i: int):
    """Extract seed row `i` from a stacked fleet state (or any stacked
    pytree — params trees work too)."""
    return jax.tree.map(lambda x: x[i], fleet_state)


def _bcast(flags: jnp.ndarray, leaf: jnp.ndarray) -> jnp.ndarray:
    """(S,) bool -> broadcastable against an (S, ...) leaf."""
    return flags.reshape(flags.shape + (1,) * (leaf.ndim - 1))


@jax.jit
def select_best(best_params, best_val, params, selection_loss):
    """Per-seed on-device best-val snapshot: where seed s improved
    (selection_loss[s] < best_val[s], the serial Trainer's strict-`<`
    rule), adopt its current params into the stacked best buffer. A pure
    select — no numeric drift vs the serial host-side branch."""
    improved = selection_loss < best_val
    new_best_val = jnp.where(improved, selection_loss, best_val)
    new_best = jax.tree.map(
        lambda b, p: jnp.where(_bcast(improved, p), p, b), best_params, params
    )
    return new_best, new_best_val


class FleetTrainer:
    """Train S seeds of one Config simultaneously in one jitted program.

    `config.train.seed` is ignored; `seeds` names the fleet. Since PR 6
    the seed axis COMPOSES with a device mesh (`mesh=...`): the stacked
    (S, ...) TrainState shards its seed lanes over the 'data' mesh axis
    and the cross-section over 'stock', per the partition-rule tables
    (parallel/partition.py, docs/sharding.md) — S/dp independent seeds
    per data slice, zero cross-seed collectives. S=1 on a mesh compiles
    the serial Trainer's sharded program (the bitwise oracle chain:
    S=1 x 1x1 mesh == serial Trainer exactly).
    """

    def __init__(
        self,
        config: Config,
        dataset: PanelDataset,
        seeds: Optional[Sequence[int]] = None,
        logger: Optional[MetricsLogger] = None,
        mesh: Optional[object] = None,
        lane_configs: Optional[Sequence[Config]] = None,
        force_hyper: bool = False,
    ):
        """``seeds`` names a classic seed fleet (every lane = `config`
        at that seed). ``lane_configs`` (mutually exclusive) names a
        HYPER fleet: one Config per lane, varying only the lane fields
        (`validate_lane_configs`). Lanes whose (lr, kl_weight) are all
        identical FOLD: the scalars are rebaked into the base config and
        the pre-hyper trace compiles — a homogeneous hyper fleet is
        bitwise the seed fleet (and S=1 bitwise the serial Trainer) by
        construction. ``force_hyper=True`` keeps the runtime-scalar
        trace even for (S>1) homogeneous lanes — the PBT loop
        (train/pbt.py) perturbs scalars BETWEEN generations of one
        compiled program, and the bitwise-oracle tests pin the hyper
        trace against the folded one."""
        if lane_configs is not None:
            if seeds is not None:
                raise ValueError(
                    "pass seeds OR lane_configs, not both (lane configs "
                    "carry their own train.seed)")
            lane_cfgs = list(lane_configs)
            if not lane_cfgs:
                raise ValueError("empty fleet: need at least one lane")
            validate_lane_configs(config, lane_cfgs)
        else:
            if seeds is None or len(seeds) == 0:
                raise ValueError("empty fleet: need at least one seed")
            if len(set(int(s) for s in seeds)) != len(seeds):
                raise ValueError(f"duplicate seeds in fleet: {list(seeds)}")
            lane_cfgs = [
                dataclasses.replace(
                    config,
                    train=dataclasses.replace(config.train, seed=int(s)))
                for s in seeds
            ]
        scalars = {(c.train.lr, c.model.kl_weight) for c in lane_cfgs}
        self.hyper = len(lane_cfgs) > 1 and (len(scalars) > 1
                                             or bool(force_hyper))
        if not self.hyper and lane_configs is not None:
            # Homogeneous fold: rebake the single scalar pair into the
            # base config so the compiled trace is EXACTLY the pre-hyper
            # seed-fleet (or, at S=1, serial-Trainer) program.
            lr, klw = next(iter(scalars))
            config = dataclasses.replace(
                config,
                model=dataclasses.replace(config.model, kl_weight=klw),
                train=dataclasses.replace(config.train, lr=lr),
            )
        self.cfg = config
        self.ds = dataset
        self.lane_cfgs = lane_cfgs
        self.seeds = [int(c.train.seed) for c in lane_cfgs]
        self.num_seeds = len(self.seeds)
        self.logger = logger or MetricsLogger(echo=False)
        self.mesh = mesh
        compose.validate(
            mesh=mesh,
            num_seeds=self.num_seeds,
            residency=getattr(dataset, "residency", "hbm"),
            days_per_step=max(1, config.train.days_per_step),
            stream_chunk_days=config.data.stream_chunk_days,
            hyper=self.hyper,
        )
        if mesh is not None:
            # HBM panels re-place onto the mesh once; stream datasets
            # round-trip as a no-op (per-chunk placement instead).
            shard_dataset(mesh, dataset)

        self.train_days = dataset.split_days(
            config.data.start_time, config.data.fit_end_time
        )
        self.val_days = dataset.split_days(
            config.data.val_start_time, config.data.val_end_time
        )
        if len(self.train_days) == 0:
            raise ValueError("empty training split")

        self.batch_days = max(1, config.train.days_per_step)
        self.steps_per_epoch = -(-len(self.train_days) // self.batch_days)
        self.total_steps = self.steps_per_epoch * config.train.num_epochs

        # Streaming residency (plan.panel_residency="stream"): per-seed
        # mini-panels ride one prefetched chunk stream; the vmapped
        # chunk fns consume them through the same device gather.
        self.stream = getattr(dataset, "residency", "hbm") == "stream"
        self.steps_per_chunk = max(
            1, config.data.stream_chunk_days // self.batch_days)

        # Training compute dtype, resolved through the ONE ladder
        # (train/state.py): bf16 lanes train the mixed master-weight
        # path, never the naive whole-model cast. The dtype is
        # trace-baked — lanes cannot vary it (validate_lane_configs);
        # grid_sweep buckets dtypes like shapes instead.
        self._train_dtype = resolve_train_dtype(config.train, config.model)
        self._mixed = self._train_dtype != "float32"
        model_cfg = config.model
        if model_cfg.compute_dtype != self._train_dtype:
            model_cfg = dataclasses.replace(
                model_cfg, compute_dtype=self._train_dtype)
        self.model = day_forward(model_cfg, train=True)
        self.model_eval = day_forward(model_cfg, train=False)
        self._build_step_fns()

        self.logger.log(
            "fleet_execution_layout",
            seeds=self.seeds,
            seeds_per_program=self.num_seeds,
            hyper=self.hyper,
            lane_labels=self.lane_labels(),
            flatten_days=config.model.flatten_days,
            days_per_step=self.batch_days,
            compute_dtype=self._train_dtype,
            model_compute_dtype=config.model.compute_dtype,
            mixed_precision=self._mixed,
            n_real=getattr(dataset, "n_real", dataset.n_max),
            n_padded=dataset.n_max,
            obs_probes=config.train.obs_probes,
        )
        if mesh is not None:
            # Rule-table shard-balance bill for the state the epoch
            # loop actually CARRIES + the 'stock'-sharded panel — the
            # per-device byte story of docs/sharding.md, measured from
            # abstract shapes at construction (obs/memory.py). At S=1
            # the run state is the UNSTACKED serial state on replicated
            # shardings (init_run_state), so the bill must drop the
            # seed axis too — billing a 1-long seed dim over a >1
            # 'data' axis would report a maximal FALSE imbalance from
            # the very diagnostic meant to catch real ones. Guarded:
            # telemetry must never abort the run it observes.
            try:
                from factorvae_tpu.obs.memory import shard_balance_block

                abstract = jax.eval_shape(self.init_fleet_state)
                if self.num_seeds == 1:
                    abstract = jax.tree.map(
                        lambda s: jax.ShapeDtypeStruct(s.shape[1:],
                                                       s.dtype), abstract)
                self.logger.log("shard_balance", **shard_balance_block(
                    mesh, state=abstract, dataset=dataset,
                    stacked=self.num_seeds > 1))
            except Exception as e:
                self.logger.log("shard_balance", error=str(e))

    # ------------------------------------------------------------------

    def _build_step_fns(self) -> None:
        """(Re)build optimizer + jitted fleet-epoch fns for the current
        `self.total_steps` (same cosine-horizon contract as
        Trainer._build_step_fns). Under a mesh, every in_sharding is
        resolved from the partition-rule tables (parallel/partition.py):
        stacked states/orders/keys ride the seed ('data') axis, the
        panel — whole or per-chunk mini — rides 'stock'."""
        from factorvae_tpu import chaos

        cfg = self.cfg
        mesh = self.mesh
        # Hyper trace: Adam with the lr multiply deferred to a runtime
        # per-lane scalar (state.make_hyper_optimizer — same opt-state
        # tree as make_optimizer, so per-lane checkpoints stay
        # serial-compatible). The folded/classic paths keep the exact
        # pre-hyper optimizer.
        self._hyper_step_size = None
        if self.hyper:
            self.tx, self._hyper_step_size = make_hyper_optimizer(
                cfg.train, self.total_steps)
        else:
            self.tx = make_optimizer(cfg.train, self.total_steps)
        # Trace-time chaos gate (same rule as the serial Trainer): the
        # poison argument exists only on builds made under an installed
        # nan_grads fault plan; per-LANE on the vmapped path, so one bad
        # seed skips its update while the others train on.
        self._inject = chaos.has_fault("nan_grads")
        # S=1 keeps the serial Trainer's exact step graph — including,
        # on a mesh, its in-step batch constraint — so the single-seed
        # fleet stays bitwise the serial Trainer mesh path. The vmapped
        # S>1 path carries no in-step constraint: input shardings plus
        # GSPMD propagation place the batched graph.
        shard_batch = (make_batch_constraint(mesh)
                       if mesh is not None and self.num_seeds == 1
                       else None)
        self.fns = make_step_fns(
            self.model, self.model_eval, self.tx, cfg.data.seq_len,
            shard_batch=shard_batch, obs=cfg.train.obs_probes,
            guard=cfg.train.finite_guard, inject_nan=self._inject,
            hyper_step_size=self._hyper_step_size,
            compute_dtype=self._train_dtype,
            loss_scale_cfg=((cfg.train.loss_scale_growth,
                             cfg.train.loss_scale_backoff,
                             cfg.train.loss_scale_growth_interval,
                             cfg.train.loss_scale_floor)
                            if self._mixed else None),
            remat=cfg.train.remat,
        )
        from factorvae_tpu.obs.watchdog import watch_jit

        self._chunk_placement = None
        self._eval_chunk_placement = None
        if mesh is not None:
            rep = replicated(mesh)
            pan_s = panel_shardings(mesh)
        # Chaos traces carry one extra poison argument on the train
        # entry points: a replicated scalar on the serial path, an
        # (S,)-per-lane vector riding the seed axis on the vmapped one.
        extra = (replicated(mesh),) if (self._inject and mesh is not None
                                        ) else ()
        if self.num_seeds == 1:
            # Bitwise-oracle path: identical jits to the serial Trainer
            # (mesh or not).
            if mesh is not None:
                ord_s = order_sharding(mesh)
                self._train_epoch_jit = watch_jit(jax.jit(
                    self.fns.train_epoch, donate_argnums=(0,),
                    in_shardings=(rep, ord_s, pan_s) + extra,
                    out_shardings=(rep, rep)), "fleet_train_epoch")
                self._eval_epoch_jit = watch_jit(jax.jit(
                    self.fns.eval_epoch,
                    in_shardings=(rep, ord_s, rep, pan_s),
                    out_shardings=rep), "fleet_eval_epoch")
            else:
                self._train_epoch_jit = watch_jit(jax.jit(
                    self.fns.train_epoch, donate_argnums=(0,)),
                    "fleet_train_epoch")
                self._eval_epoch_jit = watch_jit(
                    jax.jit(self.fns.eval_epoch), "fleet_eval_epoch")
            if self.stream:
                chunk_kw = {}
                eval_chunk_kw = {}
                if mesh is not None:
                    ord_s = order_sharding(mesh)
                    chunk_kw = dict(in_shardings=(rep, ord_s, pan_s)
                                    + extra,
                                    out_shardings=(rep, rep))
                    eval_chunk_kw = dict(
                        in_shardings=(rep, ord_s, rep, pan_s),
                        out_shardings=rep)
                    self._chunk_placement = chunk_placement(mesh)
                self._train_chunk_jit = watch_jit(jax.jit(
                    self.fns.train_chunk, donate_argnums=(0,), **chunk_kw),
                    "fleet_train_chunk")
                # Donation parity with the serial Trainer (ISSUE 16
                # audit): the threaded eval key is rebound every chunk
                # and the finalize aux is dead after the reduce.
                self._eval_chunk_jit = watch_jit(
                    jax.jit(self.fns.eval_chunk, donate_argnums=(2,),
                            **eval_chunk_kw),
                    "fleet_eval_chunk")
                self._finalize_train_jit = watch_jit(
                    jax.jit(self.fns.finalize_train, donate_argnums=(0,)),
                    "fleet_finalize_train")
                self._finalize_eval_jit = watch_jit(
                    jax.jit(self.fns.finalize_eval, donate_argnums=(0,)),
                    "fleet_finalize_eval")
        else:
            # Panel broadcast (in_axes=None): ONE HBM copy serves every
            # seed; state and day orders carry the seed axis.
            jit_kw = {}
            eval_kw = {}
            chunk_kw = {}
            eval_chunk_kw = {}
            if mesh is not None:
                # Partition-rule-resolved shardings for the STACKED
                # program: seed lanes over 'data', cross-section over
                # 'stock', day-batches over 'host' when the mesh has
                # one (partition.day_batch_axes).
                abstract = jax.eval_shape(self.init_fleet_state)
                state_sh = partition.named(mesh, partition.
                                           state_partition_specs(
                                               abstract, stacked=True))
                self._state_shardings = state_sh
                ord_sh = partition.named(
                    mesh, partition.order_partition_spec(mesh,
                                                         stacked=True))
                keys_sh = partition.named(
                    mesh, partition.eval_keys_partition_spec())
                val_ord_sh = partition.named(
                    mesh, partition.eval_order_partition_spec(
                        mesh, stacked=True))
                # out_shardings are pinned to the SAME rule-table specs
                # (a seed-axis prefix for the (S,)-leading metric/aux
                # trees): without the pin GSPMD may re-shard an output
                # leaf (e.g. a stacked bias onto ('data','stock')),
                # which then mismatches the next call's explicit
                # in_shardings — the state is a carried value, so its
                # placement must be a fixed point of the epoch jit.
            # Per-lane poison vector on chaos traces: vmapped over the
            # seed axis like the state/orders ((S,) sharded seed_pref
            # under a mesh).
            inject = self._inject
            hyper = self.hyper
            if mesh is not None:
                seed_pref = partition.named(
                    mesh, jax.sharding.PartitionSpec(partition.SEED_AXIS))
                # Trailing trace-gated args, hp FIRST (loop._split_extras):
                # the hp dict's (S,) lr/kl_weight vectors ride the seed
                # axis like every other per-lane leaf (seed_pref as a
                # prefix pytree), poison likewise.
                hyper_extra = (seed_pref,) if hyper else ()
                stacked_extra = (seed_pref,) if inject else ()
                jit_kw = dict(in_shardings=(state_sh, ord_sh, pan_s)
                              + hyper_extra + stacked_extra,
                              out_shardings=(state_sh, seed_pref))
                eval_kw = dict(in_shardings=(state_sh.params, val_ord_sh,
                                             keys_sh, pan_s) + hyper_extra,
                               out_shardings=seed_pref)
                pan_stacked = tuple(
                    partition.named(mesh, s)
                    for s in partition.panel_partition_specs(stacked=True))
                chunk_kw = dict(
                    in_shardings=(state_sh, ord_sh, pan_stacked)
                    + hyper_extra + stacked_extra,
                    out_shardings=(state_sh, seed_pref))
                eval_chunk_kw = dict(
                    in_shardings=(state_sh.params, val_ord_sh, keys_sh,
                                  pan_s) + hyper_extra,
                    out_shardings=seed_pref)
            hyp_ax = (0,) if hyper else ()
            inj_ax = (0,) if inject else ()
            train_axes = (0, 0, None) + hyp_ax + inj_ax
            self._train_epoch_jit = watch_jit(jax.jit(
                jax.vmap(self.fns.train_epoch, in_axes=train_axes),
                donate_argnums=(0,), **jit_kw,
            ), "fleet_train_epoch")
            # params/key are per-seed; the validation order is shared
            # (shuffle=False, seed 0 — identical across seeds).
            self._eval_epoch_jit = watch_jit(jax.jit(
                jax.vmap(self.fns.eval_epoch,
                         in_axes=(0, None, 0, None) + hyp_ax),
                **eval_kw,
            ), "fleet_eval_epoch")
            if self.stream:
                # Train mini-panels are PER-SEED (each seed shuffles its
                # own day order, so its chunk gathers different slabs);
                # the shared validation order keeps one broadcast panel.
                # Under a mesh the stacked mini-panels shard
                # (seed, stock, ...) and ship per-shard slabs
                # (chunk_placement(stacked=True)).
                if mesh is not None:
                    self._chunk_placement = chunk_placement(mesh,
                                                            stacked=True)
                    self._eval_chunk_placement = chunk_placement(
                        mesh, order_spec=partition.
                        eval_order_partition_spec(mesh, stacked=True))
                chunk_axes = (0, 0, 0) + hyp_ax + inj_ax
                self._train_chunk_jit = watch_jit(jax.jit(
                    jax.vmap(self.fns.train_chunk, in_axes=chunk_axes),
                    donate_argnums=(0,), **chunk_kw,
                ), "fleet_train_chunk")
                # Same donation audit as the S=1 path: per-seed keys are
                # rebound each chunk, finalize auxes die at the reduce.
                self._eval_chunk_jit = watch_jit(jax.jit(
                    jax.vmap(self.fns.eval_chunk,
                             in_axes=(0, None, 0, None) + hyp_ax),
                    donate_argnums=(2,), **eval_chunk_kw,
                ), "fleet_eval_chunk")
                self._finalize_train_jit = watch_jit(jax.jit(
                    jax.vmap(self.fns.finalize_train),
                    donate_argnums=(0,)), "fleet_finalize_train")
                self._finalize_eval_jit = watch_jit(jax.jit(
                    jax.vmap(self.fns.finalize_eval),
                    donate_argnums=(0,)), "fleet_finalize_eval")

    def panel_args(self):
        return (self.ds.values, self.ds.last_valid, self.ds.next_valid)

    # ------------------------------------------------------------------

    def init_fleet_state(self) -> TrainState:
        """Vmapped seeded init: each seed reproduces the serial
        `Trainer.init_state` key schedule (PRNGKey(seed) split 3 ways)
        exactly — vmapped threefry is elementwise per key, so the stacked
        init is bitwise the per-seed serial inits (tested)."""
        cfg = self.cfg
        b, n = self.batch_days, self.ds.n_max
        # f32 init dummies, matching Trainer.init_state: param init must
        # not depend on the plan's compute dtype
        x = jnp.zeros((b, n, cfg.data.seq_len, cfg.model.num_features),
                      jnp.float32)
        y = jnp.zeros((b, n), jnp.float32)
        mask = jnp.ones((b, n), bool)

        def init_one(seed):
            key = jax.random.PRNGKey(seed)
            k_param, k_sample, k_drop = jax.random.split(key, 3)
            params = self.model.init(
                {"params": k_param, "sample": k_sample, "dropout": k_drop},
                x, y, mask,
            )
            return create_train_state(params, self.tx, seed,
                                      train_cfg=cfg.train,
                                      compute_dtype=self._train_dtype)

        seeds = jnp.asarray(self.seeds, jnp.uint32)
        # graftlint: disable=JGL003 init traces once per fit by design — it closes over the (unhashable) model/tx, and its cost is one S-wide init vs hours of training
        return jax.jit(jax.vmap(init_one))(seeds)

    def _epoch_orders(self, epoch: int) -> jnp.ndarray:
        """(S, steps, B) stacked day orders — each seed shuffles with its
        OWN seed, matching its solo run's epoch stream."""
        cfg = self.cfg
        orders = [
            self.ds.epoch_order(
                self.train_days, shuffle=True, seed=s, epoch=epoch,
                pad_to=self.batch_days,
            ).reshape(-1, self.batch_days)
            for s in self.seeds
        ]
        return jnp.asarray(np.stack(orders))

    def _val_order(self):
        if len(self.val_days) == 0:
            return None
        order = self.ds.epoch_order(
            self.val_days, shuffle=False, seed=0, epoch=0,
            pad_to=self.batch_days,
        ).reshape(-1, self.batch_days)
        return jnp.asarray(order)

    def _eval_keys(self, epoch: int) -> jax.Array:
        """(S, key) per-seed eval keys, bitwise the serial
        fold_in(PRNGKey(seed + 1), epoch) stream."""
        seeds = jnp.asarray(self.seeds, jnp.uint32)
        return jax.vmap(
            lambda s: jax.random.fold_in(jax.random.PRNGKey(s + 1), epoch)
        )(seeds)

    # ------------------------------------------------------------------
    # The "run state" is the representation the epoch loop carries:
    # the stacked fleet state at S>1, the RAW TrainState at S==1 — the
    # serial layout, so the S=1 oracle (and the raced S=1 baseline in
    # autotune/bench) pays exactly what the serial Trainer pays: no
    # per-epoch stack/unstack dispatches. Stacking happens only at
    # boundaries (init/restore/checkpoint/return).

    def init_run_state(self) -> TrainState:
        state = self.init_fleet_state()
        state = state if self.num_seeds > 1 else unstack_state(state, 0)
        return self._place_run_state(state)

    def _stacked(self, run_state):
        """Stacked (S, ...) view of a run state, for the per-seed
        unstack consumers (checkpoints, the returned fleet state)."""
        if self.num_seeds > 1:
            return run_state
        return jax.tree.map(lambda x: x[None], run_state)

    def _poison(self, epoch: int) -> tuple:
        """() on chaos-free builds; one poison arg on injecting builds —
        NaN on the lanes a `nan_grads` fault targets this epoch (each
        lane consumes its own firing; a lane=-1 wildcard with times>1
        or times=-1 poisons several), exact 1.0 elsewhere."""
        if not self._inject:
            return ()
        from factorvae_tpu import chaos

        vals = [float("nan")
                if chaos.fault("nan_grads", epoch=epoch, lane=i) is not None
                else 1.0 for i in range(self.num_seeds)]
        if self.num_seeds == 1:
            return (jnp.float32(vals[0]),)
        return (jnp.asarray(vals, jnp.float32),)

    def _hp_args(self) -> tuple:
        """() on non-hyper traces; one (S,)-vector hp dict on hyper
        traces — rebuilt from `self.lane_cfgs` at every call so PBT's
        between-generation perturbations (set_lane_scalars) reach the
        SAME compiled program as fresh runtime values."""
        if not self.hyper:
            return ()
        return ({
            "lr": jnp.asarray([c.train.lr for c in self.lane_cfgs],
                              jnp.float32),
            "kl_weight": jnp.asarray(
                [c.model.kl_weight for c in self.lane_cfgs], jnp.float32),
        },)

    def lane_labels(self) -> list:
        """Per-lane config labels (obs satellite, ISSUE 12): alerts and
        Prometheus lanes name the CONFIG that diverged, not just the
        lane index."""
        return [lane_label(c, self.hyper) for c in self.lane_cfgs]

    def set_lane_scalars(self, lane: int, lr: Optional[float] = None,
                         kl_weight: Optional[float] = None) -> None:
        """PBT explore step: replace one lane's runtime scalars. Values
        are runtime inputs of the compiled hyper program (`_hp_args`),
        so the next epoch call picks them up with ZERO retrace; lane
        artifacts keep their names (checkpoint_name encodes run_name +
        seed, not the scalars)."""
        if not self.hyper:
            raise ValueError(
                "set_lane_scalars needs the hyper trace (construct with "
                "lane_configs and force_hyper=True for an initially "
                "homogeneous population)")
        c = self.lane_cfgs[lane]
        self.lane_cfgs[lane] = dataclasses.replace(
            c,
            model=dataclasses.replace(
                c.model, kl_weight=(c.model.kl_weight if kl_weight is None
                                    else float(kl_weight))),
            train=dataclasses.replace(
                c.train, lr=(c.train.lr if lr is None else float(lr))),
        )

    def _run_train_epoch(self, run_state, epoch):
        orders = self._epoch_orders(epoch)
        hp = self._hp_args()
        poison = self._poison(epoch)
        if self.stream:
            return self._stream_train_epoch(run_state, orders, hp + poison)
        if self.num_seeds == 1:
            st, m = self._train_epoch_jit(
                run_state, orders[0], self.panel_args(), *poison)
            return st, {k: v[None] for k, v in m.items()}
        return self._train_epoch_jit(run_state, orders, self.panel_args(),
                                     *hp, *poison)

    def _run_eval_epoch(self, run_params, val_order, epoch):
        keys = self._eval_keys(epoch)
        if self.stream:
            return self._stream_eval_epoch(run_params, val_order, keys)
        if self.num_seeds == 1:
            m = self._eval_epoch_jit(
                run_params, val_order, keys[0], self.panel_args())
            return {k: v[None] for k, v in m.items()}
        return self._eval_epoch_jit(run_params, val_order, keys,
                                    self.panel_args(), *self._hp_args())

    # ---- streaming residency -----------------------------------------

    def _stream_train_epoch(self, run_state, orders, extras: tuple = ()):
        """Chunked stream fleet epoch: per-seed mini-panels (each seed's
        shuffled order gathers different slabs) stacked into one
        prefetched chunk, consumed by the vmapped chunk scan. S=1 runs
        the serial chunk fns on the raw state — the bitwise oracle.
        `extras` is the trace-gated trailing-arg tuple (hp on hyper
        builds first, then poison on chaos builds; at S=1 only poison
        can exist — single-lane fleets always fold to the serial
        trace)."""
        from factorvae_tpu.data.stream import (
            ChunkStream,
            chunk_slices,
            stream_epoch_batches,
        )
        from factorvae_tpu.data.windows import chunk_mini_panel

        parts = []
        if self.num_seeds == 1:
            chunks = stream_epoch_batches(
                self.ds, np.asarray(orders[0]), self.steps_per_chunk,
                placement=self._chunk_placement)
            for order_local, panel_chunk in chunks:
                run_state, aux = self._train_chunk_jit(
                    run_state, order_local, panel_chunk, *extras)
                parts.append(aux)
            self.last_stream_stats = chunks
            m = self._finalize_train_jit(concat_auxes(parts))
            return run_state, {k: v[None] for k, v in m.items()}

        orders_np = np.asarray(orders, np.int32)   # (S, steps, B)
        s, steps, b = orders_np.shape
        slices = chunk_slices(steps, self.steps_per_chunk)

        def make_chunk(i):
            lo, hi = slices[i]
            rows = [chunk_mini_panel(
                self.ds.values_np, self.ds.last_valid_np,
                self.ds.next_valid_np, orders_np[j, lo:hi].reshape(-1),
                self.ds.seq_len) for j in range(s)]
            order_local = np.stack(
                [r[0].reshape(hi - lo, b) for r in rows])
            panel = tuple(np.stack([r[k] for r in rows])
                          for k in (1, 2, 3))
            return order_local, panel

        chunks = ChunkStream(make_chunk, len(slices),
                             placement=self._chunk_placement)
        for order_local, panel_chunk in chunks:
            run_state, aux = self._train_chunk_jit(
                run_state, order_local, panel_chunk, *extras)
            parts.append(aux)
        self.last_stream_stats = chunks
        return run_state, self._finalize_train_jit(
            concat_auxes(parts, 1))

    def _stream_eval_epoch(self, run_params, val_order, keys):
        """Shared validation order -> ONE broadcast mini-panel per chunk;
        keys thread across chunks per seed, preserving the whole-epoch
        key stream."""
        from factorvae_tpu.data.stream import stream_epoch_batches

        serial = self.num_seeds == 1
        chunks = stream_epoch_batches(
            self.ds, np.asarray(val_order), self.steps_per_chunk,
            placement=(self._chunk_placement if serial
                       else self._eval_chunk_placement))
        key = keys[0] if serial else keys
        # hyper traces take the per-lane hp dict on the eval chunk too
        # (the selection loss recomposes with the lane kl_weight); at
        # S=1 the fold guarantees a non-hyper trace, so hp is ().
        hp = () if serial else self._hp_args()
        parts = []
        for order_local, panel_chunk in chunks:
            key, aux = self._eval_chunk_jit(
                run_params, order_local, key, panel_chunk, *hp)
            parts.append(aux)
        if serial:
            m = self._finalize_eval_jit(concat_auxes(parts))
            return {k: v[None] for k, v in m.items()}
        return self._finalize_eval_jit(concat_auxes(parts, 1))

    # ------------------------------------------------------------------

    def fit(
        self,
        num_epochs: Optional[int] = None,
        rescale_schedule: bool = False,
        resume: bool = False,
    ):
        """Train the whole fleet. Returns (fleet_state, out) where `out`
        has `history` (per-epoch records with per-seed value lists),
        `best_val` (S,), and `best_params` (stacked, `jnp.where`-selected
        per-seed best-validation snapshots). Per-seed best-val weights
        are also written to disk under the serial naming scheme.

        `num_epochs` / `rescale_schedule` follow the serial Trainer's
        contract: N alone runs the first N epochs of the configured
        cosine horizon; rescale_schedule=True makes N the whole horizon.

        ``resume=True`` restores the whole group from its per-seed
        full-state checkpoints when EVERY member has one at the SAME
        epoch — the lockstep layout this fit writes every
        `checkpoint_every` epochs — so a killed multi-hour fleet run
        continues instead of retraining from zero (mixed or missing
        epochs fall back to a fresh start, logged). Restored members
        continue bit-for-bit like an unbroken fleet run.
        """
        cfg = self.cfg
        epochs = cfg.train.num_epochs if num_epochs is None else num_epochs
        total = self.steps_per_epoch * (
            epochs if rescale_schedule else cfg.train.num_epochs
        )
        if total != self.total_steps:
            self.total_steps = total
            self._build_step_fns()

        state = self.init_fleet_state()
        best_val = jnp.full((self.num_seeds,), jnp.inf, jnp.float32)
        # A fresh copy, not an alias: the epoch jit donates its input
        # state, and an aliased best_params buffer would be reused by
        # XLA on backends with donation support.
        best_params = jax.tree.map(jnp.copy, state.params)
        start_epoch = 0
        # Per-lane recovery escalation (docs/robustness.md): one bad
        # lane (non-finite loss or finite-guard skips) rolls back ALONE
        # from its own last-good checkpoint and the fleet continues
        # forward — no epoch replay, no lr change (the optimizer is
        # shared across lanes; the restored lane's rewound step count
        # re-positions its schedule instead).
        recover_after = max(0, int(cfg.train.recover_after))
        lane_streak = [0] * self.num_seeds
        lane_rollbacks = [0] * self.num_seeds
        lane_anchor = [None] * self.num_seeds
        if resume and cfg.train.checkpoint_every:
            restored = self._restore_checkpoints(state)
            if restored is not None:
                state, bv, start_epoch, lane_clean = restored
                best_val = jnp.asarray(bv)
                best_params = self._load_best(state.params, bv)
                # Only members whose restored checkpoint was saved at a
                # no-bad-signal epoch (meta "clean"; pre-ISSUE-9 metas
                # default clean) may anchor a rollback — resuming a
                # lane from a mid-bad-streak cadence save must not make
                # the hazard state its rollback target.
                lane_anchor = [start_epoch - 1 if c else None
                               for c in lane_clean]
                self.logger.log("fleet_resume", epoch=start_epoch,
                                seeds=self.seeds,
                                best_val=[float(v) for v in bv])
        run_state = (state if self.num_seeds > 1
                     else unstack_state(state, 0))
        run_state = self._place_run_state(run_state)
        if self.mesh is not None and self.num_seeds > 1:
            # The best-params buffer rides the same seed-axis sharding
            # as the live params (select_best is a pure elementwise
            # select — mixed placements would force a gather per epoch).
            from factorvae_tpu.parallel.multihost import global_put

            best_params = jax.tree.map(
                lambda x, s: global_put(x, s), best_params,
                self._state_shardings.params)
        val_order = self._val_order()
        ckpt_every = max(1, cfg.train.checkpoint_every or 0)
        history = []
        from factorvae_tpu.utils.logging import current_timeline
        from factorvae_tpu.utils.profiling import (
            maybe_profile_epoch,
            summarize_capture,
        )

        # On-demand profiling (ISSUE 10): same PROFILE_REQUEST drop-in
        # contract as the serial Trainer — metric-stream runs only.
        run_dir = (os.path.dirname(os.path.abspath(
            self.logger.jsonl_path)) if self.logger.jsonl_path else None)

        for epoch in range(start_epoch, epochs):
            t0 = time.perf_counter()
            # Timed spans drain the dispatch (block_until_ready) so the
            # span covers the device work; without a timeline the loop
            # keeps its original async dispatch exactly.
            with maybe_profile_epoch(run_dir, epoch) as (prof, prof_dir), \
                    timeline_span(f"train_epoch_{epoch}", cat="train",
                                  resource="device", epoch=epoch,
                                  seeds=self.num_seeds):
                run_state, train_m = self._run_train_epoch(run_state, epoch)
                if current_timeline() is not None or prof:
                    jax.block_until_ready(train_m["loss"])
            if prof:
                self.logger.log("profile_capture", epoch=epoch,
                                dir=prof_dir,
                                **summarize_capture(prof_dir, top=5))
            elif prof_dir:
                # request consumed but the capture could not start
                self.logger.log("profile_capture", epoch=epoch,
                                error=prof_dir)
            if val_order is not None:
                with timeline_span(f"val_epoch_{epoch}", cat="eval",
                                   resource="device", epoch=epoch,
                                   seeds=self.num_seeds):
                    val_m = self._run_eval_epoch(run_state.params,
                                                 val_order, epoch)
                    if current_timeline() is not None:
                        jax.block_until_ready(val_m["loss"])
                selection = val_m["loss"]
            else:
                val_m = None
                selection = train_m["loss"]
            prev_best = np.asarray(best_val)
            if self.num_seeds == 1:
                # Serial-style host branch: no stacked select dispatches
                # on the oracle path; copy only on improvement (x[None]
                # allocates fresh buffers, so the snapshot survives the
                # next epoch's donation).
                sel_f = float(np.asarray(selection)[0])
                if sel_f < float(prev_best[0]):
                    best_val = jnp.full((1,), sel_f, jnp.float32)
                    best_params = jax.tree.map(lambda x: x[None],
                                               run_state.params)
            else:
                best_params, best_val = select_best(
                    best_params, best_val, run_state.params, selection)
            dt = time.perf_counter() - t0
            step = int(np.asarray(run_state.step).reshape(-1)[0])
            # Hyper lanes each ride their own cosine (peak = lane lr):
            # the logged lr is per-lane, like every other lane metric.
            lr = (
                [learning_rate_at(c.train, self.total_steps, step)
                 for c in self.lane_cfgs]
                if self.hyper
                else learning_rate_at(cfg.train, self.total_steps, step))
            rec = dict(
                epoch=epoch,
                train_loss=[float(v) for v in np.asarray(train_m["loss"])],
                val_loss=([float(v) for v in np.asarray(val_m["loss"])]
                          if val_m is not None
                          else [float("nan")] * self.num_seeds),
                train_recon=[float(v) for v in np.asarray(train_m["recon"])],
                train_kl=[float(v) for v in np.asarray(train_m["kl"])],
                lr=lr,
                step=step,
                seconds=dt,
                # aggregate fleet throughput: every seed trains the same
                # day count, so seed-days/sec = S * days / dt.
                seed_days_per_sec=(
                    self.num_seeds * float(np.asarray(train_m["days"])[0])
                    / max(dt, 1e-9)),
                # Per-lane config labels (ISSUE 12 obs satellite):
                # obs.report/obs.live flag details and the Prometheus
                # exporter's lane_config label name the config that
                # diverged, not just the lane index.
                lane_labels=self.lane_labels(),
            )
            if "skipped_steps" in train_m:
                # Per-lane finite-guard skip counts (train/loop.py) —
                # obs.report renders any >0 as a `skip_step` flag.
                rec["skipped_steps"] = [
                    float(v) for v in np.asarray(train_m["skipped_steps"])]
            if "loss_scale" in train_m:
                # Per-lane dynamic loss scale (mixed builds, ISSUE 16):
                # the values obs.report's `loss_scale_collapse` flag and
                # the PBT fitness readers see.
                from factorvae_tpu.obs.probes import MIXED_PROBE_KEYS

                for k in MIXED_PROBE_KEYS:
                    if k in train_m:
                        rec[k] = [float(v) for v in np.asarray(train_m[k])]
            if cfg.train.obs_probes:
                # Per-seed probe lists (obs/probes.py): the vmapped
                # epoch returns every scalar probe (S,)-shaped.
                from factorvae_tpu.obs.probes import (
                    EVAL_PROBE_KEYS,
                    TRAIN_PROBE_KEYS,
                )

                for k in TRAIN_PROBE_KEYS:
                    if k in train_m:
                        rec[k] = [float(v) for v in np.asarray(train_m[k])]
                if val_m is not None:
                    for k in EVAL_PROBE_KEYS:
                        if k in val_m:
                            rec["val_" + k] = [
                                float(v) for v in np.asarray(val_m[k])]
            history.append(rec)
            self.logger.log("fleet_epoch", **rec)
            # Prometheus textfile exporter (obs/metrics.py): per-seed
            # lanes export with a seed_lane label; no-op uninstalled.
            from factorvae_tpu.obs.metrics import export_epoch_metrics

            export_epoch_metrics(rec)
            # Live allocator watermark (no-op without a timeline or on
            # backends without memory_stats — host CPU).
            from factorvae_tpu.obs.memory import watermark_event

            watermark_event(epoch=epoch, seeds=self.num_seeds)
            # ---- per-lane recovery escalation --------------------------
            loss_np = np.asarray(train_m["loss"], np.float64)
            skip_np = (np.asarray(rec["skipped_steps"], np.float64)
                       if "skipped_steps" in rec
                       else np.zeros(self.num_seeds))
            nf_np = (np.nan_to_num(np.asarray(
                rec["nonfinite_grads"], np.float64))
                if "nonfinite_grads" in rec else np.zeros(self.num_seeds))
            if self._mixed:
                # Mixed lanes earn a skip allowance: every loss-scale
                # growth attempt may overflow once by design (trainer.py
                # uses the same budget), so a lane is sick only when it
                # skips beyond that budget or its scale sat at the
                # floor — not on the first routine backoff.
                skip_budget = (self.steps_per_epoch // max(
                    1, cfg.train.loss_scale_growth_interval) + 1)
                ls_np = (np.asarray(rec["loss_scale"], np.float64)
                         if "loss_scale" in rec
                         else np.full(self.num_seeds, np.inf))
                bad_lanes = (~np.isfinite(loss_np)
                             | (skip_np > skip_budget)
                             | (ls_np <= cfg.train.loss_scale_floor))
            else:
                bad_lanes = (~np.isfinite(loss_np) | (skip_np > 0)
                             | (nf_np > 0))
            for i in range(self.num_seeds):
                lane_streak[i] = lane_streak[i] + 1 if bad_lanes[i] else 0
            to_roll = [
                i for i in range(self.num_seeds)
                if recover_after and lane_streak[i] >= recover_after
                and lane_rollbacks[i] < cfg.train.recover_max_rollbacks
                and lane_anchor[i] is not None
            ]
            if to_roll:
                run_state = self._rollback_lanes(run_state, to_roll,
                                                 lane_anchor, epoch)
                for i in to_roll:
                    lane_rollbacks[i] += 1
                    lane_streak[i] = 0
            for i in range(self.num_seeds):
                # A lane that crossed the escalation threshold with
                # nowhere to roll back to (bad from epoch 0 so no
                # good-epoch anchor, checkpointing off, or rollback
                # budget spent) must say so — the serial trainer logs
                # the same crossing — instead of burning its epoch
                # budget bad in silence. Fires once per streak, at the
                # crossing.
                if (recover_after and lane_streak[i] == recover_after
                        and i not in to_roll):
                    reason = (
                        "checkpointing disabled"
                        if not cfg.train.checkpoint_every
                        else "rollback budget spent "
                        f"({lane_rollbacks[i]}"
                        f"/{cfg.train.recover_max_rollbacks})"
                        if lane_rollbacks[i]
                        >= cfg.train.recover_max_rollbacks
                        else "no good-epoch checkpoint anchor yet")
                    self.logger.log(
                        "recovery", kind="lane_rollback_unavailable",
                        lane=i, seed=self.seeds[i], epoch=epoch,
                        note=f"{reason}; lane continues un-rolled")
                    timeline_event("recovery_rollback_unavailable",
                                   cat="recovery", resource="recovery",
                                   epoch=epoch, lane=i, reason=reason)
            # Serial save cadence, fleet-wide: improved seeds' best-val
            # snapshots hit disk THIS epoch (a killed multi-hour run
            # keeps every seed's best so far, exactly like the serial
            # Trainer's improved-branch save), and full-state resume
            # checkpoints land every checkpoint_every epochs.
            best_val_np = np.asarray(best_val)
            improved = [i for i in range(self.num_seeds)
                        if np.isfinite(best_val_np[i])
                        and best_val_np[i] < prev_best[i]]
            self._save_best(best_params, best_val_np, only=improved)
            if cfg.train.checkpoint_every and (
                    epoch % ckpt_every == 0 or epoch == epochs - 1):
                self._save_checkpoints(
                    self._stacked(run_state), epoch, best_val_np,
                    clean=[lane_streak[i] == 0
                           for i in range(self.num_seeds)])
                for i in range(self.num_seeds):
                    if lane_streak[i] == 0:
                        # Rollback anchor: newest checkpoint written
                        # while THIS lane showed no bad signal.
                        lane_anchor[i] = epoch

        # Finalize any in-flight async checkpoint saves (the barrier the
        # per-epoch loop no longer pays).
        self._close_checkpointers()
        best_val_np = np.asarray(best_val)
        self.logger.log(
            "fleet_best",
            seeds=self.seeds,
            best_val=[float(v) for v in best_val_np],
        )
        return self._stacked(run_state), {
            "history": history,
            "best_val": best_val_np,
            "best_params": best_params,
        }

    # ------------------------------------------------------------------

    def _rollback_lanes(self, run_state, lanes, lane_anchor, epoch):
        """Restore the named seed lanes from their last-good per-seed
        checkpoints and splice them into the running (possibly stacked)
        state; healthy lanes are untouched. A lane whose anchor went
        corrupt falls back to its newest VERIFIED step (restore
        quarantines as it scans); a lane with nothing verifiable keeps
        training forward un-rolled — one sick member never stops the
        fleet."""
        stacked = self.num_seeds > 1
        for i in lanes:
            seed = self.seeds[i]
            ckpt = self._lane_checkpointer(i)
            template = (unstack_state(run_state, i) if stacked
                        else run_state)
            restored_step = lane_anchor[i]
            try:
                row, _ = ckpt.restore(template, step=restored_step)
            except Exception:
                try:
                    row, meta = ckpt.restore(template)
                    restored_step = int(meta.get("epoch", -1))
                except FileNotFoundError:
                    self.logger.log(
                        "recovery", kind="lane_rollback_unavailable",
                        lane=i, seed=seed, epoch=epoch,
                        note="no verifiable checkpoint for this lane; "
                             "continuing forward")
                    continue
            if stacked:
                run_state = jax.tree.map(
                    lambda x, r: x.at[i].set(jnp.asarray(r)),
                    run_state, row)
            else:
                run_state = self._place_run_state(row)
            self.logger.log("recovery", kind="lane_rollback", lane=i,
                            seed=seed, epoch=epoch,
                            restored_step=restored_step)
            timeline_event("recovery_rollback", cat="recovery",
                           resource="recovery", lane=i, seed=seed,
                           epoch=epoch, step=restored_step)
        return run_state

    def seed_config(self, seed: int) -> Config:
        """The per-seed Config a solo run of this fleet member would use
        (what `checkpoint_name()` keys on). On hyper fleets the LANE
        config is the member's identity (`self.lane_cfgs[i]` — lanes may
        share a seed); this seed-keyed view stays for the classic
        seed-fleet callers (tests, chaos harnesses)."""
        return dataclasses.replace(
            self.cfg,
            train=dataclasses.replace(self.cfg.train, seed=int(seed)),
        )

    # ---- mesh placement / gather boundaries --------------------------

    def _place_run_state(self, run_state):
        """Place the initial (or restored) run state onto the mesh: the
        serial state replicated, the stacked state per the fleet rule
        table (seed lanes over 'data'). Without a mesh, a no-op — the
        jits place uncommitted arrays themselves, exactly as before."""
        if self.mesh is None:
            return run_state
        from factorvae_tpu.parallel.multihost import global_put

        if self.num_seeds == 1:
            rep = replicated(self.mesh)
            return jax.tree.map(lambda x: global_put(x, rep), run_state)
        return jax.tree.map(
            lambda x, s: global_put(x, s), run_state,
            self._state_shardings)

    def _gather_host(self, tree, stacked_params: bool = False):
        """Sharded stacked tree -> host numpy, through the rule table's
        gather fns (partition.make_shard_and_gather_fns): per-seed
        checkpoint rows are unstacked from gathered HOST buffers, so the
        on-disk layout never depends on the mesh shape (a mesh-saved
        checkpoint restores into a serial Trainer unchanged — pinned in
        tests/test_train.py)."""
        if self.mesh is None:
            return tree
        specs = (partition.params_partition_specs(tree, stacked=True)
                 if stacked_params
                 else partition.state_partition_specs(tree, stacked=True))
        return partition.gather_tree(self.mesh, specs, tree)

    def _save_best(self, best_params, best_val: np.ndarray,
                   only=None) -> None:
        """Per-seed best-val weights under the serial naming scheme —
        the artifact `seed_sweep` / the backtest selection rule loads.
        `only` restricts the write to the seeds that improved THIS
        epoch (the serial save cadence — everyone else's file is
        already current). Seeds that never improved (best_val still
        inf/NaN — zero epochs or an all-NaN loss stream) get NO best
        checkpoint, exactly like the serial Trainer, whose save runs
        only inside the `improved` branch; consumers then fall back to
        final-epoch params."""
        rows = [i for i in (range(self.num_seeds) if only is None else only)
                if np.isfinite(best_val[i])]
        if not rows:
            return
        # Mesh runs: ONE gather of the stacked buffer to host, then
        # unstack rows — per-seed artifacts never carry mesh layout.
        best_params = self._gather_host(best_params, stacked_params=True)
        for i in rows:
            cfg_s = self.lane_cfgs[i]
            save_params(
                cfg_s.train.save_dir, cfg_s.checkpoint_name(),
                unstack_state(best_params, i),
            )

    def _restore_checkpoints(self, template_state):
        """(stacked state, best_val (S,), start_epoch, per-lane clean
        flags) from the per-seed
        full-state checkpoints, or None when no step is common to every
        member. The restore epoch is the MAX step present in ALL
        members' dirs: a kill mid-way through the per-seed save loop
        leaves the members one epoch apart, and the Checkpointer keeps
        several steps (keep_checkpoints), so rewinding everyone to the
        newest common epoch loses at most one epoch instead of the
        whole run — mixed-latest resumes would silently desynchronize
        the cosine schedule."""
        ckpt_dirs = []
        common = None
        for cfg_s in self.lane_cfgs:
            d = f"{cfg_s.train.save_dir}/{cfg_s.checkpoint_name()}_ckpt"
            if not os.path.isdir(d):
                return None
            ckpt = Checkpointer(d, keep=cfg_s.train.keep_checkpoints)
            # verified_steps (not all_steps): a corrupt member step is
            # quarantined HERE, so the max-common-step rule settles on
            # an epoch every member can actually load — the whole group
            # rewinds past one member's corruption instead of crashing
            # on it mid-restore.
            steps = set(ckpt.verified_steps())
            ckpt.close()
            if not steps:
                return None
            ckpt_dirs.append(d)
            common = steps if common is None else common & steps
        if not common:
            self.logger.log(
                "fleet_resume_skipped", seeds=self.seeds,
                note="no checkpoint step common to every fleet member; "
                     "starting the group fresh")
            return None
        epoch = max(common)
        states, best_vals, cleans = [], [], []
        for i, seed in enumerate(self.seeds):
            cfg_s = self.lane_cfgs[i]
            ckpt = Checkpointer(ckpt_dirs[i],
                                keep=cfg_s.train.keep_checkpoints)
            try:
                # verified=True: this exact step just passed the
                # verified_steps scan above — do not sha256 the same
                # bytes a second time on the resume path.
                st, meta = ckpt.restore(unstack_state(template_state, i),
                                        step=epoch, verified=True)
            except CheckpointIntegrityError as e:
                # A member step that passed the manifest scan but failed
                # at restore time (unverified legacy step, or damage the
                # digest did not cover) is quarantined by restore();
                # rescan — the max-common rule now settles below it.
                # Bounded: every retry quarantines at least one step.
                ckpt.close()
                self.logger.log(
                    "fleet_resume_retry", seed=seed, step=epoch,
                    error=str(e),
                    note="member checkpoint failed integrity at restore; "
                         "rescanning for an older common step")
                return self._restore_checkpoints(template_state)
            ckpt.close()
            states.append(st)
            best_vals.append(float(meta.get("best_val", float("inf"))))
            cleans.append(bool(meta.get("clean", True)))
            saved_cfg = meta.get("config")
            if saved_cfg is not None and saved_cfg != cfg_s.to_dict():
                self.logger.log(
                    "fleet_resume_config_mismatch", seed=seed,
                    note="resuming with a different config than the "
                         "checkpoint was written with")
        return (stack_states(states),
                np.asarray(best_vals, np.float32), epoch + 1, cleans)

    def _load_best(self, params_template, best_val: np.ndarray):
        """Stacked best-params buffer rebuilt from the per-seed best-val
        checkpoints written before a kill (seeds without one — never
        improved — keep their current params as the running snapshot,
        matching a fresh run's initialization of the buffer)."""
        from factorvae_tpu.train.checkpoint import load_params

        rows = []
        for i, seed in enumerate(self.seeds):
            template = unstack_state(params_template, i)
            cfg_s = self.lane_cfgs[i]
            path = os.path.join(cfg_s.train.save_dir,
                                cfg_s.checkpoint_name())
            if np.isfinite(best_val[i]) and os.path.isdir(path):
                rows.append(load_params(path, template))
            else:
                rows.append(jax.tree.map(jnp.copy, template))
        return stack_states(rows)

    def _lane_checkpointer(self, i: int) -> Checkpointer:
        """Per-LANE Checkpointer, cached for the life of this trainer so
        ASYNC saves (checkpoint.py) actually overlap the next epoch —
        open/close per save would re-impose the barrier at close().
        Keyed by lane index, not seed: hyper lanes may share a seed
        while writing distinct (run_name-tagged) directories."""
        if not hasattr(self, "_ckpts"):
            self._ckpts = {}
        if i not in self._ckpts:
            cfg_s = self.lane_cfgs[i]
            self._ckpts[i] = Checkpointer(
                f"{cfg_s.train.save_dir}/{cfg_s.checkpoint_name()}_ckpt",
                keep=cfg_s.train.keep_checkpoints,
                async_save=cfg_s.train.async_checkpointing,
            )
        return self._ckpts[i]

    def _seed_checkpointer(self, seed: int) -> Checkpointer:
        """Seed-keyed view of `_lane_checkpointer` for classic fleets
        (first lane carrying that seed)."""
        return self._lane_checkpointer(self.seeds.index(int(seed)))

    def _close_checkpointers(self) -> None:
        for ckpt in getattr(self, "_ckpts", {}).values():
            ckpt.close()
        self._ckpts = {}

    def _save_checkpoints(self, fleet_state, epoch: int,
                          best_val: np.ndarray,
                          clean: Optional[list] = None) -> None:
        """Lockstep full-state checkpoint per seed (every
        `checkpoint_every` epochs + the final one), format-compatible
        with the serial Checkpointer layout so a serial `Trainer` resume
        can continue any fleet member — and `fit(resume=True)` can
        restore the whole group. Saves are async: a kill mid-way leaves
        members at MOST one complete epoch apart (uncommitted steps are
        invisible to readers), exactly the case the group-resume
        max-common-step rule rewinds over. On a mesh the stacked state
        is gathered to host ONCE through the rule table's gather fns,
        then unstacked — serial-format checkpoints regardless of mesh
        shape."""
        fleet_state = self._gather_host(fleet_state)
        for i, seed in enumerate(self.seeds):
            cfg_s = self.lane_cfgs[i]
            # 0-d ndarrays, not numpy scalars: indexing a gathered host
            # (S,) leaf yields np.int32-style scalars, which orbax's
            # sync StandardSave rejects ("Unsupported type").
            row = jax.tree.map(np.asarray, unstack_state(fleet_state, i))
            self._lane_checkpointer(i).save(
                epoch,
                row,
                {"epoch": epoch, "best_val": float(best_val[i]),
                 "config": cfg_s.to_dict(),
                 "clean": bool(clean[i]) if clean is not None else True},
            )
