from factorvae_tpu.train.checkpoint import Checkpointer, load_params, save_params
from factorvae_tpu.train.fleet import FleetTrainer, stack_states, unstack_state
from factorvae_tpu.train.loop import StepFns, make_step_fns
from factorvae_tpu.train.pbt import pbt_fit
from factorvae_tpu.train.state import (
    TrainState,
    create_train_state,
    learning_rate_at,
    make_hyper_optimizer,
    make_optimizer,
)
from factorvae_tpu.train.trainer import Trainer

__all__ = [
    "Checkpointer",
    "FleetTrainer",
    "StepFns",
    "TrainState",
    "Trainer",
    "create_train_state",
    "learning_rate_at",
    "load_params",
    "make_hyper_optimizer",
    "make_optimizer",
    "make_step_fns",
    "pbt_fit",
    "save_params",
    "stack_states",
    "unstack_state",
]
