"""Orbax checkpointing with full resume.

The reference only ever writes the best-validation model weights
(main.py:73-80); optimizer/scheduler state and the RNG are lost, so a
crashed run cannot resume (SURVEY.md §5 "Failure detection"). Here every
checkpoint carries the complete `TrainState` (params, optimizer state,
step, threaded PRNG key) plus a JSON metadata blob (epoch, best-val,
config), making resume deterministic: a run killed at epoch k continues
exactly as if it had never died.

Saves are ASYNC by default: serialization overlaps the next epoch's
compute and the barrier lives on the read side (restore/steps/close) —
see `Checkpointer`. Crash semantics are unchanged because orbax commits
step directories atomically: a kill mid-save is a lost step, never a
corrupt one.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from factorvae_tpu.train.state import TrainState
from factorvae_tpu.utils.logging import (
    current_timeline,
    timeline_span,
    timeline_span_at,
)


def _own_buffers(tree):
    """Deep-copy restored leaves into XLA-owned buffers. On CPU,
    jax.device_put of an aligned numpy array is ZERO-COPY: the restored
    jax.Array aliases host memory that orbax's restore machinery still
    owns. The training jits then DONATE that state (donate_argnums), so
    XLA reuses/frees a buffer numpy still references — the observed
    resume-then-train corruption (NaN epoch losses, at-exit/mid-epoch
    SIGSEGV on the CPU sandbox). A fresh copy severs the alias."""
    import jax.numpy as jnp

    return jax.tree.map(lambda x: jnp.array(x, copy=True), tree)


class Checkpointer:
    """Full-state checkpoint manager, ASYNC by default.

    ``save()`` snapshots the state to host synchronously (orbax copies
    device buffers before returning, so the caller may immediately
    donate/overwrite them) and serializes to disk on a background
    thread — the epoch loop never blocks on checkpoint I/O. The barrier
    moves to the READ side: ``latest_step``/``all_steps``/``restore``
    first drain any in-flight save, and ``close()`` finalizes. A kill
    mid-save loses only the uncommitted step: orbax commits a step
    directory atomically on finalize, so readers (including the fleet's
    group-resume max-common-step scan) only ever see COMPLETE steps
    (tested: tests/test_stream.py kill-between-saves).

    ``async_save=False`` restores the old blocking behavior
    (TrainConfig.async_checkpointing wires it through the trainers).
    """

    def __init__(self, directory: str, keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True,
                enable_async_checkpointing=async_save,
            ),
        )
        self._async = async_save

    def save(self, step: int, state: TrainState, meta: dict) -> None:
        # `ckpt_save` on the timeline is the part the TRAINING LOOP
        # pays: snapshot + enqueue under async, the whole serialization
        # under sync — the number that shows whether async checkpointing
        # actually moved the cost off the critical path.
        with timeline_span(f"ckpt_save_{step}", cat="checkpoint",
                           resource="checkpoint", step=step,
                           mode="async" if self._async else "sync"):
            if self._async:
                # Snapshot to OWNED host buffers before handing orbax the
                # tree: its background writer would otherwise hold
                # zero-copy views of CPU jax arrays that the next epoch's
                # jit donates (the same alias class the restore-side
                # _own_buffers severs). One host memcpy up front;
                # serialization and disk I/O then overlap the next epoch
                # freely.
                import numpy as np

                state = jax.tree.map(lambda x: np.array(x), state)
            self._mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    meta=ocp.args.JsonSave(meta),
                ),
            )
        if not self._async:
            self._mgr.wait_until_finished()
        elif current_timeline() is not None:
            self._watch_commit(step)

    def _watch_commit(self, step: int) -> None:
        """Emit the BACKGROUND serialize span for an async save: a
        daemon thread polls for orbax's atomic step-directory commit
        (tmp-dir rename) and reports enqueue->commit as
        `ckpt_serialize_{step}` — the filesystem is the only safe
        observation point (orbax's manager is not re-entrant from a
        second thread). Telemetry only: spawned when a timeline is
        installed, never on the default path."""
        t0 = time.perf_counter()
        path = os.path.join(self.directory, str(step))

        def poll() -> None:
            deadline = t0 + 600.0
            while time.perf_counter() < deadline:
                if os.path.isdir(path):
                    timeline_span_at(
                        f"ckpt_serialize_{step}", t0, time.perf_counter(),
                        cat="checkpoint", resource="ckpt_serialize",
                        step=step)
                    return
                time.sleep(0.02)

        threading.Thread(target=poll, daemon=True,
                         name=f"ckpt-commit-watch-{step}").start()

    def wait_until_finished(self) -> None:
        """Drain any in-flight async save (the moved barrier)."""
        with timeline_span("ckpt_barrier", cat="checkpoint",
                           resource="checkpoint"):
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Every retained COMPLETE step, ascending (the fleet
        group-resume picks the max step common to all members,
        train/fleet.py)."""
        self._mgr.wait_until_finished()
        return sorted(self._mgr.all_steps())

    def restore(
        self, template: TrainState, step: Optional[int] = None
    ) -> Tuple[TrainState, dict]:
        """`template` supplies the pytree structure/shapes (an abstract
        eval_shape of the state works)."""
        self._mgr.wait_until_finished()
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        out = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore(),
            ),
        )
        return _own_buffers(out["state"]), out["meta"]

    def close(self):
        self._mgr.close()


def save_params(directory: str, name: str, params: Any) -> str:
    """Best-model weights-only export under a parameter-encoding name —
    the analogue of the reference's torch.save(state_dict) filename scheme
    (main.py:78-79)."""
    path = os.path.join(os.path.abspath(directory), name)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    return path


def load_params(path: str, template: Any) -> Any:
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    ckptr = ocp.StandardCheckpointer()
    out = ckptr.restore(os.path.abspath(path), abstract)
    ckptr.close()
    return out
