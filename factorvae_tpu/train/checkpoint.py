"""Orbax checkpointing with full resume + byte-level integrity.

The reference only ever writes the best-validation model weights
(main.py:73-80); optimizer/scheduler state and the RNG are lost, so a
crashed run cannot resume (SURVEY.md §5 "Failure detection"). Here every
checkpoint carries the complete `TrainState` (params, optimizer state,
step, threaded PRNG key) plus a JSON metadata blob (epoch, best-val,
config), making resume deterministic: a run killed at epoch k continues
exactly as if it had never died.

Saves are ASYNC by default: serialization overlaps the next epoch's
compute and the barrier lives on the read side (restore/steps/close) —
see `Checkpointer`. Crash semantics are unchanged because orbax commits
step directories atomically: a kill mid-save is a lost step, never a
corrupt one.

**Integrity (ISSUE 9).** Every committed step gets a MANIFEST — sha256
per payload file + the canonical config hash — written as a sibling
(`<dir>/manifests/<step>.json`, never inside the orbax step layout).
Restore verifies the chosen step against its manifest first; a mismatch
QUARANTINES the step (`<dir>/quarantine/<step>.json`, a
`ckpt_quarantine` timeline mark) and falls back to the next older
verified step instead of loading garbage or crashing. `all_steps` /
`latest_step` exclude quarantined steps, so the fleet's group-resume
max-common-step rule skips a corrupt member automatically
(`verified_steps` verifies eagerly for exactly that scan). Steps
written before this PR have no manifest and restore UNVERIFIED (logged
as such) — integrity is additive, not a format break.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from factorvae_tpu.train.state import TrainState
from factorvae_tpu.utils.logging import (
    config_hash,
    current_timeline,
    timeline_event,
    timeline_span,
    timeline_span_at,
)

MANIFEST_DIRNAME = "manifests"
QUARANTINE_DIRNAME = "quarantine"


class CheckpointIntegrityError(RuntimeError):
    """An EXPLICITLY requested step failed manifest verification (the
    latest-step path never raises this — it quarantines and falls back).
    Carries a one-line actionable message."""


def _own_buffers(tree):
    """Deep-copy restored leaves into XLA-owned buffers. On CPU,
    jax.device_put of an aligned numpy array is ZERO-COPY: the restored
    jax.Array aliases host memory that orbax's restore machinery still
    owns. The training jits then DONATE that state (donate_argnums), so
    XLA reuses/frees a buffer numpy still references — the observed
    resume-then-train corruption (NaN epoch losses, at-exit/mid-epoch
    SIGSEGV on the CPU sandbox). A fresh copy severs the alias."""
    import jax.numpy as jnp

    return jax.tree.map(lambda x: jnp.array(x, copy=True), tree)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def step_manifest(step_dir: str, cfg_hash: Optional[str] = None) -> dict:
    """Manifest dict for one COMMITTED step directory: sha256 of every
    file (path-relative), total bytes, and the canonical config hash of
    the run that wrote it."""
    files = {}
    nbytes = 0
    for root, _, names in os.walk(step_dir):
        for n in sorted(names):
            p = os.path.join(root, n)
            rel = os.path.relpath(p, step_dir)
            files[rel] = _sha256_file(p)
            nbytes += os.path.getsize(p)
    return {"config_hash": cfg_hash, "files": files, "nbytes": nbytes,
            "created": round(time.time(), 3)}


def verify_manifest(step_dir: str, manifest: dict) -> Optional[str]:
    """None when every manifest file exists with matching sha256;
    otherwise a one-line reason naming the first mismatch."""
    for rel, digest in sorted((manifest.get("files") or {}).items()):
        p = os.path.join(step_dir, rel)
        if not os.path.exists(p):
            return f"payload file missing: {rel}"
        if _sha256_file(p) != digest:
            return f"sha256 mismatch: {rel}"
    return None


class Checkpointer:
    """Full-state checkpoint manager, ASYNC by default.

    ``save()`` snapshots the state to host synchronously (orbax copies
    device buffers before returning, so the caller may immediately
    donate/overwrite them) and serializes to disk on a background
    thread — the epoch loop never blocks on checkpoint I/O. The barrier
    moves to the READ side: ``latest_step``/``all_steps``/``restore``
    first drain any in-flight save, and ``close()`` finalizes. A kill
    mid-save loses only the uncommitted step: orbax commits a step
    directory atomically on finalize, so readers (including the fleet's
    group-resume max-common-step scan) only ever see COMPLETE steps
    (tested: tests/test_stream.py kill-between-saves).

    Manifests ride the same barrier: the WRITER process records each
    saved step and writes its sha256 manifest right after the commit
    drains (read-side barrier / close). A kill between commit and
    barrier leaves a complete step without a manifest — it restores
    UNVERIFIED, exactly like a pre-manifest checkpoint. Restore-side
    verification/quarantine semantics live in ``restore`` /
    ``verified_steps``.

    ``async_save=False`` restores the old blocking behavior
    (TrainConfig.async_checkpointing wires it through the trainers).
    """

    def __init__(self, directory: str, keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True,
                enable_async_checkpointing=async_save,
            ),
        )
        self._async = async_save
        # (step, cfg_hash) saved by THIS process whose manifest is not
        # yet on disk; flushed at every read-side barrier.
        self._pending_manifests: List[Tuple[int, Optional[str]]] = []
        # Guards the pending list only (swap/append/filter): manifest
        # hashing and writes happen OUTSIDE it, so the training loop's
        # append never blocks behind a background flush's sha256 pass.
        self._pending_lock = threading.Lock()

    # ---- manifest / quarantine paths ---------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, MANIFEST_DIRNAME,
                            f"{int(step)}.json")

    def _quarantine_path(self, step: int) -> str:
        return os.path.join(self.directory, QUARANTINE_DIRNAME,
                            f"{int(step)}.json")

    def is_quarantined(self, step: int) -> bool:
        return os.path.exists(self._quarantine_path(step))

    def quarantine(self, step: int, reason: str) -> None:
        """Mark a step as corrupt: excluded from latest/all/verified
        steps from now on, never deleted (forensics want the bytes)."""
        qdir = os.path.join(self.directory, QUARANTINE_DIRNAME)
        os.makedirs(qdir, exist_ok=True)
        with open(self._quarantine_path(step), "w") as fh:
            json.dump({"step": int(step), "reason": reason,
                       "ts": round(time.time(), 3)}, fh)
        timeline_event("ckpt_quarantine", cat="recovery",
                       resource="checkpoint", step=int(step),
                       reason=reason)

    def quarantined_steps(self) -> list:
        qdir = os.path.join(self.directory, QUARANTINE_DIRNAME)
        try:
            return sorted(int(os.path.splitext(n)[0])
                          for n in os.listdir(qdir) if n.endswith(".json"))
        except OSError:
            return []

    def _flush_manifests(self, drained: bool = True) -> None:
        """Write manifests for steps saved by this process whose commits
        have landed. Under the read-side barrier (`drained=True`) every
        pending step is either committed or lost; the opportunistic
        flush at the next save() (`drained=False`) writes manifests
        only for steps whose final directory exists — orbax commits by
        atomic rename, so an absent dir means the write is still in
        flight and the step stays pending. That flush is what bounds a
        mid-run crash to ONE unverified step instead of a whole
        manifest-less run."""
        with self._pending_lock:
            pending, self._pending_manifests = self._pending_manifests, []
        requeue = []
        for step, cfg_hash in pending:
            step_dir = os.path.join(self.directory, str(step))
            if not os.path.isdir(step_dir):
                if not drained:
                    requeue.append((step, cfg_hash))
                continue  # drained: retention dropped it, or save failed
            mdir = os.path.join(self.directory, MANIFEST_DIRNAME)
            os.makedirs(mdir, exist_ok=True)
            manifest = dict(step_manifest(step_dir, cfg_hash), step=step)
            tmp = self._manifest_path(step) + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(manifest, fh)
            os.replace(tmp, self._manifest_path(step))
        if requeue:
            with self._pending_lock:
                self._pending_manifests.extend(requeue)

    def manifest(self, step: int) -> Optional[dict]:
        """The step's manifest, or None when none was ever written.
        A manifest that EXISTS but cannot be read or parsed raises —
        corruption landing in the manifest file itself must fail the
        step's verification (verify_step), not silently demote it to
        the legacy pre-manifest 'unverified' path."""
        try:
            with open(self._manifest_path(step)) as fh:
                return json.load(fh)
        except FileNotFoundError:
            return None

    def verify_step(self, step: int) -> Tuple[bool, Optional[str]]:
        """(ok, reason). A quarantined step is not ok; a step whose
        directory is ABSENT is not ok with reason "missing" (retention
        evicted it or it never committed — manifests outlive retained
        steps, and an evicted step is gone, not corrupt); a present step
        without a manifest is ok-but-unverified (reason "unverified":
        pre-manifest layout, or the writer died between commit and
        barrier)."""
        if self.is_quarantined(step):
            return False, "quarantined"
        step_dir = os.path.join(self.directory, str(step))
        if not os.path.isdir(step_dir):
            return False, "missing"
        try:
            manifest = self.manifest(step)
        except (OSError, ValueError) as e:
            return False, f"manifest unreadable: {e}"
        if manifest is None:
            return True, "unverified"
        bad = verify_manifest(step_dir, manifest)
        return (False, bad) if bad else (True, None)

    # ---- save --------------------------------------------------------

    def save(self, step: int, state: TrainState, meta: dict) -> None:
        step = int(step)
        if step in self._mgr.all_steps():
            # Overwrite semantics: a rollback-recovery replay re-saves
            # epochs it already checkpointed, and orbax's manager
            # silently SKIPS a save for an existing step — which would
            # leave the pre-rollback bytes on disk (and stale rollback
            # anchors pointing at them) while the run moves on. The
            # REPLAYED trajectory is the one that must persist: drain
            # any in-flight write, drop the old step, its manifest and
            # any quarantine marker, then save fresh.
            self._mgr.wait_until_finished()
            with self._pending_lock:
                self._pending_manifests = [
                    (s, h) for s, h in self._pending_manifests
                    if s != step]
            self._mgr.delete(step)
            for stale in (self._manifest_path(step),
                          self._quarantine_path(step)):
                try:
                    os.remove(stale)
                except FileNotFoundError:
                    pass
        if self._async:
            # Opportunistic manifest flush for EARLIER saves whose
            # atomic commit has landed: a crash between now and the next
            # barrier then leaves at most this save unverified, not the
            # whole run manifest-less (see _flush_manifests). On a
            # BACKGROUND thread: the flush sha256-hashes the previous
            # step's full payload, exactly the host wall the async save
            # path exists to keep off the training loop; the read-side
            # barrier still flushes synchronously.
            threading.Thread(target=self._flush_manifests,
                             kwargs={"drained": False}, daemon=True,
                             name="ckpt-manifest-flush").start()
        # `ckpt_save` on the timeline is the part the TRAINING LOOP
        # pays: snapshot + enqueue under async, the whole serialization
        # under sync — the number that shows whether async checkpointing
        # actually moved the cost off the critical path.
        with timeline_span(f"ckpt_save_{step}", cat="checkpoint",
                           resource="checkpoint", step=step,
                           mode="async" if self._async else "sync"):
            if self._async:
                # Snapshot to OWNED host buffers before handing orbax the
                # tree: its background writer would otherwise hold
                # zero-copy views of CPU jax arrays that the next epoch's
                # jit donates (the same alias class the restore-side
                # _own_buffers severs). One host memcpy up front;
                # serialization and disk I/O then overlap the next epoch
                # freely.
                import numpy as np

                state = jax.tree.map(lambda x: np.array(x), state)
            self._mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    meta=ocp.args.JsonSave(meta),
                ),
            )
        cfg = meta.get("config") if isinstance(meta, dict) else None
        with self._pending_lock:
            self._pending_manifests.append(
                (step, config_hash(cfg) if isinstance(cfg, dict) else None))
        # Chaos harness (factorvae_tpu/chaos): a kill_mid_save fault
        # hard-kills HERE — write enqueued (async) or finished (sync
        # commit happens below at the wait), manifest not yet on disk —
        # the exact crash window the atomic-commit + manifest-at-barrier
        # design must survive. A None check when no plan is installed.
        from factorvae_tpu import chaos

        if chaos.fault("kill_mid_save", step=int(step)) is not None:
            chaos.ops.kill_now()
        if not self._async:
            self._mgr.wait_until_finished()
            self._flush_manifests()
        elif current_timeline() is not None:
            self._watch_commit(step)

    def _watch_commit(self, step: int) -> None:
        """Emit the BACKGROUND serialize span for an async save: a
        daemon thread polls for orbax's atomic step-directory commit
        (tmp-dir rename) and reports enqueue->commit as
        `ckpt_serialize_{step}` — the filesystem is the only safe
        observation point (orbax's manager is not re-entrant from a
        second thread). Telemetry only: spawned when a timeline is
        installed, never on the default path."""
        t0 = time.perf_counter()
        path = os.path.join(self.directory, str(step))

        def poll() -> None:
            deadline = t0 + 600.0
            while time.perf_counter() < deadline:
                if os.path.isdir(path):
                    timeline_span_at(
                        f"ckpt_serialize_{step}", t0, time.perf_counter(),
                        cat="checkpoint", resource="ckpt_serialize",
                        step=step)
                    return
                time.sleep(0.02)

        # graftlint: disable=JGL011 telemetry-only writer: the span it emits lands on the RUN.jsonl stream, whose consumers (obs.timeline/report/live) tolerate a torn final line by contract — a mid-write kill loses one span, never an artifact
        threading.Thread(target=poll, daemon=True,
                         name=f"ckpt-commit-watch-{step}").start()

    # ---- read side (barrier + verification) --------------------------

    def wait_until_finished(self) -> None:
        """Drain any in-flight async save (the moved barrier), then
        write the drained steps' manifests."""
        with timeline_span("ckpt_barrier", cat="checkpoint",
                           resource="checkpoint"):
            self._mgr.wait_until_finished()
        self._flush_manifests()

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def all_steps(self) -> list:
        """Every retained COMPLETE, non-quarantined step, ascending (the
        fleet group-resume picks the max step common to all members,
        train/fleet.py)."""
        self.wait_until_finished()
        bad = set(self.quarantined_steps())
        return sorted(s for s in self._mgr.all_steps() if s not in bad)

    def verified_steps(self) -> list:
        """`all_steps` with EAGER manifest verification: steps that fail
        are quarantined now, so a group-resume scan over every member
        settles on a max-common step that is actually loadable
        (unverified manifest-less steps stay in — rejecting every
        pre-manifest checkpoint would break old runs' resume)."""
        out = []
        for s in self.all_steps():
            ok, reason = self.verify_step(s)
            if ok:
                out.append(s)
            else:
                self.quarantine(s, reason or "corrupt")
        return out

    def restore(
        self, template: TrainState, step: Optional[int] = None,
        verified: bool = False,
    ) -> Tuple[TrainState, dict]:
        """`template` supplies the pytree structure/shapes (an abstract
        eval_shape of the state works).

        Integrity: the chosen step is verified against its manifest
        first. An implicit (latest) restore quarantines a corrupt step
        and FALLS BACK to the next older verified one; an explicit
        `step=` request raises `CheckpointIntegrityError` instead —
        the caller asked for those exact bytes and must decide.
        `verified=True` (explicit-step callers that JUST ran this step
        through `verified_steps`, e.g. the fleet group-resume scan)
        skips the redundant second sha256 pass over the same bytes;
        deserialization failures still quarantine."""
        explicit = step is not None
        candidates = [int(step)] if explicit else \
            list(reversed(self.all_steps()))
        if explicit:
            self.wait_until_finished()
        if not candidates:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        for s in candidates:
            ok, reason = ((True, None) if (verified and explicit)
                          else self.verify_step(s))
            if not ok:
                if reason == "missing":
                    # Retention-evicted (or never-committed) step: gone,
                    # not corrupt — never quarantine an absence.
                    if explicit:
                        raise FileNotFoundError(
                            f"no checkpoint step {s} in "
                            f"{self.directory} (evicted by retention or "
                            f"never committed; retained steps: "
                            f"{sorted(self._mgr.all_steps())})")
                    continue
                self.quarantine(s, reason or "corrupt")
                if explicit:
                    raise CheckpointIntegrityError(
                        f"checkpoint step {s} in {self.directory} failed "
                        f"integrity verification ({reason}); it is now "
                        f"quarantined — restore another step or retrain")
                continue
            if reason == "unverified":
                timeline_event("ckpt_unverified", cat="checkpoint",
                               resource="checkpoint", step=s)
            try:
                out = self._mgr.restore(
                    s,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(abstract),
                        meta=ocp.args.JsonRestore(),
                    ),
                )
            except Exception as e:
                # Deserialization died on a step the manifest could not
                # vouch for (unverified legacy layout, or damage in a
                # byte sha256 happens not to cover): fence it like any
                # other corruption and fall back instead of crashing.
                self.quarantine(
                    s, f"restore failed: {type(e).__name__}: {e}")
                if explicit:
                    raise CheckpointIntegrityError(
                        f"checkpoint step {s} in {self.directory} failed "
                        f"to deserialize ({type(e).__name__}: {e}); it "
                        f"is now quarantined — restore another step or "
                        f"retrain") from e
                continue
            return _own_buffers(out["state"]), out["meta"]
        raise FileNotFoundError(
            f"no verifiable checkpoint in {self.directory} (all "
            f"retained steps quarantined: {self.quarantined_steps()})")

    def close(self):
        self._mgr.wait_until_finished()
        self._flush_manifests()
        self._mgr.close()


def save_params(directory: str, name: str, params: Any) -> str:
    """Best-model weights-only export under a parameter-encoding name —
    the analogue of the reference's torch.save(state_dict) filename scheme
    (main.py:78-79). Writes a sibling `<path>.manifest.json` (sha256 per
    payload file) that `serve.registry` cold-starts verify before
    loading."""
    path = os.path.join(os.path.abspath(directory), name)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    manifest = step_manifest(path)
    tmp = path + ".manifest.json.tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
    os.replace(tmp, path + ".manifest.json")
    return path


def verify_params_dir(path: str) -> Optional[str]:
    """Verify a `save_params` directory against its sibling manifest.
    None when clean OR when no manifest exists (pre-manifest artifact —
    unverifiable, not corrupt); a one-line reason on mismatch."""
    path = os.path.abspath(path)
    try:
        with open(path + ".manifest.json") as fh:
            manifest = json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        # The manifest exists but is torn/corrupt: that is damage, not
        # a pre-manifest artifact — refuse, don't admit unverified.
        return f"manifest unreadable: {e}"
    return verify_manifest(path, manifest)


def load_params(path: str, template: Any) -> Any:
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    ckptr = ocp.StandardCheckpointer()
    out = ckptr.restore(os.path.abspath(path), abstract)
    ckptr.close()
    return out
