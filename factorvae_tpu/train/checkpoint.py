"""Orbax checkpointing with full resume.

The reference only ever writes the best-validation model weights
(main.py:73-80); optimizer/scheduler state and the RNG are lost, so a
crashed run cannot resume (SURVEY.md §5 "Failure detection"). Here every
checkpoint carries the complete `TrainState` (params, optimizer state,
step, threaded PRNG key) plus a JSON metadata blob (epoch, best-val,
config), making resume deterministic: a run killed at epoch k continues
exactly as if it had never died.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from factorvae_tpu.train.state import TrainState


class Checkpointer:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=False
            ),
        )

    def save(self, step: int, state: TrainState, meta: dict) -> None:
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta),
            ),
        )
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Every retained step, ascending (the fleet group-resume picks
        the max step common to all members, train/fleet.py)."""
        return sorted(self._mgr.all_steps())

    def restore(
        self, template: TrainState, step: Optional[int] = None
    ) -> Tuple[TrainState, dict]:
        """`template` supplies the pytree structure/shapes (an abstract
        eval_shape of the state works)."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        out = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore(),
            ),
        )
        return out["state"], out["meta"]

    def close(self):
        self._mgr.close()


def save_params(directory: str, name: str, params: Any) -> str:
    """Best-model weights-only export under a parameter-encoding name —
    the analogue of the reference's torch.save(state_dict) filename scheme
    (main.py:78-79)."""
    path = os.path.join(os.path.abspath(directory), name)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    return path


def load_params(path: str, template: Any) -> Any:
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    ckptr = ocp.StandardCheckpointer()
    out = ckptr.restore(os.path.abspath(path), abstract)
    ckptr.close()
    return out
