"""Train state and optimizer assembly.

Optimizer parity with reference main.py:60-61: Adam(lr=1e-4) with a
cosine-annealing schedule whose horizon is (steps-per-epoch x epochs) and
which advances once per optimizer update (the reference steps its
scheduler once per batch, train_model.py:31-32). torch's
CosineAnnealingLR with eta_min=0 is exactly optax's
cosine_decay_schedule(alpha=0).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from factorvae_tpu.config import TrainConfig


@flax.struct.dataclass
class TrainState:
    """Everything needed to resume a run bit-for-bit (the reference saves
    only model weights, main.py:78-79 — optimizer/scheduler state is lost
    on crash; this is the fix called out in SURVEY.md §5)."""

    step: jnp.ndarray            # optimizer updates taken
    params: Any
    opt_state: Any
    rng: jax.Array               # threaded PRNG key (sample/dropout noise)

    def advance_rng(self):
        new_rng, sub = jax.random.split(self.rng)
        return self.replace(rng=new_rng), sub


def make_optimizer(
    cfg: TrainConfig, total_steps: Optional[int] = None,
    lr_scale: float = 1.0,
) -> optax.GradientTransformation:
    """`lr_scale` multiplies the peak lr WITHOUT changing the opt-state
    tree structure (it scales the schedule, it does not add a
    transform) — the recovery path's lr backoff (trainer.py rollback)
    rebuilds the optimizer at a reduced peak and restores yesterday's
    opt_state into it unchanged."""
    lr = cfg.lr * float(lr_scale)
    if cfg.cosine_schedule and total_steps:
        schedule = optax.cosine_decay_schedule(
            init_value=lr, decay_steps=total_steps, alpha=0.0
        )
    else:
        schedule = lr
    return optax.adam(schedule)


def create_train_state(params, tx: optax.GradientTransformation, seed: int) -> TrainState:
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        rng=jax.random.PRNGKey(seed),
    )


def learning_rate_at(cfg: TrainConfig, total_steps: int, step: int,
                     lr_scale: float = 1.0) -> float:
    """Host-side LR readback for logging (reference logs
    scheduler.get_last_lr(), main.py:83). `lr_scale` mirrors
    make_optimizer's recovery backoff."""
    lr = cfg.lr * float(lr_scale)
    if cfg.cosine_schedule and total_steps:
        import math

        return 0.5 * lr * (1 + math.cos(math.pi * min(step, total_steps) / total_steps))
    return lr
