"""Train state and optimizer assembly.

Optimizer parity with reference main.py:60-61: Adam(lr=1e-4) with a
cosine-annealing schedule whose horizon is (steps-per-epoch x epochs) and
which advances once per optimizer update (the reference steps its
scheduler once per batch, train_model.py:31-32). torch's
CosineAnnealingLR with eta_min=0 is exactly optax's
cosine_decay_schedule(alpha=0).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from factorvae_tpu.config import TrainConfig


@flax.struct.dataclass
class TrainState:
    """Everything needed to resume a run bit-for-bit (the reference saves
    only model weights, main.py:78-79 — optimizer/scheduler state is lost
    on crash; this is the fix called out in SURVEY.md §5)."""

    step: jnp.ndarray            # optimizer updates taken
    params: Any
    opt_state: Any
    rng: jax.Array               # threaded PRNG key (sample/dropout noise)

    def advance_rng(self):
        new_rng, sub = jax.random.split(self.rng)
        return self.replace(rng=new_rng), sub


def make_optimizer(
    cfg: TrainConfig, total_steps: Optional[int] = None,
    lr_scale: float = 1.0,
) -> optax.GradientTransformation:
    """`lr_scale` multiplies the peak lr WITHOUT changing the opt-state
    tree structure (it scales the schedule, it does not add a
    transform) — the recovery path's lr backoff (trainer.py rollback)
    rebuilds the optimizer at a reduced peak and restores yesterday's
    opt_state into it unchanged."""
    lr = cfg.lr * float(lr_scale)
    if cfg.cosine_schedule and total_steps:
        schedule = optax.cosine_decay_schedule(
            init_value=lr, decay_steps=total_steps, alpha=0.0
        )
    else:
        schedule = lr
    return optax.adam(schedule)


def make_hyper_optimizer(
    cfg: TrainConfig, total_steps: Optional[int] = None,
):
    """Optimizer for the hyper-fleet's per-lane learning rates: the same
    Adam as `make_optimizer`, but with the final ``-(lr * decay)``
    multiply DEFERRED to the caller, so the lr can be a runtime per-lane
    scalar riding the vmapped step instead of a trace-baked constant.

    Returns ``(tx, step_size)``:

    - ``tx`` = ``chain(scale_by_adam(), scale_by_schedule(1.0))`` (or
      ``scale(1.0)`` when the cosine schedule is off) — the identity
      multiply keeps the opt-state TREE identical to ``make_optimizer``'s
      (``ScaleByAdamState`` + ``ScaleByScheduleState``/``ScaleState``
      with the same advancing count), so per-lane checkpoint rows stay
      restorable by a serial `Trainer` built at that lane's config, and a
      serial checkpoint drops into a hyper lane unchanged.
    - ``step_size(step, lane_lr)`` reproduces optax's own arithmetic
      exactly — ``-1 * (lane_lr * cosine_decay_schedule(1.0)(step))``,
      the same multiply order ``scale_by_learning_rate`` applies with
      its Python-float init — so a lane whose ``lane_lr`` bit-equals the
      serial run's ``cfg.lr`` takes bit-identical update steps
      (tests/test_hyper.py pins the whole chain).

    The caller applies ``u * step_size`` itself (train/loop.py's hyper
    path), mirroring ``scale_by_schedule``'s
    ``jnp.array(step_size, g.dtype) * g``.
    """
    if cfg.cosine_schedule and total_steps:
        tx = optax.chain(
            optax.scale_by_adam(),
            # identity multiply; exists only to carry the schedule COUNT
            # state the serial optimizer's tree has
            optax.scale_by_schedule(lambda count: 1.0),
        )
        decay = optax.cosine_decay_schedule(
            init_value=1.0, decay_steps=total_steps, alpha=0.0)

        def step_size(step, lane_lr):
            # same expression shape as scale_by_learning_rate's
            # `-1 * schedule(count)` with schedule = init * decayed:
            # one (lane_lr * decayed) rounding, one exact negation
            return -1 * (lane_lr * decay(step))
    else:
        tx = optax.chain(optax.scale_by_adam(), optax.scale(1.0))

        def step_size(step, lane_lr):
            return -1 * lane_lr

    return tx, step_size


def create_train_state(params, tx: optax.GradientTransformation, seed: int) -> TrainState:
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        rng=jax.random.PRNGKey(seed),
    )


def learning_rate_at(cfg: TrainConfig, total_steps: int, step: int,
                     lr_scale: float = 1.0) -> float:
    """Host-side LR readback for logging (reference logs
    scheduler.get_last_lr(), main.py:83). `lr_scale` mirrors
    make_optimizer's recovery backoff."""
    lr = cfg.lr * float(lr_scale)
    if cfg.cosine_schedule and total_steps:
        import math

        return 0.5 * lr * (1 + math.cos(math.pi * min(step, total_steps) / total_steps))
    return lr
