"""Train state and optimizer assembly.

Optimizer parity with reference main.py:60-61: Adam(lr=1e-4) with a
cosine-annealing schedule whose horizon is (steps-per-epoch x epochs) and
which advances once per optimizer update (the reference steps its
scheduler once per batch, train_model.py:31-32). torch's
CosineAnnealingLR with eta_min=0 is exactly optax's
cosine_decay_schedule(alpha=0).

Mixed-precision master/compute split (ISSUE 16, docs/precision.md):
`params` and `opt_state` are ALWAYS float32 — the master weights every
checkpoint row and best-weight artifact carries, serial-format-
compatible regardless of the training dtype. A mixed build (resolved
train dtype != float32, `resolve_train_dtype`) feeds the forward/
backward ONE explicit low-precision cast of the master tree
(`cast_compute`, applied inside the differentiated day loss so the
`astype` transpose returns f32 master gradients) and carries the
dynamic loss scale + consecutive-good-step counter as two extra state
leaves (`mixed_fields`). Float32 builds leave both fields `None` —
an EMPTY pytree subtree, so the state's leaf set (and therefore every
pre-mixed checkpoint and restore template) is byte-identical to the
pre-mixed layout.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from factorvae_tpu.config import TrainConfig


@flax.struct.dataclass
class TrainState:
    """Everything needed to resume a run bit-for-bit (the reference saves
    only model weights, main.py:78-79 — optimizer/scheduler state is lost
    on crash; this is the fix called out in SURVEY.md §5)."""

    step: jnp.ndarray            # optimizer updates taken
    params: Any                  # f32 master weights (mixed builds cast
    opt_state: Any               # a bf16 COPY per step; these never move)
    rng: jax.Array               # threaded PRNG key (sample/dropout noise)
    # Mixed-precision extras (None = absent leaf on f32 builds, so the
    # pytree structure — and every existing checkpoint — is unchanged):
    # the dynamic loss scale (f32 scalar) and the consecutive finite-
    # step counter (int32 scalar) driving its growth schedule.
    loss_scale: Any = None
    good_steps: Any = None

    def advance_rng(self):
        new_rng, sub = jax.random.split(self.rng)
        return self.replace(rng=new_rng), sub


_TRAIN_DTYPES = ("float32", "bfloat16")


def resolve_train_dtype(train_cfg, model_cfg) -> str:
    """The ONE place the training compute dtype is decided.

    ``train.compute_dtype`` wins when set; ``None`` inherits
    ``model.compute_dtype`` — which is how the old naive whole-model
    bf16 cast "resolves through" the mixed master-weight path instead
    of silently training without loss scaling. Anything outside the
    ladder errors loudly (int8 is a SERVING rung — training through a
    quantized forward has no gradient contract; plan.py serve_precision).
    """
    dtype = train_cfg.compute_dtype or model_cfg.compute_dtype
    if dtype not in _TRAIN_DTYPES:
        raise ValueError(
            f"train compute dtype {dtype!r} is not in the training "
            f"ladder {_TRAIN_DTYPES} — int8 and friends are serving "
            "rungs (plan.serve_precision); training runs f32 masters "
            "with an optional bf16 compute cast (docs/precision.md)")
    return dtype


def cast_compute(tree, dtype):
    """The single master->compute cast of a mixed step: every floating
    leaf of the f32 master tree as `dtype`, non-float leaves untouched.
    Applied INSIDE the differentiated loss (train/loop.py), so the
    `astype` transpose hands f32 cotangents straight back to the f32
    masters — there is no second cast site to drift from."""
    return jax.tree.map(
        lambda p: p.astype(dtype)
        if jnp.issubdtype(p.dtype, jnp.floating) else p, tree)


def mixed_fields(cfg: TrainConfig) -> dict:
    """The two extra TrainState leaves a mixed build carries (f32 builds
    leave them None): the dynamic loss scale seeded at
    ``loss_scale_init`` and the consecutive-good-step counter."""
    return {
        "loss_scale": jnp.asarray(cfg.loss_scale_init, jnp.float32),
        "good_steps": jnp.zeros((), jnp.int32),
    }


def make_optimizer(
    cfg: TrainConfig, total_steps: Optional[int] = None,
    lr_scale: float = 1.0,
) -> optax.GradientTransformation:
    """`lr_scale` multiplies the peak lr WITHOUT changing the opt-state
    tree structure (it scales the schedule, it does not add a
    transform) — the recovery path's lr backoff (trainer.py rollback)
    rebuilds the optimizer at a reduced peak and restores yesterday's
    opt_state into it unchanged."""
    lr = cfg.lr * float(lr_scale)
    if cfg.cosine_schedule and total_steps:
        schedule = optax.cosine_decay_schedule(
            init_value=lr, decay_steps=total_steps, alpha=0.0
        )
    else:
        schedule = lr
    return optax.adam(schedule)


def make_hyper_optimizer(
    cfg: TrainConfig, total_steps: Optional[int] = None,
):
    """Optimizer for the hyper-fleet's per-lane learning rates: the same
    Adam as `make_optimizer`, but with the final ``-(lr * decay)``
    multiply DEFERRED to the caller, so the lr can be a runtime per-lane
    scalar riding the vmapped step instead of a trace-baked constant.

    Returns ``(tx, step_size)``:

    - ``tx`` = ``chain(scale_by_adam(), scale_by_schedule(1.0))`` (or
      ``scale(1.0)`` when the cosine schedule is off) — the identity
      multiply keeps the opt-state TREE identical to ``make_optimizer``'s
      (``ScaleByAdamState`` + ``ScaleByScheduleState``/``ScaleState``
      with the same advancing count), so per-lane checkpoint rows stay
      restorable by a serial `Trainer` built at that lane's config, and a
      serial checkpoint drops into a hyper lane unchanged.
    - ``step_size(step, lane_lr)`` reproduces optax's own arithmetic
      exactly — ``-1 * (lane_lr * cosine_decay_schedule(1.0)(step))``,
      the same multiply order ``scale_by_learning_rate`` applies with
      its Python-float init — so a lane whose ``lane_lr`` bit-equals the
      serial run's ``cfg.lr`` takes bit-identical update steps
      (tests/test_hyper.py pins the whole chain).

    The caller applies ``u * step_size`` itself (train/loop.py's hyper
    path), mirroring ``scale_by_schedule``'s
    ``jnp.array(step_size, g.dtype) * g``.
    """
    if cfg.cosine_schedule and total_steps:
        tx = optax.chain(
            optax.scale_by_adam(),
            # identity multiply; exists only to carry the schedule COUNT
            # state the serial optimizer's tree has
            optax.scale_by_schedule(lambda count: 1.0),
        )
        decay = optax.cosine_decay_schedule(
            init_value=1.0, decay_steps=total_steps, alpha=0.0)

        def step_size(step, lane_lr):
            # same expression shape as scale_by_learning_rate's
            # `-1 * schedule(count)` with schedule = init * decayed:
            # one (lane_lr * decayed) rounding, one exact negation
            return -1 * (lane_lr * decay(step))
    else:
        tx = optax.chain(optax.scale_by_adam(), optax.scale(1.0))

        def step_size(step, lane_lr):
            return -1 * lane_lr

    return tx, step_size


def create_train_state(
    params, tx: optax.GradientTransformation, seed: int,
    train_cfg: Optional[TrainConfig] = None,
    compute_dtype: str = "float32",
) -> TrainState:
    """`compute_dtype` != float32 (a mixed build; pass the RESOLVED
    dtype + the TrainConfig carrying the scaling knobs) seeds the
    loss-scale leaves; the default leaves them None — the exact
    pre-mixed state layout."""
    extra = (mixed_fields(train_cfg)
             if train_cfg is not None and compute_dtype != "float32"
             else {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        rng=jax.random.PRNGKey(seed),
        **extra,
    )


def learning_rate_at(cfg: TrainConfig, total_steps: int, step: int,
                     lr_scale: float = 1.0) -> float:
    """Host-side LR readback for logging (reference logs
    scheduler.get_last_lr(), main.py:83). `lr_scale` mirrors
    make_optimizer's recovery backoff."""
    lr = cfg.lr * float(lr_scale)
    if cfg.cosine_schedule and total_steps:
        import math

        return 0.5 * lr * (1 + math.cos(math.pi * min(step, total_steps) / total_steps))
    return lr
