"""On-device population-based training over the hyper-fleet (ISSUE 12).

One FleetTrainer, one compiled program, G generations: every lane races
its own (lr, kl_weight) as RUNTIME scalars of the stacked hyper trace
(train/fleet.py), so the exploit/explore loop perturbs hyperparameters
between generations with ZERO recompiles — the per-lane scalars are just
fresh (S,) inputs at the next epoch dispatch.

The three PBT steps reuse machinery that already exists:

- **Fitness** — the per-lane validation loss the fleet epoch loop
  already finalizes on device (the same `jnp.where` best-val selection
  signal; obs probes ride the same record as telemetry).
- **Exploit** — a losing lane is restored from a WINNER's per-lane
  checkpoint: the winner's last lockstep full-state row is copied into
  the loser's checkpoint directory (Checkpointer.save overwrites the
  step — the PR 9 rollback discipline), and the next generation's
  `fit(resume=True)` splices it in through the ordinary group-resume
  path. No new restore code; the per-lane rollback machinery carries it.
- **Explore** — DETERMINISTIC per-lane perturbation: the loser's lane
  scalars are multiplied by `perturb_factors[(generation + lane) % n]`
  (no host RNG — a resumed run replays the same walk), clipped to the
  configured bounds.

Resume discipline (tests/test_hyper.py TestPBT): the controller
persists `{generation, per-lane scalars}` to `<save_dir>/<run>_pbt.json`
after every generation (atomic rename). A killed run resumed with
``pbt_fit(..., resume=True)`` reconstructs the lane scalars, restores
every lane from its lockstep checkpoints and continues BITWISE the
unbroken run — generations are just `fit(resume=True)` windows over the
same per-lane checkpoint layout an unbroken run writes.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Sequence

import numpy as np

from factorvae_tpu.config import Config
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.train.fleet import FleetTrainer, unstack_state
from factorvae_tpu.utils.logging import MetricsLogger


def perturb_factor(generation: int, lane: int,
                   factors: Sequence[float]) -> float:
    """The deterministic explore rule: which factor multiplies a losing
    lane's scalars at generation `generation`. Pure — the resume path
    replays the identical walk."""
    return float(factors[(int(generation) + int(lane)) % len(factors)])


def _pbt_state_path(config: Config) -> str:
    return os.path.join(config.train.save_dir,
                        f"{config.train.run_name}_pbt.json")


def _write_pbt_state(path: str, generation: int, lanes: list) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"generation": generation, "lanes": lanes}, f, indent=1)
    os.replace(tmp, path)


def pbt_fit(
    config: Config,
    dataset: PanelDataset,
    lane_configs: Sequence[Config],
    generations: int,
    epochs_per_generation: int,
    exploit_frac: float = 0.25,
    perturb_factors: Sequence[float] = (0.8, 1.25),
    lr_bounds: tuple = (1e-6, 1e-1),
    kl_weight_bounds: tuple = (1e-4, 10.0),
    logger: Optional[MetricsLogger] = None,
    mesh=None,
    resume: bool = False,
    stop_after: Optional[int] = None,
):
    """Run G generations of PBT over one hyper-fleet program.

    ``lane_configs`` seeds the population (per-lane lr/kl_weight/seed;
    `train/fleet.validate_lane_configs` rules apply — distinct run_names
    for same-seed lanes). ``config.train.num_epochs`` is overridden to
    ``generations * epochs_per_generation`` (the cosine horizon of the
    whole run) and ``checkpoint_every`` must be >= 1: the lockstep
    per-lane checkpoints ARE the exploit transport and the resume
    substrate.

    Returns ``(trainer, result)`` where result has per-generation
    records (fitness, winners, exploited lanes, the scalar walk) and
    the final ``lane_configs`` / ``state`` / ``best_val``.

    ``stop_after=g`` ends the run after generation ``g`` completes
    (exploit/explore/persist included) — the deterministic "killed at a
    generation boundary" the bitwise-resume tests and chaos harnesses
    drive; a later ``resume=True`` call continues exactly where the
    stopped run would have.
    """
    logger = logger or MetricsLogger(echo=False)
    generations = int(generations)
    epg = int(epochs_per_generation)
    if generations < 1 or epg < 1:
        raise ValueError("need generations >= 1 and "
                         "epochs_per_generation >= 1")
    total_epochs = generations * epg
    config = dataclasses.replace(
        config, train=dataclasses.replace(config.train,
                                          num_epochs=total_epochs))
    if not config.train.checkpoint_every:
        raise ValueError(
            "PBT needs checkpoint_every >= 1: the lockstep per-lane "
            "checkpoints carry the exploit step and the resume path")
    lane_cfgs = [
        dataclasses.replace(
            c, train=dataclasses.replace(c.train,
                                         num_epochs=total_epochs))
        for c in lane_configs
    ]
    state_path = _pbt_state_path(config)
    start_gen = 0
    if resume and os.path.exists(state_path):
        with open(state_path) as f:
            saved = json.load(f)
        if len(saved.get("lanes", [])) != len(lane_cfgs):
            raise ValueError(
                f"PBT state at {state_path} has "
                f"{len(saved.get('lanes', []))} lanes; this run has "
                f"{len(lane_cfgs)} — population size cannot change "
                "across a resume")
        start_gen = int(saved["generation"])
        lane_cfgs = [
            dataclasses.replace(
                c,
                model=dataclasses.replace(
                    c.model, kl_weight=float(s["kl_weight"])),
                train=dataclasses.replace(c.train, lr=float(s["lr"])),
            )
            for c, s in zip(lane_cfgs, saved["lanes"])
        ]
        logger.log("pbt_resume", generation=start_gen,
                   lanes=saved["lanes"])

    # force_hyper: an initially homogeneous population would otherwise
    # fold to the constant-baked trace, and the first explore step
    # would have no runtime scalar input to move.
    trainer = FleetTrainer(config, dataset, lane_configs=lane_cfgs,
                           logger=logger, mesh=mesh, force_hyper=True)
    num_lanes = trainer.num_seeds
    n_exploit = (max(1, int(round(num_lanes * float(exploit_frac))))
                 if num_lanes > 1 else 0)
    n_exploit = min(n_exploit, num_lanes // 2)

    gen_records = []
    state = out = None
    for gen in range(start_gen, generations):
        state, out = trainer.fit(num_epochs=(gen + 1) * epg,
                                 resume=(gen > 0 or resume))
        last = out["history"][-1] if out["history"] else None
        if last is not None:
            fitness = np.asarray(
                last["val_loss"]
                if np.isfinite(np.asarray(last["val_loss"])).any()
                else last["train_loss"], np.float64)
        else:
            # Killed between this generation's final checkpoint commit
            # and the PBT-state write: the resumed fit() restored at
            # the generation's last epoch and had nothing to train, so
            # there is no history to read fitness from. Recompute it
            # from the RESTORED params with the SAME eval key/order the
            # unbroken run's last epoch used — the select (and the
            # whole exploit/explore step) then replays bitwise instead
            # of ranking on garbage.
            val_order = trainer._val_order()
            if val_order is not None:
                m = trainer._run_eval_epoch(state.params, val_order,
                                            (gen + 1) * epg - 1)
                fitness = np.asarray(m["loss"], np.float64)
            else:
                fitness = np.asarray(out["best_val"], np.float64)
        # NaN lanes rank LAST (a diverged lane is the exploit target,
        # never a winner).
        order = np.argsort(np.where(np.isfinite(fitness), fitness,
                                    np.inf), kind="stable")
        winners = [int(i) for i in order[:max(1, n_exploit)]]
        losers = ([int(i) for i in order[-n_exploit:]]
                  if n_exploit else [])
        rec = {
            "generation": gen,
            "epochs": [gen * epg, (gen + 1) * epg],
            "fitness": [float(v) for v in fitness],
            "lane_labels": trainer.lane_labels(),
            "winners": winners,
            "exploited": [],
        }
        if gen < generations - 1 and losers:
            gather_epoch = (gen + 1) * epg - 1
            for j, loser in enumerate(losers):
                winner = winners[j % len(winners)]
                if loser == winner:
                    continue
                # ---- explore: deterministic scalar perturbation ------
                f = perturb_factor(gen, loser, perturb_factors)
                w_cfg = trainer.lane_cfgs[winner]
                new_lr = float(np.clip(w_cfg.train.lr * f,
                                       *lr_bounds))
                new_klw = float(np.clip(w_cfg.model.kl_weight * f,
                                        *kl_weight_bounds))
                trainer.set_lane_scalars(loser, lr=new_lr,
                                         kl_weight=new_klw)
                # ---- exploit: winner's checkpoint row -> loser's dir -
                # (PR 9's per-lane rollback transport: restore from the
                # winner's Checkpointer, overwrite-save into the
                # loser's; the next fit(resume=True) group-restore
                # splices it in.)
                template = unstack_state(trainer._stacked(state), loser)
                w_ckpt = trainer._lane_checkpointer(winner)
                row, w_meta = w_ckpt.restore(template, step=gather_epoch)
                l_ckpt = trainer._lane_checkpointer(loser)
                l_ckpt.save(
                    gather_epoch,
                    row,
                    {"epoch": gather_epoch,
                     "best_val": float(out["best_val"][loser]),
                     "config": trainer.lane_cfgs[loser].to_dict(),
                     "clean": True},
                )
                rec["exploited"].append(
                    {"lane": loser, "from": winner,
                     "perturb_factor": f, "lr": new_lr,
                     "kl_weight": new_klw})
            # Drain the exploit overwrites before the next generation's
            # group-restore opens fresh readers on the same dirs: an
            # async save still in flight would be invisible to them.
            trainer._close_checkpointers()
        gen_records.append(rec)
        logger.log("pbt_generation", **{
            k: v for k, v in rec.items() if k != "fitness"},
            best_fitness=float(np.nanmin(np.where(
                np.isfinite(fitness), fitness, np.nan)))
            if np.isfinite(fitness).any() else float("nan"))
        _write_pbt_state(
            state_path, gen + 1,
            [{"lr": c.train.lr, "kl_weight": c.model.kl_weight}
             for c in trainer.lane_cfgs])
        if stop_after is not None and gen >= stop_after:
            logger.log("pbt_stopped", after_generation=gen)
            break

    return trainer, {
        "generations": gen_records,
        "lane_configs": list(trainer.lane_cfgs),
        "state": state,
        "best_val": out["best_val"] if out is not None else None,
        "best_params": out["best_params"] if out is not None else None,
    }
