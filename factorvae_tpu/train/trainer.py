"""The experiment driver: wires config + data + model + optimizer + mesh
into an epoch loop with best-validation tracking, checkpoint/resume and a
structured metric stream.

Capability parity with reference main.py:19-87 (seeding, module assembly,
loader construction, Adam + cosine schedule, epoch loop, best-val save,
optional wandb), plus what the reference lacks: full-state resume, mesh
parallelism and on-device epoch execution.

Multi-seed workloads (seed sweeps, the parity protocol) have a
seed-parallel sibling: `train.fleet.FleetTrainer` trains S seeds of one
config simultaneously by vmapping this module's epoch functions over a
stacked TrainState — same artifacts, per-seed names; a 1-seed fleet is
bitwise this Trainer.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from factorvae_tpu.config import Config
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.models.factorvae import day_forward
from factorvae_tpu.parallel import compose
from factorvae_tpu.parallel.mesh import make_mesh
from factorvae_tpu.parallel.sharding import (
    chunk_placement,
    make_batch_constraint,
    order_sharding,
    panel_shardings,
    replicated,
    shard_dataset,
)
from factorvae_tpu.train.checkpoint import Checkpointer, save_params
from factorvae_tpu.train.loop import concat_auxes, make_step_fns
from factorvae_tpu.train.state import (
    TrainState,
    create_train_state,
    learning_rate_at,
    make_optimizer,
    resolve_train_dtype,
)
from factorvae_tpu.utils.logging import (
    MetricsLogger,
    timeline_event,
    timeline_span,
)


class Trainer:
    def __init__(
        self,
        config: Config,
        dataset: PanelDataset,
        mesh: Optional[object] = None,
        logger: Optional[MetricsLogger] = None,
        use_mesh: bool = False,
    ):
        self.cfg = config
        self.ds = dataset
        self.logger = logger or MetricsLogger(echo=False)

        self.train_days = dataset.split_days(
            config.data.start_time, config.data.fit_end_time
        )
        self.val_days = dataset.split_days(
            config.data.val_start_time, config.data.val_end_time
        )
        if len(self.train_days) == 0:
            raise ValueError("empty training split")

        self.batch_days = max(1, config.train.days_per_step)
        self.steps_per_epoch = -(-len(self.train_days) // self.batch_days)
        self.total_steps = self.steps_per_epoch * config.train.num_epochs

        # Streaming residency (plan.panel_residency="stream"): the panel
        # is host-resident and epochs consume double-buffered prefetched
        # chunks (data/stream.py) — bitwise the HBM epochs.
        self.stream = getattr(dataset, "residency", "hbm") == "stream"
        self.steps_per_chunk = max(
            1, config.data.stream_chunk_days // self.batch_days)

        # mesh (optional; single device works without one). The
        # composition matrix — mesh x stream included since PR 6 — is
        # validated in ONE place (parallel/compose.py).
        self.mesh = mesh if mesh is not None else (
            make_mesh(config.mesh) if use_mesh else None
        )
        compose.validate(
            mesh=self.mesh,
            residency=getattr(dataset, "residency", "hbm"),
            days_per_step=self.batch_days,
            stream_chunk_days=config.data.stream_chunk_days,
        )
        shard_batch = None
        if self.mesh is not None:
            # HBM residency: re-place the panel onto the mesh once.
            # Stream residency: a documented no-op — each prefetched
            # mini-panel chunk is placed per the SAME panel rules by
            # chunk_placement instead.
            shard_dataset(self.mesh, dataset)
            shard_batch = make_batch_constraint(self.mesh)

        # model + optimizer. The TRAINING compute dtype resolves in one
        # place (train/state.resolve_train_dtype): train.compute_dtype
        # wins, None inherits model.compute_dtype — so the old naive
        # whole-model bf16 cast now routes through the mixed
        # master-weight path (f32 params/opt_state, one compute cast,
        # dynamic loss scaling) instead of training unscaled. An
        # explicit train.compute_dtype="float32" forces the bitwise f32
        # oracle under a bf16 serving/scoring model.
        self._train_dtype = resolve_train_dtype(config.train, config.model)
        self._mixed = self._train_dtype != "float32"
        model_cfg = config.model
        if model_cfg.compute_dtype != self._train_dtype:
            import dataclasses

            model_cfg = dataclasses.replace(
                model_cfg, compute_dtype=self._train_dtype)
        self.model = day_forward(model_cfg, train=True)
        self.model_eval = day_forward(model_cfg, train=False)
        self._shard_batch = shard_batch
        self._build_step_fns()

        # The effective execution layout, as one structured record — the
        # training-loop counterpart of bench.py's `plan` block, so a
        # metrics stream always shows which configuration (planner-chosen
        # or hand-set) actually ran (cli --auto_plan logs the planner's
        # decision + provenance separately as "plan").
        self.logger.log(
            "execution_layout",
            flatten_days=config.model.flatten_days,
            days_per_step=self.batch_days,
            # the dtype the TRAINING programs actually run (resolved
            # through the precision ladder), not the raw model knob —
            # the stale pre-mixed seam logged the model dtype even when
            # it never reached the hot loop
            compute_dtype=self._train_dtype,
            model_compute_dtype=config.model.compute_dtype,
            mixed_precision=self._mixed,
            n_real=getattr(dataset, "n_real", dataset.n_max),
            n_padded=dataset.n_max,
            dead_compute_frac=round(
                getattr(dataset, "dead_compute_frac", 0.0), 4),
            obs_probes=config.train.obs_probes,
        )
        if self.mesh is not None:
            # Rule-table shard-balance bill (obs/memory.py): per-device
            # bytes of the replicated state + the 'stock'-sharded panel
            # and the imbalance fraction — abstract shapes only, logged
            # once so an uneven axis is visible before it straggles.
            # Guarded like every other observation path: telemetry must
            # never abort the construction it observes.
            try:
                from factorvae_tpu.obs.memory import shard_balance_block

                self.logger.log("shard_balance", **shard_balance_block(
                    self.mesh, state=jax.eval_shape(self.init_state),
                    dataset=dataset))
            except Exception as e:
                self.logger.log("shard_balance", error=str(e))

    def _build_step_fns(self) -> None:
        """(Re)build optimizer + jitted epoch fns for the current
        `self.total_steps`. Called again by `fit(num_epochs=...)` when the
        override changes the cosine-schedule horizon (ADVICE round 1: the
        LR horizon must follow the actual run length), and by the
        recovery rollback when it backs the peak lr off
        (`self._lr_scale`; the opt-state TREE is unchanged, so the
        restored optimizer state drops in)."""
        from factorvae_tpu import chaos

        cfg = self.cfg
        self._lr_scale = getattr(self, "_lr_scale", 1.0)
        # Trace-time chaos gate: poison only exists on traces built
        # while a nan_grads fault is installed (tests/bench); a chaos-
        # free build compiles a program with no poison argument at all.
        self._inject = chaos.has_fault("nan_grads")
        self.tx = make_optimizer(cfg.train, self.total_steps,
                                 lr_scale=self._lr_scale)
        self.fns = make_step_fns(
            self.model,
            self.model_eval,
            self.tx,
            cfg.data.seq_len,
            shard_batch=self._shard_batch,
            obs=cfg.train.obs_probes,
            guard=cfg.train.finite_guard,
            inject_nan=self._inject,
            compute_dtype=self._train_dtype,
            loss_scale_cfg=(
                cfg.train.loss_scale_growth, cfg.train.loss_scale_backoff,
                cfg.train.loss_scale_growth_interval,
                cfg.train.loss_scale_floor) if self._mixed else None,
            remat=cfg.train.remat,
        )

        # Every jit goes through the compile watchdog (obs/watchdog.py):
        # a pure passthrough unless a timeline is installed, in which
        # case cache misses become jit_compile spans and retrace storms
        # are flagged in RUN.jsonl.
        from factorvae_tpu.obs.watchdog import watch_jit

        donate = (0,)
        # The eval-epoch jits deliberately donate NOTHING — including
        # the eval key (ISSUE 19 revisited the ROADMAP-item-3 question
        # with the JIR002 audit): a (2,) uint32 key has no shape/dtype-
        # matching output among the f32 scalar metrics, so XLA drops
        # the donation silently (zero `input_output_alias` entries;
        # jax warns "donated buffers were not usable"). Donating would
        # free zero bytes, add a standing JIR002 finding, and poison
        # host-side key reuse (tests/test_train.py recomputes the
        # sample-weighted metric from the same key — the oracle
        # pattern). The STREAM eval-chunk jit below is the opposite
        # case: its key threads through and returns, so that donation
        # verifies as a real alias.
        # Chaos traces carry one extra replicated scalar (the poison
        # multiplier) on the train entry points.
        extra = (replicated(self.mesh),) if (
            self._inject and self.mesh is not None) else ()
        if self.mesh is not None:
            rep = replicated(self.mesh)
            ord_s = order_sharding(self.mesh)
            pan_s = panel_shardings(self.mesh)
            # `rep` as a prefix pytree replicates the whole state/metrics
            self._train_epoch_jit = watch_jit(jax.jit(
                self.fns.train_epoch,
                donate_argnums=donate,
                in_shardings=(rep, ord_s, pan_s) + extra,
                out_shardings=(rep, rep),
            ), "train_epoch")
            self._eval_epoch_jit = watch_jit(jax.jit(
                self.fns.eval_epoch, in_shardings=(rep, ord_s, rep, pan_s),
                out_shardings=rep,
            ), "eval_epoch")
        else:
            self._train_epoch_jit = watch_jit(jax.jit(
                self.fns.train_epoch, donate_argnums=donate), "train_epoch")
            self._eval_epoch_jit = watch_jit(
                jax.jit(self.fns.eval_epoch), "eval_epoch")
        if self.stream:
            # Chunked stream-epoch programs: the same step bodies scanned
            # over prefetched batches + the shared metric finalizers
            # (train/loop.py docstrings spell out the bitwise contract).
            # Under a mesh the chunk jits take the SAME shardings the
            # whole-epoch jits take — mini-panels share the full panel's
            # axis layout, so one rule table covers both (and keeps
            # mesh x stream bitwise mesh x hbm: identical partitioned
            # step graphs).
            chunk_kw = {}
            eval_chunk_kw = {}
            if self.mesh is not None:
                rep = replicated(self.mesh)
                ord_s = order_sharding(self.mesh)
                pan_s = panel_shardings(self.mesh)
                # out_shardings pin the carried state (and the returned
                # eval key) replicated: the state is a fixed point of
                # the chunk jit, and an unpinned output lets GSPMD
                # re-shard a leaf that then mismatches the next call's
                # explicit in_shardings.
                chunk_kw = dict(in_shardings=(rep, ord_s, pan_s) + extra,
                                out_shardings=(rep, rep))
                eval_chunk_kw = dict(in_shardings=(rep, ord_s, rep, pan_s),
                                     out_shardings=rep)
            self._train_chunk_jit = watch_jit(jax.jit(
                self.fns.train_chunk, donate_argnums=donate, **chunk_kw),
                "train_chunk")
            # Donation audit (ISSUE 16): the eval chunk's threaded key
            # rebinds every chunk (`key, aux = jit(...)`) — its input
            # buffer is dead on return, so donate it; likewise the
            # finalizers consume the chunk-concatenated aux stacks,
            # which nothing reads afterwards. No-ops where the backend
            # doesn't support donation; the epoch-jit state donation
            # precedent applies.
            self._eval_chunk_jit = watch_jit(
                jax.jit(self.fns.eval_chunk, donate_argnums=(2,),
                        **eval_chunk_kw), "eval_chunk")
            self._finalize_train_jit = watch_jit(
                jax.jit(self.fns.finalize_train, donate_argnums=(0,)),
                "finalize_train")
            self._finalize_eval_jit = watch_jit(
                jax.jit(self.fns.finalize_eval, donate_argnums=(0,)),
                "finalize_eval")
            self._chunk_placement = (
                chunk_placement(self.mesh) if self.mesh is not None
                else None)

    def panel_args(self):
        """The HBM panel as explicit jit arguments (loop.py: passing these
        instead of closing over them keeps the ~O(100 MB) panel out of the
        compile payload)."""
        return (self.ds.values, self.ds.last_valid, self.ds.next_valid)

    def _globalize(self, tree, sharding):
        """Multi-process meshes need explicitly global inputs: every
        process holds identical host values (same seeds), so each leaf
        not already spanning processes is re-placed via
        multihost.global_put. Single-process is a no-op."""
        if jax.process_count() == 1:
            return tree
        from factorvae_tpu.parallel.multihost import global_put, is_global

        return jax.tree_util.tree_map(
            lambda x: x if is_global(x) else global_put(x, sharding), tree
        )

    def _poison(self, epoch: int) -> tuple:
        """Extra train-entry-point args for chaos traces: () normally;
        (scalar,) when this build injects — NaN where a `nan_grads`
        fault targets this epoch (consuming one firing), an exact 1.0
        multiply elsewhere."""
        if not self._inject:
            return ()
        from factorvae_tpu import chaos

        hit = chaos.fault("nan_grads", epoch=epoch) is not None
        return (jnp.float32(float("nan") if hit else 1.0),)

    def _train_epoch(self, state, order, epoch: int = 0):
        poison = self._poison(epoch)
        if self.stream:
            if self.mesh is not None:
                state = self._globalize(state, replicated(self.mesh))
            return self._train_epoch_stream(state, order, poison)
        if self.mesh is not None:
            state = self._globalize(state, replicated(self.mesh))
            order = self._globalize(
                jnp.asarray(order), order_sharding(self.mesh))
        return self._train_epoch_jit(state, order, self.panel_args(),
                                     *poison)

    def _eval_epoch(self, params, order, key):
        if self.stream:
            if self.mesh is not None:
                params = self._globalize(params, replicated(self.mesh))
                key = self._globalize(key, replicated(self.mesh))
            return self._eval_epoch_stream(params, order, key)
        if self.mesh is not None:
            params = self._globalize(params, replicated(self.mesh))
            key = self._globalize(key, replicated(self.mesh))
            order = self._globalize(
                jnp.asarray(order), order_sharding(self.mesh))
        return self._eval_epoch_jit(params, order, key, self.panel_args())

    # ---- streaming residency -----------------------------------------

    def _train_epoch_stream(self, state, order, poison: tuple = ()):
        """Chunked stream epoch: the prefetcher gathers + device_puts
        chunk k+1 on a worker thread while the jitted scan consumes
        chunk k. Step order, RNG stream, updates and the metric
        reduction are exactly the whole-epoch scan's (bitwise; pinned
        in tests/test_stream.py)."""
        from factorvae_tpu.data.stream import stream_epoch_batches

        chunks = stream_epoch_batches(
            self.ds, np.asarray(order), self.steps_per_chunk,
            placement=self._chunk_placement)
        parts = []
        for order_local, panel_chunk in chunks:
            state, aux = self._train_chunk_jit(state, order_local,
                                               panel_chunk, *poison)
            parts.append(aux)
        self.last_stream_stats = chunks
        return state, self._finalize_train_jit(concat_auxes(parts))

    def _eval_epoch_stream(self, params, order, key):
        from factorvae_tpu.data.stream import stream_epoch_batches

        chunks = stream_epoch_batches(
            self.ds, np.asarray(order), self.steps_per_chunk,
            placement=self._chunk_placement)
        parts = []
        for order_local, panel_chunk in chunks:
            key, aux = self._eval_chunk_jit(params, order_local, key,
                                            panel_chunk)
            parts.append(aux)
        return self._finalize_eval_jit(concat_auxes(parts))

    # ------------------------------------------------------------------

    def init_state(self) -> TrainState:
        """Seeded module assembly (reference main.py:21,27-33)."""
        cfg = self.cfg
        key = jax.random.PRNGKey(cfg.train.seed)
        k_param, k_sample, k_drop = jax.random.split(key, 3)
        b, n = self.batch_days, self.ds.n_max
        # init dummies are pinned f32 regardless of the plan's compute
        # dtype: param init must not depend on the execution layout
        x = jnp.zeros((b, n, cfg.data.seq_len, cfg.model.num_features),
                      jnp.float32)
        y = jnp.zeros((b, n), jnp.float32)
        mask = jnp.ones((b, n), bool)
        params = self.model.init(
            {"params": k_param, "sample": k_sample, "dropout": k_drop}, x, y, mask
        )
        return create_train_state(params, self.tx, cfg.train.seed,
                                  train_cfg=cfg.train,
                                  compute_dtype=self._train_dtype)

    def _epoch_orders(self, epoch: int):
        cfg = self.cfg
        train_order = self.ds.epoch_order(
            self.train_days,
            shuffle=True,
            seed=cfg.train.seed,
            epoch=epoch,
            pad_to=self.batch_days,
        ).reshape(-1, self.batch_days)
        return jnp.asarray(train_order)

    def _val_order(self):
        if len(self.val_days) == 0:
            return None
        order = self.ds.epoch_order(
            self.val_days, shuffle=False, seed=0, epoch=0, pad_to=self.batch_days
        ).reshape(-1, self.batch_days)
        return jnp.asarray(order)

    # ------------------------------------------------------------------

    def fit(
        self,
        state: Optional[TrainState] = None,
        resume: bool = False,
        num_epochs: Optional[int] = None,
        rescale_schedule: bool = False,
    ):
        """Train for `num_epochs` (default: the config value).

        `num_epochs` alone means "run the FIRST N epochs of the configured
        schedule": the cosine horizon stays at `cfg.train.num_epochs` so a
        partial run + resume reproduces an unbroken run exactly (see
        TestCheckpointResume). Pass `rescale_schedule=True` to instead
        treat N as the whole run length and rebuild the optimizer so the
        cosine schedule decays to its floor at epoch N (ADVICE round 1:
        the two meanings must be explicit, not silently conflated).
        """
        cfg = self.cfg
        # `is None` (not `or`): num_epochs=0 means "train zero epochs",
        # not "fall back to the config value" (ADVICE round 1).
        epochs = cfg.train.num_epochs if num_epochs is None else num_epochs
        # Without rescale_schedule the horizon is ALWAYS the config's —
        # including restoring it after an earlier rescale_schedule=True fit
        # on this Trainer (a stale shrunken horizon would pin the LR at the
        # cosine floor for the whole run).
        total = self.steps_per_epoch * (
            epochs if rescale_schedule else cfg.train.num_epochs
        )
        if total != self.total_steps:
            self.total_steps = total
            self._build_step_fns()
        ckpt = None
        start_epoch = 0
        best_val = float("inf")
        if cfg.train.checkpoint_every:
            ckpt = Checkpointer(
                f"{cfg.train.save_dir}/{cfg.checkpoint_name()}_ckpt",
                keep=cfg.train.keep_checkpoints,
                async_save=cfg.train.async_checkpointing,
            )
        # Host-side recovery escalation (docs/robustness.md): a streak of
        # `recover_after` consecutive bad epochs — non-finite train loss,
        # finite-guard skipped updates, or (with obs probes) non-finite
        # gradient elements — rolls back to the last checkpoint written
        # before the streak, backs the peak lr off by
        # `recover_lr_backoff`, and replays. Bounded by
        # `recover_max_rollbacks` per fit.
        recover_after = max(0, int(cfg.train.recover_after))
        bad_streak = 0
        rollbacks = 0
        last_good_step: Optional[int] = None
        if state is None:
            state = self.init_state()
            if resume and ckpt is not None and ckpt.latest_step() is not None:
                state, meta = ckpt.restore(state)
                start_epoch = int(meta.get("epoch", 0)) + 1
                # Only a checkpoint saved at an epoch with NO bad
                # signal may anchor a future rollback (the meta
                # records it; pre-ISSUE-9 checkpoints default to
                # clean): resuming from a mid-bad-streak cadence save
                # must not make the hazard state a rollback target.
                if meta.get("clean", True):
                    last_good_step = start_epoch - 1
                best_val = float(meta.get("best_val", best_val))
                saved_cfg = meta.get("config")
                if saved_cfg is not None and saved_cfg != cfg.to_dict():
                    diff = {
                        k
                        for k in set(saved_cfg) | set(cfg.to_dict())
                        if saved_cfg.get(k) != cfg.to_dict().get(k)
                    }
                    self.logger.log(
                        "resume_config_mismatch",
                        sections=sorted(diff),
                        note="resuming with a different config than the "
                             "checkpoint was written with",
                    )
                self.logger.log("resume", epoch=start_epoch, best_val=best_val)

        import os

        from factorvae_tpu.utils.profiling import (
            maybe_profile_epoch,
            step_annotation,
            summarize_capture,
        )

        # On-demand profiling (ISSUE 10): a PROFILE_REQUEST drop-in
        # next to the metrics stream captures the next train epoch.
        # Only metric-stream runs poll (one exists() per epoch); the
        # default path never stats the filesystem.
        run_dir = (os.path.dirname(os.path.abspath(
            self.logger.jsonl_path)) if self.logger.jsonl_path else None)

        val_order = self._val_order()
        history = []
        epoch = start_epoch
        while epoch < epochs:
            t0 = time.perf_counter()
            order = self._epoch_orders(epoch)
            # The timeline span shares its name with the profiler
            # step_annotation so host spans cross-link with --profile
            # device lanes; the float() sync inside the span makes the
            # span cover the device work, not just the dispatch.
            with maybe_profile_epoch(run_dir, epoch) as (prof, prof_dir), \
                    step_annotation(f"train_epoch_{epoch}"), \
                    timeline_span(f"train_epoch_{epoch}", cat="train",
                                  resource="device", epoch=epoch):
                state, train_m = self._train_epoch(state, order, epoch)
                train_loss = float(train_m["loss"])
            if prof:
                # summarize the on-demand capture into the same stream
                # (guarded: telemetry never aborts the epoch loop)
                self.logger.log("profile_capture", epoch=epoch,
                                dir=prof_dir,
                                **summarize_capture(prof_dir, top=5))
            elif prof_dir:
                # a request WAS consumed but the capture could not
                # start (profiler busy, unwritable dir) — say so
                self.logger.log("profile_capture", epoch=epoch,
                                error=prof_dir)
            if val_order is not None:
                eval_key = jax.random.fold_in(
                    jax.random.PRNGKey(cfg.train.seed + 1), epoch
                )
                with timeline_span(f"val_epoch_{epoch}", cat="eval",
                                   resource="device", epoch=epoch):
                    val_m = self._eval_epoch(state.params, val_order,
                                             eval_key)
                    val_loss = float(val_m["loss"])
                selection_loss = val_loss
            else:
                # No validation split: select the best epoch on train loss
                # so the best-weights export still gets written.
                val_loss = float("nan")
                selection_loss = train_loss
            dt = time.perf_counter() - t0
            lr = learning_rate_at(cfg.train, self.total_steps,
                                  int(state.step), lr_scale=self._lr_scale)
            rec = dict(
                epoch=epoch,
                train_loss=train_loss,
                val_loss=val_loss,
                # Loss decomposition (already accumulated on device by
                # loop.train_epoch): recon is a mean over stocks, kl a
                # sum over K (module.py:261,268) — their relative
                # magnitude is the K-scaling diagnostic VERDICT r4 #2
                # asks about, so it belongs in the metric stream.
                train_recon=float(train_m["recon"]),
                train_kl=float(train_m["kl"]),
                val_recon=float(val_m["recon"]) if val_order is not None
                else float("nan"),
                val_kl=float(val_m["kl"]) if val_order is not None
                else float("nan"),
                lr=lr,
                step=int(state.step),
                seconds=dt,
                days_per_sec=float(train_m["days"]) / max(dt, 1e-9),
            )
            if "skipped_steps" in train_m:
                # Updates the in-graph finite gate skipped this epoch
                # (train/loop.py) — obs.report renders >0 as a
                # `skip_step` recovery flag.
                rec["skipped_steps"] = float(train_m["skipped_steps"])
            if "loss_scale" in train_m:
                # Mixed-precision telemetry (loop.py/probes.py): the
                # dynamic scale after the epoch's last step and how
                # many steps sat at the floor — obs.report renders a
                # floored scale as `loss_scale_collapse`.
                rec["loss_scale"] = float(train_m["loss_scale"])
                rec["loss_scale_floor_steps"] = float(
                    train_m["loss_scale_floor_steps"])
            if cfg.train.obs_probes:
                # On-device health probes (obs/probes.py), already in
                # the fetched metric dicts — same per-epoch host sync
                # the loss metrics pay, no extra dispatches.
                from factorvae_tpu.obs.probes import (
                    EVAL_PROBE_KEYS,
                    TRAIN_PROBE_KEYS,
                )

                for k in TRAIN_PROBE_KEYS:
                    if k in train_m:
                        rec[k] = float(train_m[k])
                if val_order is not None:
                    for k in EVAL_PROBE_KEYS:
                        if k in val_m:
                            rec["val_" + k] = float(val_m[k])
            history.append(rec)
            self.logger.log("epoch", **rec)
            # Prometheus textfile exporter (obs/metrics.py): one atomic
            # .prom rewrite per epoch when installed; one `is None`
            # check when not (the default).
            from factorvae_tpu.obs.metrics import export_epoch_metrics

            export_epoch_metrics(rec)
            # Live-buffer watermark where the backend exposes allocator
            # stats (TPU/GPU; no-op on host CPU or without a timeline) —
            # the measured complement of the compile records' peak
            # estimate (obs/memory.py).
            from factorvae_tpu.obs.memory import watermark_event

            watermark_event(epoch=epoch)

            # ---- recovery escalation -----------------------------------
            # Mixed builds EXPECT about one overflow-skip per loss-scale
            # growth attempt (the scale probes upward every
            # growth_interval steps and backs off when it overshoots) —
            # that housekeeping must not read as a hazard, or a healthy
            # bf16 run would rollback-loop. Only a skip count past the
            # per-epoch growth budget, or a scale pinned at its floor
            # (bf16 training no longer learning), escalates; float32
            # builds keep the exact pre-mixed signal. The nonfinite-
            # grads probe is folded into the same budget on mixed
            # builds (an overflow step IS a nonfinite-grad step).
            skipped = float(train_m.get("skipped_steps", 0.0) or 0.0)
            if self._mixed:
                skip_budget = self.steps_per_epoch // max(
                    1, self.cfg.train.loss_scale_growth_interval) + 1
                bad = (not np.isfinite(train_loss)
                       or skipped > skip_budget
                       or float(train_m.get("loss_scale", np.inf))
                       <= cfg.train.loss_scale_floor)
            else:
                bad = (not np.isfinite(train_loss)
                       or skipped > 0
                       or float(train_m.get("nonfinite_grads", 0.0)
                                or 0.0) > 0)
            bad_streak = bad_streak + 1 if bad else 0
            escalate = bool(recover_after and bad_streak >= recover_after)
            if (escalate
                    and not (rollbacks < cfg.train.recover_max_rollbacks
                             and ckpt is not None
                             and last_good_step is not None)
                    and bad_streak == recover_after):
                # Escalation point with nowhere to roll back to — run bad
                # from epoch 0 (no good-epoch anchor yet, the k60
                # degenerate-init regime), checkpointing off, or rollback
                # budget spent. The operator asked for action at this
                # streak: degrade to lr backoff alone (unless the budget
                # is the blocker — then the backoffs already happened)
                # and say so, instead of burning the epoch budget in
                # silence. Fires once per streak, at the crossing.
                budget_spent = rollbacks >= cfg.train.recover_max_rollbacks
                reason = ("rollback budget spent "
                          f"({rollbacks}/{cfg.train.recover_max_rollbacks})"
                          if budget_spent
                          else "checkpointing disabled" if ckpt is None
                          else "no good-epoch checkpoint anchor yet")
                if not budget_spent:
                    self._lr_scale *= cfg.train.recover_lr_backoff
                    self._build_step_fns()
                self.logger.log(
                    "recovery", kind="rollback_unavailable", epoch=epoch,
                    lr_scale=self._lr_scale,
                    note=f"{reason}; continuing with lr backoff only")
                timeline_event("recovery_rollback_unavailable",
                               cat="recovery", resource="recovery",
                               epoch=epoch, reason=reason)
            if (escalate
                    and rollbacks < cfg.train.recover_max_rollbacks
                    and ckpt is not None and last_good_step is not None):
                rollbacks += 1
                bad_streak = 0
                self._lr_scale *= cfg.train.recover_lr_backoff
                # Same opt-state tree at a backed-off peak lr: the
                # restored optimizer state drops into the rebuilt tx
                # unchanged (train/state.make_optimizer).
                self._build_step_fns()
                try:
                    state, _ = ckpt.restore(state, step=last_good_step)
                    restored = last_good_step
                except Exception:
                    # The anchor step went corrupt under us: fall back to
                    # the newest VERIFIED step (restore quarantines as it
                    # scans); with none left, continue forward un-rolled
                    # rather than die.
                    try:
                        state, meta = ckpt.restore(state)
                        restored = int(meta.get("epoch", epoch))
                    except FileNotFoundError:
                        self.logger.log(
                            "recovery", kind="rollback_unavailable",
                            epoch=epoch,
                            note="no verifiable checkpoint to roll back "
                                 "to; continuing with lr backoff only")
                        epoch += 1
                        continue
                self.logger.log(
                    "recovery", kind="rollback", epoch=epoch,
                    restored_step=restored, lr_scale=self._lr_scale,
                    rollbacks=rollbacks)
                timeline_event("recovery_rollback", cat="recovery",
                               resource="recovery", epoch=epoch,
                               step=restored, lr_scale=self._lr_scale)
                epoch = restored + 1
                continue

            improved = selection_loss < best_val
            if improved:
                best_val = selection_loss
                save_params(cfg.train.save_dir, cfg.checkpoint_name(), state.params)
            if ckpt is not None and (
                epoch % max(1, cfg.train.checkpoint_every) == 0 or epoch == epochs - 1
            ):
                ckpt.save(
                    epoch,
                    state,
                    {"epoch": epoch, "best_val": best_val,
                     "config": cfg.to_dict(), "clean": not bad},
                )
                if not bad:
                    # Rollback anchor: the newest checkpoint written at
                    # an epoch with NO bad signal (a mid-streak save
                    # would re-enter the hazard on restore).
                    last_good_step = epoch
            epoch += 1
        if ckpt is not None:
            ckpt.close()
        self.logger.log("best", best_val=best_val)
        return state, {"history": history, "best_val": best_val}

    # ------------------------------------------------------------------

    def evaluate(self, params, start=None, end=None, seed: int = 0) -> dict:
        """Validation-style metrics over an arbitrary date range (the
        standalone `validate` the reference exposes, train_model.py:40)."""
        days = self.ds.split_days(
            start if start is not None else self.cfg.data.val_start_time,
            end if end is not None else self.cfg.data.val_end_time,
        )
        if len(days) == 0:
            raise ValueError("no trading days in the requested range")
        order = jnp.asarray(
            self.ds.epoch_order(
                days, shuffle=False, seed=0, epoch=0, pad_to=self.batch_days
            ).reshape(-1, self.batch_days)
        )
        m = self._eval_epoch(params, order, jax.random.PRNGKey(seed))
        return {k: float(v) for k, v in m.items()}

    def score(self, params, start=None, end=None, **kw):
        """Prediction scores DataFrame (see eval.generate_prediction_scores)."""
        from factorvae_tpu.eval.predict import generate_prediction_scores

        return generate_prediction_scores(
            params, self.cfg, self.ds, start=start, end=end, **kw
        )
