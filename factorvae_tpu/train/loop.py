"""Jitted train/eval step and whole-epoch device loops.

Re-design of the reference's hot loop (train_model.py:11-60). The
reference pays a host->device copy and a `loss.item()` device sync every
step (train_model.py:21-28, SURVEY.md §3.5). Here an *entire epoch* is one
`lax.scan` under jit: the day order goes in as an int32 array, every step
gathers its day-batch from the HBM-resident panel, computes grads, applies
the optimizer update, and accumulates metrics on device; the host fetches
one scalar pair per epoch.

Semantics knobs:
- days_per_step=1 reproduces the reference exactly: one trading day = one
  SGD step, scheduler advanced per step (train_model.py:31-32).
- days_per_step=B>1 averages gradients over B days per update — the
  day-level data-parallel mode; with a ('data',) mesh the B axis is
  sharded and XLA all-reduces the gradients over ICI.
- day index -1 marks epoch padding (so the scan length is static and
  divisible); padded days get loss weight 0 and contribute no gradient.

Fleet contract (train/fleet.py): `train_epoch` and `eval_epoch` are
vmappable over a leading seed axis on (state, order) / (params, key)
with the panel held broadcast — nothing in the bodies closes over
per-seed state, and every metric in the returned dicts is a scalar, so
the vmapped entry points return (S,)-shaped metric dicts with the same
keys. Keep new metrics scalar (accumulate inside the scan) so the fleet
path keeps working unchanged.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from factorvae_tpu.data.windows import gather_day
from factorvae_tpu.train.state import TrainState, cast_compute


def concat_auxes(parts, axis: int = 0):
    """Per-chunk (k, ...) aux stacks -> one (steps, ...) epoch stack
    (device concat: no host sync inside the epoch loop). `axis=1` for
    fleet auxes carrying a leading seed axis."""
    if len(parts) == 1:
        return parts[0]
    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=axis), *parts)


class StepFns(NamedTuple):
    train_step: Callable        # (state, days, panel) -> (state, aux)
    train_epoch: Callable       # (state, order (S,B), panel) -> (state, metrics)
    eval_epoch: Callable        # (params, order (S,B), key, panel) -> metrics
    batch_for: Callable         # (days (B,), panel) -> (x, y, mask)
    # Streaming-residency chunk fns (plan.panel_residency="stream"): the
    # SAME scan bodies over a (k, B) slice of the epoch order, fed a
    # per-chunk mini-panel (data/stream.py) instead of the full HBM
    # panel. Per-step aux comes back un-reduced so the epoch metrics can
    # be finalized over the full step axis exactly like the whole-epoch
    # scan does.
    train_chunk: Callable       # (state, order (k,B), panel) -> (state, auxes)
    eval_chunk: Callable        # (params, order (k,B), key, panel) -> (key, auxes)
    finalize_train: Callable    # (auxes (steps,)) -> metrics
    finalize_eval: Callable     # (auxes (steps,)) -> metrics


def _all_finite(tree) -> jnp.ndarray:
    """Scalar bool: every element of every leaf is finite. The reduction
    the in-graph gate keys on — cheap relative to the backward pass that
    produced the tree."""
    flags = [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(tree)]
    return functools.reduce(jnp.logical_and, flags,
                            jnp.asarray(True))


def make_step_fns(
    model_train: Any,
    model_eval: Any,
    tx: optax.GradientTransformation,
    seq_len: int,
    shard_batch: Any = None,
    obs: bool = False,
    guard: bool = False,
    inject_nan: bool = False,
    hyper_step_size: Any = None,
    compute_dtype: str = "float32",
    loss_scale_cfg: Any = None,
    remat: str = "none",
) -> StepFns:
    """`model_train` / `model_eval` are the day-batched forward variants
    (models.day_forward with train=True/False; they share one param tree).

    Every entry point takes `panel = (values, last_valid, next_valid)` as
    an EXPLICIT runtime argument. Closing over the HBM panel instead
    (the round-1 design) made JAX embed it as a compile-time constant —
    at real CSI300 history length (~1,200 days, ~280 MB) that blew the
    axon relay's compile-payload limit (HTTP 413) and would bloat any
    serialized executable; as arguments the arrays stay where they live
    and the compiled program is shape-only.

    `shard_batch`, when given (parallel.make_batch_constraint), pins the
    gathered (B, N, ...) batch to the ('data', 'stock') mesh layout inside
    the jitted step.

    `obs=True` (TrainConfig.obs_probes) compiles the on-device health
    probes (obs/probes.py: grad/update/param global norms, non-finite
    counters, factor-posterior spread) into the step aux and the epoch
    finalizers — scalar additions to the scan carry, zero extra
    dispatches, vmappable over the fleet seed axis like every other
    metric. `obs=False` (the default) is gated at TRACE TIME: the traced
    graph is the pre-observatory one, so the default path stays bitwise
    identical (pinned in tests/test_obs.py, the `panel_residency`
    discipline).

    `hyper_step_size` (the hyper-fleet trace, train/fleet.py +
    state.make_hyper_optimizer) switches the per-LANE hyperparameter
    mode on: every train/eval entry point takes one extra `hp` argument
    — a dict ``{"lr": scalar, "kl_weight": scalar}`` of f32 runtime
    scalars ((S,) vectors once vmapped over the fleet axis) — the
    per-day loss is recomposed as ``recon + hp.kl_weight * kl`` (the
    model's own expression with the trace constant replaced by the
    runtime scalar; the model still computes its baked ``out.loss``,
    which this path simply ignores), and the optimizer's deferred lr
    multiply is applied as ``u * hyper_step_size(step, hp.lr)``. Gated
    at TRACE TIME like `obs`: `hyper_step_size=None` (every pre-hyper
    caller) compiles the exact pre-hyper graph — signatures, arithmetic
    and all.

    `guard=True` (TrainConfig.finite_guard, the self-healing default)
    compiles the in-graph all-finite gate: the optimizer update is
    applied through a `jnp.where` select on "all gradient elements
    finite", so a poisoned step keeps the previous params/opt_state
    (step and RNG still advance — the scan length and key stream stay
    static) and the per-step `skipped` aux counts it. With no fault the
    select always takes the updated branch and the params are BITWISE
    the unguarded path's (tests/test_chaos.py); vmapped over a fleet,
    each seed lane carries its own gate. `inject_nan=True` (trace-gated
    on an installed chaos plan, factorvae_tpu/chaos) appends a `poison`
    gradient multiplier argument to the train entry points — NaN on the
    epochs/lanes a fault targets, 1.0 elsewhere — applied between the
    backward pass and the gate.

    `compute_dtype` != "float32" (the RESOLVED training dtype,
    state.resolve_train_dtype) compiles the mixed master-weight trace:
    the f32 master params get ONE `cast_compute` inside the
    differentiated day loss (so the astype transpose returns f32 master
    grads), the loss is multiplied by the state's dynamic `loss_scale`
    before the backward and the grads divided by it after, and a
    non-finite grad tree skips the update through the SAME `jnp.where`
    select as `guard` (compiled in whenever guard OR mixed) while
    backing the scale off; `loss_scale_cfg` is the knob tuple
    ``(growth, backoff, growth_interval, floor)`` (TrainConfig
    loss_scale_*). Trace-gated like everything else: the default
    float32 build never references the scale leaves and is bitwise the
    pre-mixed graph.

    `remat` ("none" | "dots" | "full", TrainConfig.remat) wraps the
    TRAIN day loss in `jax.checkpoint` — "dots" keeps matmul results
    and recomputes the elementwise chain, "full" recomputes everything
    — shrinking the epoch scan's saved-residual footprint (the win is
    measured per jit by bench.py --mixed/--kernels via obs.compile).
    Since PR 19 the knob is plan-raced: `autotune_plan.py --remat`
    times the rungs at the row's days_per_step AND, where a rung
    measurably frees peak_bytes, at doubled days_per_step — so a rung
    can win by admitting a larger step — and persists a `train_remat`
    block only past a wall-clock win (apply_plan then sets
    TrainConfig.remat; docs/kernels.md). "none" is the exact pre-remat
    graph and what every verdict-free row resolves to; eval never
    backprops and stays unwrapped."""

    hyper = hyper_step_size is not None
    mixed = compute_dtype != "float32"
    gate = guard or mixed
    if mixed:
        if loss_scale_cfg is None:
            raise ValueError(
                "mixed build (compute_dtype != float32) needs "
                "loss_scale_cfg=(growth, backoff, growth_interval, "
                "floor) — TrainConfig's loss_scale_* knobs")
        ls_growth, ls_backoff, ls_interval, ls_floor = (
            jnp.float32(loss_scale_cfg[0]), jnp.float32(loss_scale_cfg[1]),
            jnp.int32(loss_scale_cfg[2]), jnp.float32(loss_scale_cfg[3]))
        _cdtype = jnp.dtype(compute_dtype)

    def _split_extras(extras: tuple) -> tuple:
        """(hp, poison) from a train entry point's trailing positional
        args. Both exist only on the traces that compiled them in (hp on
        hyper builds — FIRST, so mesh in_shardings stay positional;
        poison on chaos builds), so every pre-hyper caller's positional
        `*poison` keeps binding exactly where it always did."""
        if hyper and inject_nan:
            return extras[0], extras[1]
        if hyper:
            return extras[0], None
        if inject_nan:
            return None, extras[0]
        return None, None

    def batch_for(days: jnp.ndarray, panel):
        values, last_valid, next_valid = panel
        safe = jnp.maximum(days, 0)
        x, y, mask = jax.vmap(
            lambda d: gather_day(values, last_valid, next_valid, d, seq_len)
        )(safe)
        mask = mask & (days >= 0)[:, None]
        if shard_batch is not None:
            x, y, mask = shard_batch(x, y, mask)
        return x, y, mask

    def weighted_day_loss(params, days, key, panel, train: bool, hp=None):
        if mixed:
            # THE master->compute cast (state.cast_compute): inside the
            # differentiated function, so grads flow back through the
            # astype transpose as f32 cotangents onto the f32 masters.
            params = cast_compute(params, _cdtype)
        x, y, mask = batch_for(days, panel)
        day_w = (days >= 0).astype(jnp.float32)
        k_sample, k_drop = jax.random.split(key)
        model = model_train if train else model_eval
        out = model.apply(
            params, x, y, mask, rngs={"sample": k_sample, "dropout": k_drop}
        )
        if hyper:
            # Per-lane loss recomposition: the model's own expression
            # (models/factorvae.py `recon + cfg.kl_weight * kl`) with
            # the trace constant replaced by the runtime lane scalar —
            # the same single multiply+add on the same operands, so a
            # lane whose kl_weight bit-equals the baked constant takes
            # the same loss (and loss gradient) value-for-value.
            day_loss = out.recon_loss + hp["kl_weight"] * out.kl
        else:
            day_loss = out.loss
        loss_sum = jnp.sum(day_loss * day_w)
        count = jnp.sum(day_w)
        # mean over real days this step; padded days carry zero weight
        loss = loss_sum / jnp.maximum(count, 1.0)
        n_valid = jnp.sum(mask, axis=-1).astype(jnp.float32) * day_w
        aux = {
            "loss_sum": loss_sum,
            "recon_sum": jnp.sum(out.recon_loss * day_w),
            "kl_sum": jnp.sum(out.kl * day_w),
            "days": count,
            # sample-weighted numerator/denominator: the (fixed) intent of
            # the reference's dead `test` loop (train_model.py:62-82 weights
            # by batch size but divides by batch count — we divide by the
            # sample count)
            "wloss_sum": jnp.sum(day_loss * n_valid),
            "samples": jnp.sum(n_valid),
        }
        if obs:
            from factorvae_tpu.obs.probes import loss_probes

            aux.update(loss_probes(out, day_w))
        return loss, aux

    # Remat policy for the backward pass: wrap the TRAIN loss only
    # (eval never differentiates). `train` (arg 4) is trace-static.
    if remat == "dots":
        _train_loss = jax.checkpoint(
            weighted_day_loss, static_argnums=(4,),
            policy=jax.checkpoint_policies.checkpoint_dots)
    elif remat == "full":
        _train_loss = jax.checkpoint(weighted_day_loss,
                                     static_argnums=(4,))
    elif remat == "none":
        _train_loss = weighted_day_loss
    else:
        raise ValueError(
            f"remat={remat!r}: expected 'none', 'dots' or 'full' "
            "(TrainConfig.remat)")

    def _scaled_loss(params, days, key, panel, train, hp, scale):
        # Dynamic loss scaling (mixed builds): ONE f32 multiply on the
        # scalar loss so the bf16 backward's small cotangents sit in
        # representable range; grads are divided back down outside.
        loss, aux = _train_loss(params, days, key, panel, train, hp)
        return loss * scale, aux

    def train_step(state: TrainState, days: jnp.ndarray, panel,
                   *extras):
        hp, poison = _split_extras(extras)
        state, key = state.advance_rng()
        if mixed:
            (_, aux), grads = jax.value_and_grad(
                _scaled_loss, has_aux=True)(
                state.params, days, key, panel, True, hp,
                state.loss_scale)
            inv = jnp.float32(1.0) / state.loss_scale
            grads = jax.tree.map(lambda g: g * inv, grads)
        else:
            (_, aux), grads = jax.value_and_grad(
                _train_loss, has_aux=True)(
                state.params, days, key, panel, True, hp
            )
        if inject_nan:
            # Chaos-only trace (factorvae_tpu/chaos): poison is 1.0 on
            # clean epochs/lanes (an exact float multiply — identity),
            # NaN where a nan_grads fault targets.
            grads = jax.tree.map(lambda g: g * poison, grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if hyper:
            # The deferred lr multiply (state.make_hyper_optimizer):
            # optax's scale_by_schedule arithmetic with the Python-float
            # init replaced by the runtime lane lr. `state.step` equals
            # the schedule count at update time (both advance once per
            # update; the identity transform in the chain carries the
            # count the serial opt-state tree has).
            s = hyper_step_size(state.step, hp["lr"])
            updates = jax.tree.map(
                lambda u: jnp.asarray(s, dtype=u.dtype) * u, updates)
        new_params = optax.apply_updates(state.params, updates)
        if gate:
            # The all-finite gate: a poisoned step KEEPS the previous
            # params/opt_state (a pure elementwise select — bitwise the
            # ungated path when ok is always True); step and RNG still
            # advance so the scan stays static-length and the key
            # stream is unchanged. Mixed builds compile the SAME select
            # even with finite_guard off: a loss-scale overflow IS a
            # skipped step (ISSUE 16 — one gate, one `skipped` metric).
            ok = _all_finite(grads)
            new_params = jax.tree.map(
                lambda a, b: jnp.where(ok, a, b), new_params, state.params)
            new_opt = jax.tree.map(
                lambda a, b: jnp.where(ok, a, b), new_opt, state.opt_state)
            aux["skipped"] = (~ok).astype(jnp.float32)
        state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt
        )
        if mixed:
            # In-graph scale walk: overflow -> backoff (clamped at the
            # floor) + counter reset; `ls_interval` consecutive finite
            # steps -> growth + counter reset. Rides the state, so fleet
            # vmap gives every lane its own (S,) scale for free.
            good = jnp.where(ok, state.good_steps + 1,
                             jnp.zeros((), jnp.int32))
            grow = good >= ls_interval
            new_scale = jnp.where(
                ok,
                jnp.where(grow, state.loss_scale * ls_growth,
                          state.loss_scale),
                jnp.maximum(state.loss_scale * ls_backoff, ls_floor))
            state = state.replace(
                loss_scale=new_scale,
                good_steps=jnp.where(grow, jnp.zeros((), jnp.int32),
                                     good))
            aux["loss_scale"] = new_scale
        if obs:
            from factorvae_tpu.obs.probes import grad_probes

            aux.update(grad_probes(grads, updates, new_params))
        return state, aux

    def finalize_train(auxes):
        """Per-step aux (steps,) -> epoch metrics. ONE definition shared
        by the whole-epoch scan (inside its jit) and the stream path
        (jitted over the chunk-concatenated aux): the metric reduction
        over the full step axis is identical either way."""
        days = jnp.maximum(jnp.sum(auxes["days"]), 1.0)
        m = {
            "loss": jnp.sum(auxes["loss_sum"]) / days,
            "recon": jnp.sum(auxes["recon_sum"]) / days,
            "kl": jnp.sum(auxes["kl_sum"]) / days,
            "days": jnp.sum(auxes["days"]),
        }
        if gate:
            # Steps whose update the gate skipped this epoch — the
            # host-side escalation signal (trainer.py recovery). On
            # mixed builds this includes loss-scale overflow skips.
            m["skipped_steps"] = jnp.sum(auxes["skipped"])
        if mixed:
            from factorvae_tpu.obs.probes import loss_scale_probes

            m.update(loss_scale_probes(auxes, ls_floor))
        if obs:
            from factorvae_tpu.obs.probes import finalize_train_probes

            m.update(finalize_train_probes(auxes, days))
        return m

    def finalize_eval(auxes):
        days = jnp.maximum(jnp.sum(auxes["days"]), 1.0)
        m = {
            "loss": jnp.sum(auxes["loss_sum"]) / days,
            "recon": jnp.sum(auxes["recon_sum"]) / days,
            "kl": jnp.sum(auxes["kl_sum"]) / days,
            "days": jnp.sum(auxes["days"]),
            # per-sample weighted mean (row 19 of SURVEY §2; see
            # weighted_day_loss)
            "loss_sample_weighted": jnp.sum(auxes["wloss_sum"])
            / jnp.maximum(jnp.sum(auxes["samples"]), 1.0),
        }
        if obs:
            from factorvae_tpu.obs.probes import finalize_eval_probes

            m.update(finalize_eval_probes(auxes, days))
        return m

    def train_chunk(state: TrainState, order: jnp.ndarray, panel,
                    *extras):
        """One epoch SEGMENT: the epoch scan body over a (k, B) slice of
        the step order, returning the UN-reduced per-step aux so the
        caller can finalize over the whole epoch. The stream path runs
        this over per-chunk mini-panels (data/windows.chunk_mini_panel)
        whose gather resolves to the same values as the full panel's —
        the traced graph is IDENTICAL to the whole-epoch scan's body, so
        per-step updates stay bitwise (pre-gathered batches as jit
        inputs were measured to perturb XLA's backward fusion by ~1 ulp;
        keeping the gather in-graph is what makes stream == hbm exact).
        `extras` carries the trace-gated trailing args — `hp` on hyper
        builds, `poison` on chaos builds (see make_step_fns) — threaded
        to every step of the segment.
        """
        def body(st, days):
            st, aux = train_step(st, days, panel, *extras)
            return st, aux

        return jax.lax.scan(body, state, order)

    def train_epoch(state: TrainState, order: jnp.ndarray, panel,
                    *extras):
        """order: (S, B) int32 day indices (-1 = pad)."""
        state, auxes = train_chunk(state, order, panel, *extras)
        return state, finalize_train(auxes)

    def eval_chunk(params, order: jnp.ndarray, key: jax.Array, panel,
                   *extras):
        """Eval epoch segment. The key threads ACROSS chunks (returned
        with the aux), so the concatenated per-step key stream is
        exactly the whole-epoch scan's. On hyper builds `extras` is
        `(hp,)` — the per-lane kl_weight recomposes the selection
        loss."""
        hp = extras[0] if hyper else None

        def body(k, days):
            k, sub = jax.random.split(k)
            _, aux = weighted_day_loss(params, days, sub, panel, False, hp)
            return k, aux

        return jax.lax.scan(body, key, order)

    def eval_epoch(params, order: jnp.ndarray, key: jax.Array, panel,
                   *extras):
        """Validation mean loss (reference validate(), train_model.py:40-60:
        dropout off, reconstruction still sampled)."""
        _, auxes = eval_chunk(params, order, key, panel, *extras)
        return finalize_eval(auxes)

    return StepFns(
        train_step=train_step,
        train_epoch=train_epoch,
        eval_epoch=eval_epoch,
        batch_for=batch_for,
        train_chunk=train_chunk,
        eval_chunk=eval_chunk,
        finalize_train=finalize_train,
        finalize_eval=finalize_eval,
    )
