"""Tracing / profiling utilities.

The reference has no tracing at all — only tqdm progress bars
(SURVEY.md §5 "Tracing/profiling"). Here: `jax.profiler` trace capture
around training epochs (viewable in TensorBoard / Perfetto), named step
annotations, and a NaN-debug mode replacing the reference's scattered
runtime NaN guards (module.py:149-150) with a framework-level switch.

ISSUE 10 adds ON-DEMAND capture to the long-lived processes:

- `start_profile` / `stop_profile` — explicit start/stop pair behind
  the scoring daemon's `POST /profile`; `stop_profile` summarizes the
  captured trace through `utils/trace_summary.py` and returns the
  device-time breakdown.
- `maybe_profile_epoch` — the trainer's epoch-boundary hook: dropping a
  `PROFILE_REQUEST` file (empty, or JSON `{"log_dir": ...}`) into the
  run directory makes the NEXT epoch run under `jax.profiler`, after
  which the capture is summarized and logged. The poll is one
  `os.path.exists` per epoch and only when the run has a metrics
  stream; without the request file the epoch path is untouched.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
from typing import Iterator, Optional, Tuple

import jax

#: drop this file into a run directory to request an epoch capture
PROFILE_REQUEST_BASENAME = "PROFILE_REQUEST"


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a device trace into `log_dir` (no-op when None)."""
    if not log_dir:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# on-demand capture (ISSUE 10)
# ---------------------------------------------------------------------------


class ProfilerError(RuntimeError):
    """Capture state/backend failure with a one-line actionable
    message (the daemon's /profile answers it as {"ok": false})."""


# Active on-demand capture dir (one at a time per process — the jax
# profiler itself is a singleton).
_ACTIVE: dict = {"dir": None}


def start_profile(log_dir: Optional[str] = None) -> str:
    """Begin an on-demand `jax.profiler` capture; returns the log dir
    (a fresh temp dir when none given). One capture at a time."""
    if _ACTIVE["dir"] is not None:
        raise ProfilerError(
            f"a profile capture is already running into "
            f"{_ACTIVE['dir']}; POST {{\"action\": \"stop\"}} first")
    log_dir = log_dir or tempfile.mkdtemp(prefix="factorvae_profile_")
    os.makedirs(log_dir, exist_ok=True)
    try:
        jax.profiler.start_trace(log_dir)
    except Exception as e:
        raise ProfilerError(f"jax.profiler failed to start: {e}") from e
    _ACTIVE["dir"] = log_dir
    return log_dir


def stop_profile(top: int = 10) -> dict:
    """End the active capture and summarize it: {"log_dir", "files",
    "total_us", "host_us", "top": [[name, us, count], ...]} via the
    existing trace_summary machinery."""
    log_dir = _ACTIVE["dir"]
    if log_dir is None:
        raise ProfilerError(
            "no profile capture is running; POST "
            "{\"action\": \"start\"} first")
    _ACTIVE["dir"] = None
    try:
        jax.profiler.stop_trace()
    except Exception as e:
        raise ProfilerError(f"jax.profiler failed to stop: {e}") from e
    return {"log_dir": log_dir, **summarize_capture(log_dir, top=top)}


def summarize_capture(log_dir: str, top: int = 10) -> dict:
    """Guarded trace_summary digest of a capture dir — profiling is
    telemetry, so an unreadable trace degrades to an `error` field,
    never an exception on the serving/training path."""
    from factorvae_tpu.utils.trace_summary import summarize_trace

    try:
        s = summarize_trace(log_dir, top=top)
    except Exception as e:
        return {"files": 0, "error": str(e)}
    return {
        "files": len(s["files"]),
        "total_us": round(s["total_us"], 3),
        "host_us": round(s.get("host_us", 0.0), 3),
        "top": [[name, round(us, 3), count]
                for name, us, count in s["by_name"]],
    }


def poll_profile_request(run_dir: Optional[str]) -> Optional[dict]:
    """Consume a PROFILE_REQUEST drop-in from `run_dir`: returns its
    JSON body ({} for an empty/garbled file — the request still
    counts) and removes the file, or None when absent."""
    if not run_dir:
        return None
    path = os.path.join(run_dir, PROFILE_REQUEST_BASENAME)
    if not os.path.exists(path):
        return None
    req: dict = {}
    try:
        with open(path) as fh:
            body = fh.read().strip()
        if body:
            parsed = json.loads(body)
            if isinstance(parsed, dict):
                req = parsed
    except (OSError, ValueError):
        req = {}  # an unreadable request is still a request
    try:
        os.remove(path)
    except OSError:
        pass  # already consumed by a sibling process — capture anyway
    return req


@contextlib.contextmanager
def maybe_profile_epoch(run_dir: Optional[str],
                        epoch: int) -> Iterator[Tuple[bool, Optional[str]]]:
    """The trainer's epoch-boundary hook: when `run_dir` carries a
    PROFILE_REQUEST file, run the epoch body under a `jax.profiler`
    capture into the request's `log_dir` (default:
    `<run_dir>/profile_epoch<e>`) and yield (True, log_dir); otherwise
    (False, None) with zero added work beyond the existence poll.

    The capture start is GUARDED — telemetry never aborts the epoch
    loop: a profiler that refuses to start (a `--profile` whole-run
    trace already active, an unwritable log_dir) yields
    (False, "<error message>") and the epoch runs unprofiled (the
    request file is consumed either way; the caller logs the error)."""
    req = poll_profile_request(run_dir)
    if req is None:
        yield False, None
        return
    log_dir = str(req.get("log_dir") or os.path.join(
        run_dir, f"profile_epoch{int(epoch)}"))
    try:
        os.makedirs(log_dir, exist_ok=True)
        jax.profiler.start_trace(log_dir)
    except Exception as e:
        yield False, f"profile capture failed to start: {e}"
        return
    try:
        yield True, log_dir
    finally:
        # a failed stop leaves no trace files — summarize_capture then
        # reports files=0, which is how the failure surfaces
        with contextlib.suppress(Exception):
            jax.profiler.stop_trace()


def step_annotation(name: str):
    """Named region that shows up on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def debug_nans(enable: bool = True) -> Iterator[None]:
    """Raise on any NaN produced inside jitted code while active — the
    debugging replacement for the reference's silent NaN guards."""
    old = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", old)
