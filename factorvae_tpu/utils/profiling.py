"""Tracing / profiling utilities.

The reference has no tracing at all — only tqdm progress bars
(SURVEY.md §5 "Tracing/profiling"). Here: `jax.profiler` trace capture
around training epochs (viewable in TensorBoard / Perfetto), named step
annotations, and a NaN-debug mode replacing the reference's scattered
runtime NaN guards (module.py:149-150) with a framework-level switch.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a device trace into `log_dir` (no-op when None)."""
    if not log_dir:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step_annotation(name: str):
    """Named region that shows up on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def debug_nans(enable: bool = True) -> Iterator[None]:
    """Raise on any NaN produced inside jitted code while active — the
    debugging replacement for the reference's silent NaN guards."""
    old = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", old)
