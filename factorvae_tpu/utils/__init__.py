from factorvae_tpu.utils.testing import force_host_devices, host_device_count

__all__ = ["force_host_devices", "host_device_count"]
