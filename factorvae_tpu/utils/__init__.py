from factorvae_tpu.utils.logging import (
    MetricsLogger,
    Timeline,
    current_timeline,
    install_timeline,
)
from factorvae_tpu.utils.profiling import debug_nans, step_annotation, trace
from factorvae_tpu.utils.rng import set_seed
from factorvae_tpu.utils.testing import (
    enable_persistent_compile_cache,
    force_host_devices,
    host_device_count,
)

__all__ = [
    "MetricsLogger",
    "Timeline",
    "current_timeline",
    "debug_nans",
    "install_timeline",
    "enable_persistent_compile_cache",
    "force_host_devices",
    "host_device_count",
    "set_seed",
    "step_annotation",
    "trace",
]
