"""Structured metrics stream + the unified host timeline.

`MetricsLogger` replaces the reference's print + tqdm + optional wandb
combo (main.py:63-87) with a JSONL metric stream (one line per
epoch/event) plus the same optional wandb hookup, gated so the framework
runs without wandb installed or configured. Every file-backed stream
opens with a `run_meta` header record (jax/platform/device_count, git
sha, config hash) so a RUN.jsonl is self-describing, and the logger is a
context manager that closes its file handle on error paths.

`Timeline` is the span/event half of the run observatory
(factorvae_tpu/obs): monotonic-clock spans (`time.perf_counter`, immune
to wall-clock jumps), thread-safe by construction (the underlying
logger serializes writes), emitted as `span` / `mark` records into the
SAME JSONL stream as the metrics. The logger's write lock is a LEAF in
the project's lock order (the lock-order sanitizer's graph,
analysis/sanitize.py): every subsystem may log while holding its own
lock (daemon tick lock, registry lock, drift lock, ...), so `log()`
itself must never acquire another subsystem's lock — and signal
handlers must never log at all (graftlint JGL010) — one RUN.jsonl carries epochs, health
probes, stream-prefetch spans, checkpoint spans and compile-watchdog
events, which `python -m factorvae_tpu.obs.timeline` renders as a text
Gantt with per-resource overlap fractions. Span names are chosen to
match `utils.profiling.step_annotation` names so a host span can be
cross-linked with the device lanes of a `--profile` trace.

Producers deep in the stack (data/stream.py's prefetch worker, the
async Checkpointer, the jit watchdog) reach the run's timeline through
the module-level `install_timeline` / `current_timeline` registry and
the no-op-when-absent `timeline_span` / `timeline_event` /
`timeline_span_at` helpers — zero overhead and zero behavior change
when no timeline is installed (the default). Cross-thread spans (a
queue wait that one thread opens and another closes) use the
`timeline_span_begin`/`timeline_span_end` token pair; pairing them
inside one function is a graftlint JGL013 finding — the context
manager is the only form that cannot leak a span.

Spans may additionally carry distributed-trace identity (`trace`,
`span`, `parent` fields — obs/trace.py) passed through `**fields`;
the record schema is additive and every pre-trace consumer
(obs.report, obs.live, obs.timeline) ignores the extra keys.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Iterator, Optional


def _git_sha() -> Optional[str]:
    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
        )
        return r.stdout.strip() or None if r.returncode == 0 else None
    except Exception:
        return None


def backend_env() -> dict:
    """The XLA/backend environment bench comparability depends on
    (ISSUE 7): numbers measured under different platform pins, virtual
    device counts or XLA flags are different rigs, and the perf ledger
    (obs/ledger.py) must refuse to compare them rather than flag false
    regressions. `xla_flags` drops the virtual-device flag (it gets its
    own field) and is sorted, so equal rigs hash equal regardless of
    flag order."""
    flags = os.environ.get("XLA_FLAGS", "").split()
    host_devices = None
    rest = []
    for f in flags:
        if "xla_force_host_platform_device_count" in f:
            try:
                host_devices = int(f.split("=", 1)[1])
            except (IndexError, ValueError):
                host_devices = None
        else:
            rest.append(f)
    return {
        "jax_platforms": os.environ.get("JAX_PLATFORMS"),
        "xla_force_host_platform_device_count": host_devices,
        "xla_flags": sorted(rest),
    }


def config_hash(config: dict) -> str:
    """Canonical 12-hex digest of a config dict — THE identity every
    subsystem keys on: the `run_meta` header of a metrics stream, the
    AOT artifact header (eval/export_aot.py) and the serving model
    registry (serve/registry.py) must all agree on what "same config"
    means, so the hash function lives in exactly one place."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def run_meta(config: Optional[dict] = None,
             run_name: Optional[str] = None) -> dict:
    """Header fields for the first record of a metrics stream. jax is
    queried only if already imported (probing it here must not
    initialize a backend behind the caller's platform setup)."""
    meta: dict = {"run_name": run_name, "git_sha": _git_sha(),
                  "env": backend_env()}
    jax = sys.modules.get("jax")
    if jax is not None:
        meta["jax"] = getattr(jax, "__version__", None)
        try:
            meta["platform"] = jax.default_backend()
            meta["device_count"] = jax.device_count()
        except Exception:  # graftlint: disable=JGL007 header degrades to null platform fields by design — run_meta is called while building the log file, so there is no sink to log to yet
            meta["platform"] = None
            meta["device_count"] = None
    if config is not None:
        meta["config_hash"] = config_hash(config)
    return meta


class MetricsLogger:
    """JSONL metric stream; context manager; thread-safe writes."""

    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        use_wandb: bool = False,
        wandb_project: str = "factorvae-tpu",
        run_name: Optional[str] = None,
        config: Optional[dict] = None,
        echo: bool = True,
        echo_to: Any = None,
    ):
        self.jsonl_path = jsonl_path
        self.echo = echo
        # Scripts whose stdout IS the artifact (autotune's table JSON)
        # route the echo to stderr instead.
        self._echo_to = echo_to
        self._lock = threading.Lock()
        self._fh = None
        self._wandb = None
        if jsonl_path:
            os.makedirs(os.path.dirname(os.path.abspath(jsonl_path)), exist_ok=True)
            self._fh = open(jsonl_path, "a")
            # Every file-backed stream opens with a run_meta header: a
            # RUN.jsonl must identify the software/hardware/config that
            # produced it (obs.report reads this back).
            self.log("run_meta", _echo=False,
                     **run_meta(config, run_name=run_name))
        if use_wandb:
            try:
                import wandb  # type: ignore

                self._wandb = wandb
                wandb.init(project=wandb_project, name=run_name, config=config or {})
            except Exception as e:  # wandb absent or offline — degrade to JSONL
                print(f"[metrics] wandb unavailable ({e}); JSONL only", file=sys.stderr)
                self._wandb = None

    def log(self, event: str, _echo: Optional[bool] = None, **fields: Any) -> None:
        rec = {"ts": time.time(), "event": event, **fields}
        with self._lock:
            if self._fh:
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()
        # One read of the handle: finish() (main thread) may null it
        # between a check and a call from a worker-thread log.
        wandb = self._wandb
        if wandb is not None and event == "epoch":
            wandb.log({k: v for k, v in fields.items() if isinstance(v, (int, float))})
        if self.echo if _echo is None else _echo:
            shown = ", ".join(
                f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items()
            )
            print(f"[{event}] {shown}", file=self._echo_to)

    def finish(self, **fields: Any) -> None:
        if fields:
            self.log("final", **fields)
        if self._wandb is not None:
            self._wandb.finish()
            self._wandb = None
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None

    # Context-manager form: the file handle must not leak on error paths
    # (pre-observatory, only wandb ever got a finish()).
    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.finish()


# ---------------------------------------------------------------------------
# Unified host timeline
# ---------------------------------------------------------------------------


class Timeline:
    """Span/event emitter over a MetricsLogger stream.

    Spans are measured on `time.perf_counter` (monotonic, high
    resolution) relative to this timeline's origin, so records from
    every thread of one run share one time base. Emission is
    thread-safe: the logger serializes writes, and span bookkeeping is
    local to each call. Record shapes:

        {"event": "span", "name", "cat", "resource", "t0", "t1",
         "dur", "thread", ...}
        {"event": "mark", "name", "cat", "resource", "t", ...}

    `resource` is the lane the Gantt renderer groups by ("device",
    "stream", "checkpoint", "compile", ...); `cat` is the subsystem.
    """

    _clock = staticmethod(time.perf_counter)

    def __init__(self, logger: MetricsLogger, origin: Optional[float] = None):
        self.logger = logger
        self.origin = self._clock() if origin is None else origin

    def rel(self, mono: float) -> float:
        return mono - self.origin

    def event(self, name: str, cat: str = "host", resource: str = "host",
              **fields: Any) -> None:
        self.logger.log(
            "mark", _echo=False, name=name, cat=cat, resource=resource,
            t=round(self.rel(self._clock()), 6), **fields)

    def span_at(self, name: str, t0: float, t1: float, cat: str = "host",
                resource: str = "host", **fields: Any) -> None:
        """Emit a span from already-measured perf_counter endpoints (the
        ChunkStream ledger path: the worker measured its own window)."""
        self.logger.log(
            "span", _echo=False, name=name, cat=cat, resource=resource,
            t0=round(self.rel(t0), 6), t1=round(self.rel(t1), 6),
            dur=round(t1 - t0, 6),
            thread=threading.current_thread().name, **fields)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", resource: str = "host",
             **fields: Any) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self.span_at(name, t0, self._clock(), cat=cat,
                         resource=resource, **fields)


# Module-level registry: producers deep in the stack (stream prefetch
# worker, async checkpoint commit watcher, jit watchdog) emit into the
# run's timeline without threading it through every constructor. A plain
# module global (not a contextvar): worker THREADS must see it too.
_TIMELINE: Optional[Timeline] = None


def install_timeline(tl: Optional[Timeline]) -> Optional[Timeline]:
    """Install the process-wide timeline; returns the previous one so
    callers (tests) can restore it."""
    global _TIMELINE
    prev = _TIMELINE
    _TIMELINE = tl
    return prev


def current_timeline() -> Optional[Timeline]:
    return _TIMELINE


@contextlib.contextmanager
def timeline_span(name: str, cat: str = "host", resource: str = "host",
                  **fields: Any) -> Iterator[None]:
    """`Timeline.span` against the installed timeline; no-op without one."""
    tl = _TIMELINE
    if tl is None:
        yield
        return
    with tl.span(name, cat=cat, resource=resource, **fields):
        yield


def timeline_event(name: str, cat: str = "host", resource: str = "host",
                   **fields: Any) -> None:
    tl = _TIMELINE
    if tl is not None:
        tl.event(name, cat=cat, resource=resource, **fields)


def timeline_span_at(name: str, t0: float, t1: float, cat: str = "host",
                     resource: str = "host", **fields: Any) -> None:
    tl = _TIMELINE
    if tl is not None:
        tl.span_at(name, t0, t1, cat=cat, resource=resource, **fields)


def timeline_now() -> Optional[float]:
    """Current time on the installed timeline's base (seconds since its
    origin), or None without one. This is the value /healthz echoes as
    `mono` so the fleet collector (obs/collect.py) can estimate each
    process's clock offset from handshake round trips."""
    tl = _TIMELINE
    if tl is None:
        return None
    return round(tl.rel(tl._clock()), 6)


def timeline_span_begin(name: str, cat: str = "host", resource: str = "host",
                        **fields: Any) -> Optional[dict]:
    """Open a span that a DIFFERENT function (usually a different
    thread) will close: returns an opaque token carrying the raw clock
    start, or None when no timeline is installed. The only sanctioned
    use is the cross-thread handoff — e.g. `TickScheduler.submit`
    starts a queue-wait span that the scheduler loop closes once the
    request is pulled into a tick. Pairing begin/end inside ONE
    function is a graftlint JGL013 finding: use `timeline_span`
    instead, which cannot leak the span on an exception path."""
    tl = _TIMELINE
    if tl is None:
        return None
    return {"name": name, "cat": cat, "resource": resource,
            "t0": tl._clock(), "fields": dict(fields)}


def timeline_span_end(token: Optional[dict], **extra: Any) -> None:
    """Close a span opened by `timeline_span_begin`; no-op on a None
    token. Extra fields (e.g. outcome annotations) merge over the
    begin-time fields. Emits on the CURRENTLY installed timeline so the
    token stays valid across an install/restore in tests."""
    if token is None:
        return
    tl = _TIMELINE
    if tl is None:
        return
    fields = {**token["fields"], **extra}
    tl.span_at(token["name"], token["t0"], tl._clock(), cat=token["cat"],
               resource=token["resource"], **fields)
