"""Structured metrics stream.

Replaces the reference's print + tqdm + optional wandb combo
(main.py:63-87) with a JSONL metric stream (one line per epoch/event)
plus the same optional wandb hookup, gated so the framework runs without
wandb installed or configured.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Optional


class MetricsLogger:
    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        use_wandb: bool = False,
        wandb_project: str = "factorvae-tpu",
        run_name: Optional[str] = None,
        config: Optional[dict] = None,
        echo: bool = True,
    ):
        self.jsonl_path = jsonl_path
        self.echo = echo
        self._fh = None
        if jsonl_path:
            os.makedirs(os.path.dirname(os.path.abspath(jsonl_path)), exist_ok=True)
            self._fh = open(jsonl_path, "a")
        self._wandb = None
        if use_wandb:
            try:
                import wandb  # type: ignore

                self._wandb = wandb
                wandb.init(project=wandb_project, name=run_name, config=config or {})
            except Exception as e:  # wandb absent or offline — degrade to JSONL
                print(f"[metrics] wandb unavailable ({e}); JSONL only", file=sys.stderr)
                self._wandb = None

    def log(self, event: str, **fields: Any) -> None:
        rec = {"ts": time.time(), "event": event, **fields}
        if self._fh:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        if self._wandb is not None and event == "epoch":
            self._wandb.log({k: v for k, v in fields.items() if isinstance(v, (int, float))})
        if self.echo:
            shown = ", ".join(
                f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items()
            )
            print(f"[{event}] {shown}")

    def finish(self, **fields: Any) -> None:
        if fields:
            self.log("final", **fields)
        if self._wandb is not None:
            self._wandb.finish()
        if self._fh:
            self._fh.close()
            self._fh = None
