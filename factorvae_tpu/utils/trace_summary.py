"""Summarize a captured `jax.profiler` trace from the command line.

The reference has no profiling story at all (SURVEY.md §5); this closes
the loop on ours: `--profile DIR` captures a trace
(utils/profiling.trace), and

    python -m factorvae_tpu.utils.trace_summary DIR [--top 15]

prints the device-time breakdown — total on-device time and the top
kernels/fusions by accumulated duration — without needing TensorBoard
(the round-2 PERF.md trace analysis was done by hand; this is that
analysis as a tool).

Format notes: jax.profiler writes TensorBoard plugin layout
`DIR/plugins/profile/<run>/<host>.trace.json.gz` in Chrome trace-event
format. Device lanes are identified by their process_name metadata
events (e.g. "/device:TPU:0 ..."); complete events ("ph" == "X") carry
microsecond durations.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from collections import defaultdict
from typing import Optional


def find_trace_files(log_dir: str) -> list:
    """All .trace.json(.gz) files under a profiler log dir."""
    pats = [
        os.path.join(log_dir, "**", "*.trace.json.gz"),
        os.path.join(log_dir, "**", "*.trace.json"),
    ]
    out: list = []
    for p in pats:
        out.extend(glob.glob(p, recursive=True))
    return sorted(out)


def _load_events(path: str) -> list:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as fh:
        data = json.load(fh)
    if isinstance(data, list):      # bare-array chrome trace format
        return data
    return data.get("traceEvents", [])


# Transfer events in jax/XLA chrome traces: memcpy kernels, infeed/
# outfeed, and async copy ops. Substring-matched case-insensitively on
# the event name; direction classified when the name says so.
_TRANSFER_MARKERS = ("memcpy", "infeed", "outfeed", "copy-start",
                     "copy-done", "transferto", "transferfrom")
_H2D_MARKERS = ("h2d", "htod", "infeed", "transferto")
_D2H_MARKERS = ("d2h", "dtoh", "outfeed", "transferfrom")


def _classify_transfer(name: str) -> Optional[str]:
    low = name.lower()
    if not any(m in low for m in _TRANSFER_MARKERS):
        return None
    if any(m in low for m in _H2D_MARKERS):
        return "h2d_us"
    if any(m in low for m in _D2H_MARKERS):
        return "d2h_us"
    return "other_us"


def summarize_trace(
    log_dir: str, device_only: bool = True, top: int = 15
) -> dict:
    """{'files', 'device_pids', 'host_pids', 'total_us', 'host_us',
    'transfer', 'by_name': [(name, us, count)]}

    Aggregates complete ("X") event durations by event name across every
    trace file, restricted (by default) to processes whose metadata
    process_name mentions a device lane ("/device:" — TPU/GPU streams).
    Host lanes are no longer silently dropped: their total rides along
    as `host_us` (+ `host_by_name` top rows), and memcpy/infeed/outfeed
    transfer events from EVERY lane are classified into the `transfer`
    breakdown {h2d_us, d2h_us, other_us, count} — the h2d column is the
    device-side view of the ChunkStream ledger's bytes_put."""
    files = find_trace_files(log_dir)
    device_pids: dict = {}
    host_pids: dict = {}
    durations: dict = defaultdict(float)
    counts: dict = defaultdict(int)
    host_durations: dict = defaultdict(float)
    host_counts: dict = defaultdict(int)
    transfer = {"h2d_us": 0.0, "d2h_us": 0.0, "other_us": 0.0, "count": 0}
    total = 0.0
    host_total = 0.0
    # first pass: lane metadata for every file, and the GLOBAL decision
    # of whether any device lane exists — the fallback must not be
    # per-file, or a host-only trace file alongside a device-lane file
    # (multi-host captures) would pour host wall time into the total
    loaded = []
    any_device = False
    for f in files:
        events = _load_events(f)
        lanes = {}
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                lanes[ev.get("pid")] = (ev.get("args") or {}).get("name", "")
        any_device = any_device or any("/device:" in n for n in lanes.values())
        loaded.append((events, lanes))
    restrict = device_only and any_device
    for events, lanes in loaded:
        if restrict:
            pids = {p for p, n in lanes.items() if "/device:" in n}
            device_pids.update({p: lanes[p] for p in pids})
            host_pids.update({p: n for p, n in lanes.items()
                              if "/device:" not in n})
        else:
            # CPU-only captures have no "/device:" lane (everything runs
            # under "/host:CPU"): take every lane rather than reporting
            # an empty trace. `pids = None` means "admit any pid" so
            # files without process_name metadata still count.
            pids = None
            device_pids.update(lanes)
        for ev in events:
            if ev.get("ph") != "X":
                continue
            name = ev.get("name", "?")
            if name.startswith("$"):
                # python source-frame events ($file.py:line fn) are a
                # nested call stack — summing them double-counts; the
                # kernel/op events carry the real time
                continue
            dur = float(ev.get("dur", 0.0))
            kind = _classify_transfer(name)
            if kind is not None:
                transfer[kind] += dur
                transfer["count"] += 1
            if pids is not None and ev.get("pid") not in pids:
                # a host-lane event under device restriction: tallied
                # in the host breakdown instead of dropped
                host_durations[name] += dur
                host_counts[name] += 1
                host_total += dur
                continue
            durations[name] += dur
            counts[name] += 1
            total += dur
    by_name = sorted(
        ((n, d, counts[n]) for n, d in durations.items()),
        key=lambda t: -t[1],
    )[: max(top, 0)]
    host_by_name = sorted(
        ((n, d, host_counts[n]) for n, d in host_durations.items()),
        key=lambda t: -t[1],
    )[: max(top, 0)]
    return {
        "files": files,
        "device_pids": device_pids,
        "host_pids": host_pids,
        # NOTE (ADVICE r2): durations are summed across ALL matched lanes
        # and threads. On a multi-device (or multi-stream) capture,
        # overlapping execution is counted once per lane, so total_us can
        # legitimately exceed wall time; num_lanes is surfaced so readers
        # can tell aggregate device-time from wall time.
        "num_lanes": len(device_pids),
        "total_us": total,
        "host_us": host_total,
        "host_by_name": host_by_name,
        "transfer": transfer,
        "by_name": by_name,
    }


def format_summary(s: dict) -> str:
    lines = []
    if not s["files"]:
        return "no .trace.json(.gz) files found (did the trace capture run?)"
    lines.append(f"trace files : {len(s['files'])}")
    lanes = ", ".join(str(v) for v in s["device_pids"].values()) or "(none)"
    lines.append(f"device lanes: {lanes}")
    n_lanes = s.get("num_lanes", len(s["device_pids"]))
    qualifier = (
        f" (summed across {n_lanes} lanes; overlapping execution counts "
        "once per lane, so this can exceed wall time)"
        if n_lanes > 1 else ""
    )
    lines.append(f"device time : {s['total_us'] / 1e3:.3f} ms{qualifier}")
    if s.get("host_us"):
        n_host = len(s.get("host_pids", {}))
        lines.append(
            f"host time   : {s['host_us'] / 1e3:.3f} ms across "
            f"{n_host} host lane(s) (--all_lanes merges them into the "
            "breakdown)")
    tr = s.get("transfer") or {}
    if tr.get("count"):
        lines.append(
            f"transfer    : H2D {tr['h2d_us'] / 1e3:.3f} ms, "
            f"D2H {tr['d2h_us'] / 1e3:.3f} ms, "
            f"other {tr['other_us'] / 1e3:.3f} ms "
            f"({tr['count']} memcpy/infeed events)")
    if s["by_name"]:
        width = max(len(n) for n, _, _ in s["by_name"])
        lines.append(f"{'kernel/fusion':<{width}}  {'total':>10}  {'count':>6}  share")
        for name, us, cnt in s["by_name"]:
            share = us / s["total_us"] if s["total_us"] else 0.0
            lines.append(
                f"{name:<{width}}  {us / 1e3:>8.3f}ms  {cnt:>6}  {share:>5.1%}"
            )
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Device-time breakdown of a jax.profiler trace dir")
    ap.add_argument("log_dir")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--all_lanes", action="store_true",
                    help="include host lanes (default: device lanes only)")
    args = ap.parse_args(argv)
    s = summarize_trace(args.log_dir, device_only=not args.all_lanes,
                        top=args.top)
    print(format_summary(s))
    return 0 if s["files"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
