"""Seeding.

Reference parity for `set_seed` (utils.py:10-17) minus the CUDA/cudnn
knobs, which have no TPU analogue: JAX computation is deterministic by
construction because all randomness flows through explicit `jax.random`
keys threaded by the trainer (SURVEY.md §5 "Race detection"). The host
seeds only affect host-side numpy/python use (e.g. day-order shuffles use
their own seeded Generators and don't depend on these globals).
"""

from __future__ import annotations

import random

import numpy as np

import jax


def set_seed(seed: int) -> jax.Array:
    """Seed host RNGs and return the root jax PRNG key."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)
