"""Host-CPU multi-device test rig.

`force_host_devices(n)` makes `jax.devices()` return `n` virtual CPU
devices — the TPU-world answer to "test multi-node without a cluster"
(SURVEY.md §4): sharding/collective code paths run unchanged against a
CPU mesh, exactly how the driver dry-runs the multi-chip path.

Must be called BEFORE any JAX backend is initialized. It also neutralizes
sandbox TPU-plugin shims (which pin ``jax_platforms`` at the config level,
so setting the JAX_PLATFORMS env var alone is not enough) by removing
their backend factory before first use.
"""

from __future__ import annotations

import os


def force_host_devices(n: int = 8) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # graftlint: disable=JGL007 best-effort pin for jax versions without the config key; the env var above already covers them
        pass
    try:
        from jax._src import xla_bridge as xb

        for plugin in ("axon", "neuron"):
            xb._backend_factories.pop(plugin, None)
    except Exception:  # graftlint: disable=JGL007 jax-internal API probe — absent on some versions; the factories then never existed and need no removal
        pass


def host_device_count() -> int:
    import jax

    return len(jax.devices())


def enable_persistent_compile_cache(
    cache_dir: str = "/tmp/factorvae_jax_cache",
) -> None:
    """Persistent XLA compilation cache (shared by tests and bench): repeat
    runs skip recompiles — the dominant fixed cost on slow hosts and under
    remote compilation. No-op on JAX versions without the flags."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # graftlint: disable=JGL007 documented no-op on JAX versions without the cache flags (docstring); runs are correct without the cache, just slower
        pass
