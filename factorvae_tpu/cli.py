"""Command-line experiment driver.

Flag-for-flag parity with the reference CLI (main.py:90-114) — every
reference flag is accepted with the same name and default — plus the
TPU-framework extensions (mesh/data-parallel knobs, resume, recon-loss
selection, bf16 compute, score export). ``--num_workers`` is accepted for
compatibility and ignored: there are no loader workers in this design
(the reference parses it and never wires it either, main.py:112).

Usage:
    python -m factorvae_tpu.cli --num_epochs 30 --dataset ./data/csi_data.pkl
    python -m factorvae_tpu.cli --score_only --resume ...
    python -m factorvae_tpu.cli --fleet_seeds 8 --auto_plan ...  # seed fleet

The nightly closed loop (append -> drift judge -> warm refit ->
zero-downtime rollover) lives in its own driver:
`python -m factorvae_tpu.wf` (docs/walkforward.md).
"""

from __future__ import annotations

import argparse
import sys

from factorvae_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Train a FactorVAE model on stock data (TPU-native)")
    # --- reference flags (main.py:92-113) ---
    p.add_argument("--num_epochs", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--num_latent", type=int, default=158,
                   help="number of input features C (reference --num_latent)")
    p.add_argument("--num_portfolio", type=int, default=128)
    p.add_argument("--seq_len", type=int, default=20)
    p.add_argument("--num_factor", type=int, default=96)
    p.add_argument("--hidden_size", type=int, default=64)
    p.add_argument("--dataset", type=str, default=None)
    p.add_argument("--start_time", type=str, default=None)
    p.add_argument("--fit_end_time", type=str, default=None)
    p.add_argument("--val_start_time", type=str, default=None)
    p.add_argument("--val_end_time", type=str, default=None)
    p.add_argument("--end_time", type=str, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--run_name", type=str, default=None)
    p.add_argument("--save_dir", type=str, default=None)
    p.add_argument("--num_workers", type=int, default=4,
                   help="accepted for reference parity; unused (no loader workers)")
    p.add_argument("--wandb", action="store_true")
    # --- TPU-framework extensions ---
    p.add_argument("--days_per_step", type=int, default=None,
                   help="days whose grads are averaged per update (1 = reference-faithful)")
    p.add_argument("--mesh", action="store_true",
                   help="shard over all visible devices (data x stock "
                        "mesh). Composes with --fleet_seeds (seed lanes "
                        "ride the 'data' axis) and --panel_residency "
                        "stream (per-shard chunk prefetch) — one "
                        "program, all three axes (docs/sharding.md)")
    p.add_argument("--mesh_stock", type=int, default=None,
                   help="size of the 'stock' (cross-section) mesh axis "
                        "(default: 1, or a measured plan row's 'mesh' "
                        "block under --auto_plan)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest full-state checkpoint")
    p.add_argument("--fleet_seeds", type=int, default=None,
                   help="train N independent seeds ([seed, seed+N)) "
                        "simultaneously in one seed-parallel program "
                        "(train/fleet.py: stacked params, vmapped epoch, "
                        "one HBM panel copy), report the per-seed "
                        "Rank-IC sweep, then score/export with the best "
                        "seed's best-val weights. With --auto_plan the "
                        "planner's raced seeds_per_program knob sizes "
                        "the programs; otherwise all N share one")
    p.add_argument("--hyper_grid", type=str, default=None,
                   metavar="LR:KLW,LR:KLW,...",
                   help="race a hyperparameter grid through hyper-fleet "
                        "programs (ISSUE 12, train/fleet.py): each "
                        "lr:kl_weight point trains as one LANE of a "
                        "stacked program (per-lane runtime scalars — one "
                        "compile for the whole grid), every point scores "
                        "its best-val snapshot, and the rest of the "
                        "pipeline (score/backtest/export) runs on the "
                        "best point's weights. Composes with --mesh "
                        "(lanes ride the 'data' axis; an indivisible "
                        "lane count is the documented CompositionError, "
                        "exit 2) and with --auto_plan "
                        "(Plan.lanes_per_program sizes the programs)")
    p.add_argument("--kl_weight", type=float, default=None,
                   help="scale on the summed-over-K KL term (default 1.0 "
                        "= reference-faithful loss). Measured null for "
                        "k60 parity (r4 sweep: recovery 0.31 -> 0.33, "
                        "within noise) — the r5 diagnosis shows KL~=0 "
                        "from epoch 2, so this lever has nothing to "
                        "rescale there; kept as a general loss knob")
    p.add_argument("--recon_loss", choices=["mse", "nll"], default=None,
                   help="mse = reference-faithful single-sample MSE; nll = "
                        "Gaussian NLL (default: mse, or the preset's choice)")
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="bfloat16 compute dtype — the default on every CLI "
                        "path and preset (measured-best on TPU, PERF.md); "
                        "--no-bf16 forces float32. Since the mixed-"
                        "precision path landed, a bf16 TRAINING run keeps "
                        "f32 master weights and optimizer state with one "
                        "bf16 cast feeding forward/backward plus dynamic "
                        "loss scaling (train/state.py; docs/precision.md) "
                        "— not a whole-model cast. An --auto_plan row may "
                        "also pin the training dtype separately "
                        "(train_precision); an explicit --bf16/--no-bf16 "
                        "still wins for both")
    p.add_argument("--pallas", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="force the fused Pallas kernels (attention + GRU "
                        "recurrence, ops/pallas/) on (--pallas) or off "
                        "(--no-pallas). Default: 'auto' — per-shape choice "
                        "from the measured on-chip race "
                        "(ops/pallas/select.py)")
    p.add_argument("--pallas_auto", action="store_true",
                   help="deprecated alias of the default 'auto' behavior "
                        "(kept for round-2 command lines)")
    p.add_argument("--max_stocks", type=int, default=None,
                   help="cross-section padding N_max (default: inferred)")
    p.add_argument("--panel_residency", choices=["hbm", "stream"],
                   default=None,
                   help="where the feature panel lives: 'hbm' ships it "
                        "to the device once (default); 'stream' keeps it "
                        "host-resident and double-buffers prefetched "
                        "day-chunks (data/stream.py) — bitwise-identical "
                        "results with O(2 chunks) device residency, for "
                        "universes/histories past the HBM wall. "
                        "--auto_plan may pick it from a measured row")
    p.add_argument("--stream_chunk_days", type=int, default=None,
                   help="days per host->device transfer chunk under "
                        "--panel_residency stream (default 32, or the "
                        "planner's raced value; docs/streaming.md has "
                        "the budget math)")
    p.add_argument("--auto_plan", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="let the execution planner (factorvae_tpu/plan.py) "
                        "pick days_per_step, compute dtype, day-batch "
                        "layout and the cross-section pad target for this "
                        "backend+shape from its measured envelope table "
                        "(PLAN_TABLE.json / scripts/autotune_plan.py); "
                        "unmeasured shapes get the conservative "
                        "per-backend default. Explicitly passed flags "
                        "(--days_per_step, --bf16/--no-bf16, "
                        "--max_stocks) still win")
    p.add_argument("--score_only", action="store_true",
                   help="skip training; score [--score_start, --score_end] from the best checkpoint")
    p.add_argument("--score_start", type=str, default="2019-01-01")
    p.add_argument("--score_end", type=str, default="2020-12-31")
    p.add_argument("--score_dir", type=str, default="./scores")
    p.add_argument("--stochastic_scores", dest="stochastic_scores",
                   action="store_true", default=None,
                   help="sample at inference like the reference "
                        "(module.py:123). This is the DEFAULT, matching "
                        "both the reference and ModelConfig")
    p.add_argument("--deterministic_scores", dest="stochastic_scores",
                   action="store_false",
                   help="score with the prior mean instead of sampling "
                        "(reproducible scores; diverges from the "
                        "reference's stochastic inference)")
    p.add_argument("--int8_scores", action="store_true",
                   help="quantize weights to per-channel int8 for the "
                        "scoring pass (ops/quant.py): 4x smaller HBM "
                        "parameter residency, rank-correlation ~1 vs "
                        "the float path. Also applies to --export "
                        "(int8-baked serving artifact)")
    p.add_argument("--metrics_jsonl", type=str, default=None)
    p.add_argument("--prom_textfile", type=str, default=None,
                   metavar="PATH",
                   help="write a Prometheus textfile (node-exporter "
                        "textfile-collector format) of the latest "
                        "epoch's metrics to PATH after every epoch — "
                        "the trainer-side half of the live telemetry "
                        "plane (the daemon's is GET /metrics); atomic "
                        "rewrite, scraper-safe (obs/metrics.py)")
    p.add_argument("--compile_cache", type=str, default=None,
                   metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(plan.setup_compilation_cache): repeat runs — "
                        "and the scoring daemon's restarts — reuse "
                        "compiled programs from disk instead of paying "
                        "the compile wall again. Default: "
                        "$FACTORVAE_COMPILE_CACHE if set, else off; "
                        "pass 'off' to disable explicitly")
    p.add_argument("--obs", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="run observatory (factorvae_tpu/obs): compile the "
                        "on-device health probes into the epoch scan "
                        "(grad/update/param norms, non-finite counters, "
                        "factor-posterior spread — zero extra dispatches; "
                        "overhead measured by bench.py --obs) and emit "
                        "the host timeline (epoch/stream/checkpoint/"
                        "compile spans) plus one `compile` record per jit "
                        "build — wall time and the guarded cost_analysis/"
                        "memory_analysis program bill (obs/compile.py) — "
                        "into the metrics stream; --metrics_jsonl "
                        "defaults to RUN.jsonl when set. Render with "
                        "python -m factorvae_tpu.obs.report / .timeline. "
                        "--no-obs pins probes off even when a measured "
                        "plan row enables them")
    p.add_argument("--preset", type=str, default=None,
                   help="named config preset (see factorvae_tpu.presets). The "
                        "preset fixes the model architecture; explicitly "
                        "passed data/training flags (--dataset, date ranges, "
                        "--num_epochs, --lr, --seed, --run_name, --save_dir, "
                        "--days_per_step, --wandb) override its values")
    p.add_argument("--profile", type=str, default=None,
                   help="capture a jax.profiler trace of training into this dir")
    p.add_argument("--debug_nans", action="store_true",
                   help="raise on any NaN inside jitted code (replaces the "
                        "reference's silent runtime NaN guards while debugging)")
    p.add_argument("--backtest", action="store_true",
                   help="run the built-in TopkDropout backtest on the "
                        "generated scores (reference backtest.ipynb cell 6 "
                        "parameters: topk 50, n_drop 10, costs 5bp/15bp)")
    p.add_argument("--backtest_topk", type=int, default=50)
    p.add_argument("--backtest_n_drop", type=int, default=10)
    p.add_argument("--backtest_plot", type=str, default=None, metavar="PNG",
                   help="write the report_graph-style 4-panel figure "
                        "(backtest.ipynb cell 7 artifact) to this path")
    p.add_argument("--export", type=str, default=None, metavar="PATH",
                   help="write an AOT serving artifact (StableHLO, weights "
                        "baked in) of the prediction function to PATH")
    p.add_argument("--export_platform", type=str, default=None,
                   help="cross-export target platform (e.g. 'tpu' from a "
                        "CPU host); default: current backend")
    return p


# Reference CLI defaults (main.py:92-113), applied when a flag is neither
# passed explicitly nor supplied by a preset. Flags that may override a
# preset use default=None sentinels in build_parser.
_DEFAULTS = dict(
    num_epochs=30, lr=1e-4, dataset="./data/csi_data.pkl",
    start_time="2009-01-01", fit_end_time="2017-12-31",
    val_start_time="2018-01-01", val_end_time="2018-12-31",
    end_time="2020-12-31", seed=42, run_name="VAE-Revision2",
    save_dir="./best_models", days_per_step=1,
)


def config_from_args(args: argparse.Namespace) -> Config:
    import dataclasses

    def resolve(name, preset_value=None):
        """Explicit flag > preset value > reference default."""
        v = getattr(args, name)
        if v is not None:
            return v
        return preset_value if preset_value is not None else _DEFAULTS[name]

    if args.preset:
        from factorvae_tpu.presets import get_preset

        try:
            cfg = get_preset(args.preset)
        except KeyError as e:
            raise SystemExit(f"error: {e.args[0]}")
        return dataclasses.replace(
            cfg,
            # The preset fixes the *architecture* (sizes/param layout); the
            # behavior knobs below are runtime choices and must still
            # follow the flags (e.g. --deterministic_scores with --preset).
            model=dataclasses.replace(
                cfg.model,
                stochastic_inference=(
                    cfg.model.stochastic_inference
                    if args.stochastic_scores is None
                    else args.stochastic_scores
                ),
                recon_loss=args.recon_loss or cfg.model.recon_loss,
                kl_weight=(cfg.model.kl_weight if args.kl_weight is None
                           else args.kl_weight),
                compute_dtype=(
                    cfg.model.compute_dtype if args.bf16 is None
                    else ("bfloat16" if args.bf16 else "float32")
                ),
                use_pallas_attention=(
                    "auto" if args.pallas_auto
                    else cfg.model.use_pallas_attention if args.pallas is None
                    else args.pallas
                ),
                use_pallas_gru=(
                    "auto" if args.pallas_auto
                    else cfg.model.use_pallas_gru if args.pallas is None
                    else args.pallas
                ),
            ),
            data=dataclasses.replace(
                cfg.data,
                dataset_path=resolve("dataset", cfg.data.dataset_path),
                start_time=resolve("start_time", cfg.data.start_time),
                fit_end_time=resolve("fit_end_time", cfg.data.fit_end_time),
                val_start_time=resolve("val_start_time", cfg.data.val_start_time),
                val_end_time=resolve("val_end_time", cfg.data.val_end_time),
                end_time=resolve("end_time", cfg.data.end_time),
                panel_residency=(cfg.data.panel_residency
                                 if args.panel_residency is None
                                 else args.panel_residency),
                stream_chunk_days=(cfg.data.stream_chunk_days
                                   if args.stream_chunk_days is None
                                   else args.stream_chunk_days),
            ),
            train=dataclasses.replace(
                cfg.train,
                num_epochs=resolve("num_epochs", cfg.train.num_epochs),
                lr=resolve("lr", cfg.train.lr),
                seed=resolve("seed", cfg.train.seed),
                run_name=resolve("run_name", cfg.train.run_name),
                save_dir=resolve("save_dir", cfg.train.save_dir),
                days_per_step=resolve("days_per_step", cfg.train.days_per_step),
                wandb=args.wandb,
                obs_probes=(cfg.train.obs_probes if args.obs is None
                            else args.obs),
            ),
        )
    return Config(
        model=ModelConfig(
            num_features=args.num_latent,
            hidden_size=args.hidden_size,
            num_factors=args.num_factor,
            num_portfolios=args.num_portfolio,
            seq_len=args.seq_len,
            recon_loss=args.recon_loss or "mse",
            kl_weight=1.0 if args.kl_weight is None else args.kl_weight,
            # bf16 is the measured-best default on TPU (PERF.md); --no-bf16
            # opts back into float32 compute.
            compute_dtype="float32" if args.bf16 is False else "bfloat16",
            stochastic_inference=(True if args.stochastic_scores is None
                                  else args.stochastic_scores),
            use_pallas_attention=(
                "auto" if args.pallas_auto or args.pallas is None
                else bool(args.pallas)),
            use_pallas_gru=(
                "auto" if args.pallas_auto or args.pallas is None
                else bool(args.pallas)),
        ),
        data=DataConfig(
            dataset_path=resolve("dataset"),
            start_time=resolve("start_time"),
            fit_end_time=resolve("fit_end_time"),
            val_start_time=resolve("val_start_time"),
            val_end_time=resolve("val_end_time"),
            end_time=resolve("end_time"),
            seq_len=args.seq_len,
            max_stocks=args.max_stocks,
            panel_residency=args.panel_residency or "hbm",
            stream_chunk_days=(32 if args.stream_chunk_days is None
                               else args.stream_chunk_days),
        ),
        train=TrainConfig(
            num_epochs=resolve("num_epochs"),
            lr=resolve("lr"),
            seed=resolve("seed"),
            days_per_step=resolve("days_per_step"),
            run_name=resolve("run_name"),
            save_dir=resolve("save_dir"),
            wandb=args.wandb,
            obs_probes=bool(args.obs),
        ),
        mesh=MeshConfig(stock_axis=args.mesh_stock or 1),
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)

    # multi-host pods: wire processes together before any backend use
    from factorvae_tpu.parallel.multihost import maybe_initialize

    maybe_initialize()

    # Persistent XLA compilation cache (ISSUE 8): flag > env > off.
    # Configured before any jit so the epoch/scoring programs of this
    # run land in (or load from) the cache.
    from factorvae_tpu import plan as planlib

    compile_cache_dir = planlib.setup_compilation_cache(args.compile_cache)

    from factorvae_tpu.data import PanelDataset, build_panel, load_frame
    from factorvae_tpu.train import Trainer, load_params
    from factorvae_tpu.utils.logging import (
        MetricsLogger,
        Timeline,
        install_timeline,
    )

    # --obs with no explicit metrics path still needs somewhere for the
    # RUN stream to land; RUN.jsonl is the documented default.
    metrics_path = args.metrics_jsonl or ("RUN.jsonl" if args.obs else None)
    logger = MetricsLogger(
        jsonl_path=metrics_path,
        use_wandb=cfg.train.wandb,
        run_name=cfg.train.run_name,
        config=cfg.to_dict(),
    )
    prev_tl = None
    if metrics_path:
        # Host timeline: Trainer/fleet epochs, ChunkStream prefetch,
        # async checkpoint saves and the jit compile watchdog all emit
        # spans into the same stream the metrics land in.
        prev_tl = install_timeline(Timeline(logger))
    prev_exp = None
    if args.prom_textfile:
        # Trainer-side scrape surface (ISSUE 10): the epoch loops
        # rewrite this textfile after every epoch; same registry
        # pattern (and same restore-in-finally contract) as the
        # timeline.
        from factorvae_tpu.obs.metrics import (
            TextfileExporter,
            install_exporter,
        )

        prev_exp = install_exporter(TextfileExporter(args.prom_textfile))
    # try/finally so EVERY exit path — including the early `return 2`
    # error paths — detaches the timeline and closes the metrics stream
    # (the close-on-error contract MetricsLogger now carries).
    try:
        logger.log("config", **{"json": cfg.to_json()})
        if compile_cache_dir:
            logger.log("compile_cache", dir=compile_cache_dir)
        if args.obs:
            logger.log("obs", probes=cfg.train.obs_probes,
                       run_jsonl=metrics_path)

        import os

        if not os.path.exists(cfg.data.dataset_path):
            print(
                f"error: dataset not found: {cfg.data.dataset_path} "
                f"(see data/README.md for the qlib ETL recipe)",
                file=sys.stderr,
            )
            return 2

        frame = load_frame(cfg.data.dataset_path, cfg.data.select_feature)
        panel = build_panel(frame)

        auto_plan = None
        if args.auto_plan:
            # Adaptive execution planner: measured per-(platform, shape)
            # knobs, conservative per-backend defaults elsewhere. Explicit
            # flags keep precedence (their argparse sentinel is None when
            # not passed).
            from factorvae_tpu import plan as planlib

            auto_plan = planlib.plan_for_config(
                cfg, panel.num_instruments,
                shard=(args.mesh_stock or 1) if args.mesh else 1)
            cfg = planlib.apply_plan(
                cfg, auto_plan,
                keep_days_per_step=args.days_per_step is not None,
                keep_dtype=args.bf16 is not None,
                keep_pad=args.max_stocks is not None,
                keep_kernels=args.pallas is not None or args.pallas_auto,
                keep_residency=(args.panel_residency is not None
                                or args.stream_chunk_days is not None),
                keep_obs=args.obs is not None,
                # A measured mesh-shape row only matters under --mesh,
                # and an explicit --mesh_stock still wins.
                keep_mesh=not args.mesh or args.mesh_stock is not None,
            )
            logger.log("plan", **auto_plan.describe(
                planlib.shape_of(cfg, panel.num_instruments)))

        dataset = PanelDataset(
            panel,
            seq_len=cfg.data.seq_len,
            max_stocks=cfg.data.max_stocks,
            pad_multiple=cfg.data.pad_multiple,
            residency=cfg.data.panel_residency,
        )
        if dataset.panel.num_features != cfg.model.num_features:
            print(
                f"error: model expects {cfg.model.num_features} features "
                f"(--num_latent/preset) but {cfg.data.dataset_path} has "
                f"{dataset.panel.num_features}",
                file=sys.stderr,
            )
            return 2

        # The mesh (if any) the run trains/scores on — threaded into the
        # scoring pass so stream-resident chunks land pre-sharded. Built
        # HERE so a shape that doesn't fit the visible devices (a stale
        # plan row's factorization, a lone-device host) is the CLI's
        # documented exit-2 error, not a traceback.
        run_mesh = None
        if args.mesh:
            from factorvae_tpu.parallel.mesh import make_mesh

            try:
                run_mesh = make_mesh(cfg.mesh)
            except ValueError as e:
                print(
                    f"error: cannot build the requested "
                    f"(data x stock) mesh over the visible devices: {e} "
                    f"(--mesh_stock overrides a plan row's shape)",
                    file=sys.stderr)
                return 2
        if args.score_only:
            # Scoring needs no training split — restore the best-val weights
            # through the model factory (reference utils.load_model analogue).
            # --mesh applies here too: the HBM panel re-places onto the
            # mesh (stream chunks land pre-sharded via mesh=run_mesh
            # below), so a score-only pass on a wide universe shards
            # exactly like a train+score run's scoring leg.
            if run_mesh is not None:
                from factorvae_tpu.parallel.sharding import shard_dataset

                shard_dataset(run_mesh, dataset)
            from factorvae_tpu.models.factorvae import load_model

            path = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
            if not os.path.isdir(path):
                print(f"error: no checkpoint at {path}; train first", file=sys.stderr)
                return 2
            _, params = load_model(cfg, checkpoint_path=path, n_max=dataset.n_max)
        elif args.hyper_grid:
            # Hyper-fleet config grid (ISSUE 12): the whole lr:kl_weight
            # grid rides ONE compiled program per shape bucket
            # (eval/sweep.grid_sweep -> train/fleet.py lane_configs);
            # downstream scoring/backtest/export runs on the winning
            # point's best-val weights under its own tagged names.
            import contextlib

            import numpy as np

            from factorvae_tpu.eval.sweep import (
                _point_config,
                grid_sweep,
                parse_hyper_grid,
                point_label,
            )
            from factorvae_tpu.models.factorvae import load_model
            from factorvae_tpu.parallel.compose import CompositionError
            from factorvae_tpu.utils.profiling import debug_nans, trace

            points = parse_hyper_grid(args.hyper_grid)
            if not points:
                print("error: --hyper_grid parsed to zero points "
                      "(format: LR:KLW,LR:KLW,...)", file=sys.stderr)
                return 2
            lpp = None
            if auto_plan is not None:
                # measured hyper row > measured fleet row (>1 only:
                # seeds_per_program's default IS 1, which is "no
                # signal", not "serialize the grid" — one single-lane
                # program per point would fold every lane to the serial
                # trace and pay the per-config compile this mode
                # exists to amortize) > whole grid in one program
                lpp = auto_plan.lanes_per_program or None
                if lpp is None and auto_plan.seeds_per_program > 1:
                    lpp = auto_plan.seeds_per_program
            nan_ctx = (debug_nans() if args.debug_nans
                       else contextlib.nullcontext())
            try:
                with trace(args.profile), nan_ctx:
                    df = grid_sweep(
                        cfg, dataset, points,
                        score_start=args.score_start,
                        score_end=args.score_end,
                        logger=logger, lanes_per_program=lpp,
                        mesh=run_mesh)
            except CompositionError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            except ValueError as e:
                if "empty training split" in str(e):
                    print(
                        f"error: no trading days in [{cfg.data.start_time}, "
                        f"{cfg.data.fit_end_time}]; adjust --start_time/"
                        f"--fit_end_time", file=sys.stderr)
                    return 2
                raise

            by_label = {point_label(p): p for p in points}

            def _point_ckpt(lbl):
                pcfg = _point_config(cfg, by_label[lbl], lbl)
                return pcfg, os.path.join(pcfg.train.save_dir,
                                          pcfg.checkpoint_name())

            ranked = df["rank_ic"].dropna()
            ranked = ranked[np.isfinite(df.loc[ranked.index, "best_val"])]
            ranked = ranked[[os.path.isdir(_point_ckpt(lbl)[1])
                             for lbl in ranked.index]]
            if ranked.empty:
                print("error: no grid point with finite rank_ic and a "
                      "best-val checkpoint; nothing to score/export "
                      "(check the grid / data ranges)", file=sys.stderr)
                return 2
            best_label = str(ranked.idxmax())
            # best_label here is the CHECKPOINT-FILTERED winner (a point
            # whose weights survived on disk); the summary's own
            # best_label is the raw rank_ic argmax — keep the filtered
            # one, it is what ships downstream.
            logger.log("hyper_grid", best_label=best_label,
                       points=[point_label(p) for p in points],
                       **{k: v for k, v in df.attrs["summary"].items()
                          if k != "best_label"})
            cfg, best_path = _point_ckpt(best_label)
            _, params = load_model(cfg, checkpoint_path=best_path,
                                   n_max=dataset.n_max)
        elif args.fleet_seeds and args.fleet_seeds > 1:
            # Seed-parallel fleet (train/fleet.py): one program trains the
            # whole seed range [seed, seed+N), the sweep frame picks the
            # winner by Rank-IC, and the rest of the pipeline (scoring /
            # backtest / export) runs on that winner's best-val weights
            # under its own per-seed checkpoint name.
            import dataclasses

            from factorvae_tpu.eval.sweep import seed_sweep
            from factorvae_tpu.models.factorvae import load_model

            # The seed axis composes with the mesh since PR 6: seed
            # lanes shard over 'data', the cross-section over 'stock'
            # (parallel/partition.py; compose.validate checks the
            # divisibility constraints below). run_mesh was built above.
            seeds = list(range(cfg.train.seed, cfg.train.seed + args.fleet_seeds))
            spp = auto_plan.seeds_per_program if auto_plan is not None else None
            import contextlib

            from factorvae_tpu.parallel.compose import CompositionError
            from factorvae_tpu.utils.profiling import debug_nans, trace

            nan_ctx = debug_nans() if args.debug_nans else contextlib.nullcontext()
            try:
                with trace(args.profile), nan_ctx:
                    df = seed_sweep(
                        cfg, dataset, seeds=seeds,
                        score_start=args.score_start, score_end=args.score_end,
                        logger=logger, fleet=True, seeds_per_program=spp,
                        # --resume: each group restores from its lockstep
                        # per-seed full-state checkpoints when present.
                        fleet_resume=args.resume, mesh=run_mesh)
            except CompositionError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            except ValueError as e:
                if "empty training split" in str(e):
                    print(
                        f"error: no trading days in [{cfg.data.start_time}, "
                        f"{cfg.data.fit_end_time}]; adjust --start_time/"
                        f"--fit_end_time", file=sys.stderr)
                    return 2
                raise
            # Winner = best rank_ic among the seeds with a finite best_val
            # AND a best-val checkpoint on disk. The finite-best_val filter
            # matters beyond NaN hygiene: a seed whose validation never
            # improved was scored on FINAL-epoch params and wrote no fresh
            # checkpoint this run — a stale same-name directory from an
            # earlier run would otherwise pass the isdir test and export
            # weights that never produced the winning rank_ic.
            def _ckpt(seed):
                c = dataclasses.replace(
                    cfg, train=dataclasses.replace(cfg.train, seed=int(seed)))
                return os.path.join(c.train.save_dir, c.checkpoint_name())

            import numpy as np

            ranked = df["rank_ic"].dropna()
            ranked = ranked[np.isfinite(df.loc[ranked.index, "best_val"])]
            ranked = ranked[[os.path.isdir(_ckpt(s)) for s in ranked.index]]
            if ranked.empty:
                # Every seed's scores were NaN (e.g. a divergent lr) or no
                # checkpoint survived: there is no winner to pick — fail
                # like every other CLI path, with a message instead of an
                # int(NaN) traceback.
                print("error: no fleet seed with finite rank_ic and a "
                      "best-val checkpoint; nothing to score/export "
                      "(check lr / data ranges)", file=sys.stderr)
                return 2
            best_seed = int(ranked.idxmax())
            logger.log("fleet_sweep", best_seed=best_seed,
                       seeds=seeds, **df.attrs["summary"])
            cfg = dataclasses.replace(
                cfg, train=dataclasses.replace(cfg.train, seed=best_seed))
            _, params = load_model(cfg, checkpoint_path=_ckpt(best_seed),
                                   n_max=dataset.n_max)
        else:
            from factorvae_tpu.parallel.compose import CompositionError
            from factorvae_tpu.utils.profiling import trace

            try:
                trainer = Trainer(cfg, dataset, logger=logger,
                                  mesh=run_mesh)
            except CompositionError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            except ValueError as e:
                if "empty training split" in str(e):
                    print(
                        f"error: no trading days in [{cfg.data.start_time}, "
                        f"{cfg.data.fit_end_time}] — the dataset covers "
                        f"[{dataset.dates[0].date()}, {dataset.dates[-1].date()}]; "
                        f"adjust --start_time/--fit_end_time",
                        file=sys.stderr,
                    )
                    return 2
                raise
            import contextlib

            from factorvae_tpu.utils.profiling import debug_nans

            nan_ctx = debug_nans() if args.debug_nans else contextlib.nullcontext()
            with trace(args.profile), nan_ctx:
                state, _ = trainer.fit(resume=args.resume)
            # Score with the best-validation weights (what the reference's
            # backtest loads, backtest.ipynb cell 2), not the final step.
            best = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
            params = load_params(best, state.params) if os.path.isdir(best) else state.params
            if os.path.isdir(best):
                # Serving/walk-forward admission drop-in: with it, the
                # weights directory resolves its Config standalone
                # (serve.registry.checkpoint_config) — admission no
                # longer depends on the sibling full-state _ckpt
                # manager surviving retention.
                with open(os.path.join(best, "serve_config.json"),
                          "w") as fh:
                    fh.write(cfg.to_json())

        from factorvae_tpu.eval import RankIC, export_scores, generate_prediction_scores

        score_cfg = cfg
        if auto_plan is not None:
            # Scoring gets the plan's SCORING knobs — the measured winner
            # flips between workloads (r05: the scoring dtype/layout winner
            # differs from the training one). Safe on the same params:
            # compute_dtype only casts activations and flatten_days keeps an
            # identical parameter tree. A user-forced dtype still wins.
            import dataclasses

            from factorvae_tpu import plan as planlib

            m = planlib.score_model_config(cfg.model, auto_plan)
            if args.bf16 is not None:
                m = dataclasses.replace(m, compute_dtype=cfg.model.compute_dtype)
            score_cfg = dataclasses.replace(cfg, model=m)

        scores = generate_prediction_scores(
            params, score_cfg, dataset,
            start=args.score_start, end=args.score_end,
            stochastic=None,  # defer to cfg.model.stochastic_inference
            with_labels=True,
            int8=args.int8_scores,
            mesh=run_mesh,
        )
        path = export_scores(scores, cfg, args.score_dir)
        ic = RankIC(scores.dropna(), "LABEL0", "score")
        logger.log(
            "scores",
            path=path,
            rank_ic=float(ic["RankIC"].iloc[0]),
            rank_ic_ir=float(ic["RankIC_IR"].iloc[0]),
        )
        if args.backtest:
            from factorvae_tpu.eval.backtest import (
                simulate_topk_account,
                topk_dropout_backtest,
            )

            bt = topk_dropout_backtest(
                scores.dropna(), topk=args.backtest_topk,
                n_drop=args.backtest_n_drop,
            )
            logger.log("backtest", **{
                k: v for k, v in bt.summary().items() if v is not None
            })
            # Full-fidelity account simulation (cell 6 exchange config) and
            # the cell-8 annualized excess-return risk table. Pass the
            # UN-dropped frame: the simulator owns the NaN semantics (all-NaN
            # day = no-trade day that marks to market; in-frame NaN-label
            # name = undealable on the execution day).
            acct = simulate_topk_account(
                scores, topk=args.backtest_topk,
                n_drop=args.backtest_n_drop,
            )
            logger.log("backtest_account", **{
                k: (v if v is None or isinstance(v, (int, float)) else float(v))
                for k, v in acct.summary().items()
            })
            if args.backtest_plot:
                from factorvae_tpu.eval.plots import report_graph

                out_png = report_graph(
                    acct.report, args.backtest_plot,
                    title=cfg.train.run_name)
                logger.log("backtest_plot", path=out_png)
        if args.export:
            from factorvae_tpu.eval.export_aot import export_prediction

            platforms = (args.export_platform,) if args.export_platform else None
            blob = export_prediction(
                params, cfg, n_max=dataset.n_max,
                stochastic=cfg.model.stochastic_inference, platforms=platforms,
                int8=args.int8_scores,
            )
            with open(args.export, "wb") as fh:
                fh.write(blob)
            logger.log("export", path=args.export, bytes=len(blob))
        return 0
    finally:
        if metrics_path:
            # Detach the run's timeline before closing the stream
            # (stray spans from daemon watchers become no-ops) and
            # RESTORE whatever the in-process caller had installed.
            install_timeline(prev_tl)
        if args.prom_textfile:
            from factorvae_tpu.obs.metrics import install_exporter

            install_exporter(prev_exp)
        logger.finish()


if __name__ == "__main__":
    sys.exit(main())
