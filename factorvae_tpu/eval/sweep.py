"""Multi-seed Rank-IC sweep harness.

Bitwise RNG parity with the torch reference is impossible (different
PRNGs), so parity is *statistical*: the same Rank-IC within tolerance
across seeds (SURVEY.md §7 hard-part 3). This harness trains S seeds of a
config, scores each deterministically, and reports per-seed Rank-IC plus
the mean ± std the parity comparison needs.

Execution modes:
- serial (default): one `Trainer` per seed, strictly sequential — the
  resume-compatible equality oracle.
- ``fleet=True``: seeds not adopted from ``prior_records`` train
  together in seed-parallel programs of ``seeds_per_program`` (the
  planner's raced knob, plan.py) via `train.fleet.FleetTrainer`, then
  score in one seed-batched scan (`eval.predict.predict_panel_fleet`).
  Output frame, per-seed artifacts (best-val checkpoints under the
  serial names), ``on_seed`` firing and resumed-seed adoption are
  preserved; per-seed numbers match the serial sweep at f32 tolerance
  (bitwise for a 1-seed program), pinned by tests/test_fleet.py.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import pandas as pd

from factorvae_tpu.config import Config
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.eval.metrics import rank_ic_frame
from factorvae_tpu.eval.predict import generate_prediction_scores
from factorvae_tpu.train.checkpoint import load_params
from factorvae_tpu.train.trainer import Trainer
from factorvae_tpu.utils.logging import MetricsLogger


def _adopted_record(seed: int, prev, logger: MetricsLogger,
                    on_seed) -> dict:
    """Record for a seed adopted from ``prior_records`` without
    retraining (shared by the serial and fleet paths)."""
    if not isinstance(prev, dict):
        prev = {"rank_ic": prev}

    def _f(v):
        # JSON round-trips our own NaN placeholders as null
        # (strict-JSON flushes serialize non-finite as null);
        # a resume of a resume must not crash on float(None).
        return float("nan") if v is None else float(v)

    rec = {
        "seed": int(seed),
        "rank_ic": _f(prev["rank_ic"]),
        "rank_ic_ir": _f(prev.get("rank_ic_ir", float("nan"))),
        "best_val": _f(prev.get("best_val", float("nan"))),
    }
    logger.log("sweep_seed_resumed", **rec)
    # Fire on_seed for resumed seeds too (ADVICE r5): callers that
    # persist partial results inside on_seed would otherwise write
    # files missing every seed adopted from prior_records — a
    # resume-of-a-resume would then retrain them. Persisting an
    # already-finished record is idempotent.
    if on_seed is not None:
        on_seed(rec)
    return rec


def _fleet_records(
    config: Config,
    dataset: PanelDataset,
    pending: Sequence[int],
    seeds_per_program: Optional[int],
    score_start: Optional[str],
    score_end: Optional[str],
    logger: MetricsLogger,
    on_seed,
    fleet_resume: bool = False,
    mesh=None,
) -> list:
    """Train `pending` seeds in seed-parallel programs and score each
    group in one seed-batched scan. Returns records in `pending` order.
    ``mesh`` composes the seed axis with a device mesh (seed lanes over
    'data', cross-section over 'stock' — parallel/partition.py)."""
    import jax
    import numpy as np

    from factorvae_tpu.eval.predict import fleet_prediction_scores
    from factorvae_tpu.train.fleet import FleetTrainer

    spp = len(pending) if not seeds_per_program else max(
        1, int(seeds_per_program))
    records = []
    for g0 in range(0, len(pending), spp):
        group = list(pending[g0:g0 + spp])
        trainer = FleetTrainer(config, dataset, group, logger=logger,
                               mesh=mesh)
        state, out = trainer.fit(resume=fleet_resume)
        best_val = np.asarray(out["best_val"])
        # Score with the per-seed BEST-VALIDATION snapshot (the serial
        # selection rule). A seed whose selection never improved (NaN
        # loss stream) falls back to its FINAL-epoch params, with the
        # same warning the serial path logs for a missing checkpoint.
        scoring = out["best_params"]
        for i, seed in enumerate(group):
            if not np.isfinite(best_val[i]):
                logger.log(
                    "sweep_warning", seed=int(seed),
                    note="best-val selection never improved; scoring "
                         "FINAL-epoch params")
                scoring = jax.tree.map(
                    lambda b, p: b.at[i].set(p[i]), scoring, state.params)
        # Scoring emits NaN BY DESIGN (padded/absent stocks), so a
        # caller-armed --debug_nans guard must not trip here — the
        # serial CLI likewise scores outside its NaN context; only the
        # training epochs above run guarded.
        from factorvae_tpu.utils.profiling import debug_nans

        with debug_nans(False):
            frames = fleet_prediction_scores(
                scoring, config, dataset, start=score_start,
                end=score_end, stochastic=False, with_labels=True,
                mesh=mesh)
        for i, seed in enumerate(group):
            ic = rank_ic_frame(frames[i].dropna(), "LABEL0", "score")
            rec = {
                "seed": int(seed),
                "rank_ic": float(ic["RankIC"].iloc[0]),
                "rank_ic_ir": float(ic["RankIC_IR"].iloc[0]),
                "best_val": float(best_val[i]),
            }
            records.append(rec)
            logger.log("sweep_seed", **rec)
            if on_seed is not None:
                on_seed(rec)
    return records


def seed_sweep(
    config: Config,
    dataset: PanelDataset,
    seeds: Sequence[int],
    score_start: Optional[str] = None,
    score_end: Optional[str] = None,
    logger: Optional[MetricsLogger] = None,
    on_seed=None,
    prior_records: Optional[dict] = None,
    fleet: bool = False,
    seeds_per_program: Optional[int] = None,
    fleet_resume: bool = False,
    mesh=None,
) -> pd.DataFrame:
    """Returns a frame indexed by seed with columns
    [rank_ic, rank_ic_ir, best_val]; .attrs['summary'] holds mean/std.

    ``on_seed(rec)`` (optional) fires after each seed completes —
    including seeds adopted from ``prior_records`` — so long-running
    sweeps can persist partial results: a multi-hour CPU sweep killed at
    round end should leave its finished seeds on disk, and a resumed
    sweep's partial file must contain the adopted seeds too.

    ``prior_records`` (optional) maps seed -> an already-finished record
    (``{"rank_ic": float, ...}``, or a bare rank_ic float as older
    partial files stored) restored from such a partial file; those
    seeds are included in the output without retraining, so a restarted
    sweep resumes instead of redoing finished work.

    ``fleet=True`` trains the non-adopted seeds in seed-parallel
    programs of ``seeds_per_program`` (None/0 = one program for all of
    them) and scores each program in one seed-batched scan; the output
    frame keeps the ``seeds`` order either way. ``fleet_resume=True``
    additionally lets each group restore from its lockstep per-seed
    full-state checkpoints (FleetTrainer.fit(resume=True)) — a killed
    fleet sweep continues mid-group instead of retraining the group,
    provided ``checkpoint_every`` was on and the save_dir survived.

    ``mesh`` (optional) composes the run with a device mesh: fleet
    groups train/score with seed lanes sharded over 'data' and the
    cross-section over 'stock'; serial trainings run the sharded serial
    program (parallel/partition.py owns the placement either way).
    """
    logger = logger or MetricsLogger(echo=False)
    prior_records = prior_records or {}
    records = []
    pending = []
    for seed in seeds:
        if int(seed) in prior_records or str(seed) in prior_records:
            prev = prior_records.get(int(seed),
                                     prior_records.get(str(seed)))
            records.append(_adopted_record(seed, prev, logger, on_seed))
            continue
        if fleet:
            pending.append(int(seed))
            continue
        cfg = dataclasses.replace(
            config, train=dataclasses.replace(config.train, seed=int(seed))
        )
        trainer = Trainer(cfg, dataset, mesh=mesh, logger=logger)
        state, out = trainer.fit()
        # Score with the per-seed BEST-VALIDATION weights (the reference
        # backtest's selection rule, backtest.ipynb cell 2; the
        # checkpoint name encodes the seed so sweeps don't collide).
        best = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
        if os.path.isdir(best):
            params = load_params(best, state.params)
        else:
            logger.log("sweep_warning", seed=int(seed),
                       note=f"best-val checkpoint missing at {best}; "
                            "scoring FINAL-epoch params")
            params = state.params
        scores = generate_prediction_scores(
            params, cfg, dataset, start=score_start, end=score_end,
            stochastic=False, with_labels=True,
        )
        ic = rank_ic_frame(scores.dropna(), "LABEL0", "score")
        rec = {
            "seed": int(seed),
            "rank_ic": float(ic["RankIC"].iloc[0]),
            "rank_ic_ir": float(ic["RankIC_IR"].iloc[0]),
            "best_val": float(out["best_val"]),
        }
        records.append(rec)
        logger.log("sweep_seed", **rec)
        if on_seed is not None:
            on_seed(rec)

    if pending:
        records.extend(_fleet_records(
            config, dataset, pending, seeds_per_program,
            score_start, score_end, logger, on_seed,
            fleet_resume=fleet_resume, mesh=mesh))
        # The frame keeps the caller's seed order regardless of how the
        # fleet grouped the training (equality with the serial sweep).
        order = {int(s): i for i, s in enumerate(seeds)}
        records.sort(key=lambda r: order[r["seed"]])

    df = pd.DataFrame(records).set_index("seed")
    df.attrs["summary"] = {
        "rank_ic_mean": float(df["rank_ic"].mean()),
        "rank_ic_std": float(df["rank_ic"].std(ddof=0)),
        "rank_ic_ir_mean": float(df["rank_ic_ir"].mean()),
        # Legacy-resumed seeds may lack rank_ic_ir (NaN, skipped by
        # mean): publish the n that statistic actually covers so it
        # can't read as a num_seeds-seed figure.
        "rank_ic_ir_num_seeds": int(df["rank_ic_ir"].notna().sum()),
        "num_seeds": len(df),
    }
    logger.log("sweep_summary", **df.attrs["summary"])
    return df
