"""Multi-seed Rank-IC sweep harness.

Bitwise RNG parity with the torch reference is impossible (different
PRNGs), so parity is *statistical*: the same Rank-IC within tolerance
across seeds (SURVEY.md §7 hard-part 3). This harness trains S seeds of a
config, scores each deterministically, and reports per-seed Rank-IC plus
the mean ± std the parity comparison needs.

Execution modes:
- serial (default): one `Trainer` per seed, strictly sequential — the
  resume-compatible equality oracle.
- ``fleet=True``: seeds not adopted from ``prior_records`` train
  together in seed-parallel programs of ``seeds_per_program`` (the
  planner's raced knob, plan.py) via `train.fleet.FleetTrainer`, then
  score in one seed-batched scan (`eval.predict.predict_panel_fleet`).
  Output frame, per-seed artifacts (best-val checkpoints under the
  serial names), ``on_seed`` firing and resumed-seed adoption are
  preserved; per-seed numbers match the serial sweep at f32 tolerance
  (bitwise for a 1-seed program), pinned by tests/test_fleet.py.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import pandas as pd

from factorvae_tpu.config import Config
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.eval.metrics import rank_ic_frame
from factorvae_tpu.eval.predict import generate_prediction_scores
from factorvae_tpu.train.checkpoint import load_params
from factorvae_tpu.train.trainer import Trainer
from factorvae_tpu.utils.logging import MetricsLogger


def _float_or_nan(v) -> float:
    """JSON round-trips our own NaN placeholders as null (strict-JSON
    flushes serialize non-finite as null); a resume of a resume must
    not crash on float(None) — and a legitimate 0.0 must survive (a
    falsy-`or` fallback would turn it into NaN and silently drop the
    point from winner selection)."""
    return float("nan") if v is None else float(v)


def _adopted_record(seed: int, prev, logger: MetricsLogger,
                    on_seed) -> dict:
    """Record for a seed adopted from ``prior_records`` without
    retraining (shared by the serial and fleet paths)."""
    if not isinstance(prev, dict):
        prev = {"rank_ic": prev}

    _f = _float_or_nan

    rec = {
        "seed": int(seed),
        "rank_ic": _f(prev["rank_ic"]),
        "rank_ic_ir": _f(prev.get("rank_ic_ir", float("nan"))),
        "best_val": _f(prev.get("best_val", float("nan"))),
    }
    logger.log("sweep_seed_resumed", **rec)
    # Fire on_seed for resumed seeds too (ADVICE r5): callers that
    # persist partial results inside on_seed would otherwise write
    # files missing every seed adopted from prior_records — a
    # resume-of-a-resume would then retrain them. Persisting an
    # already-finished record is idempotent.
    if on_seed is not None:
        on_seed(rec)
    return rec


def _fleet_records(
    config: Config,
    dataset: PanelDataset,
    pending: Sequence[int],
    seeds_per_program: Optional[int],
    score_start: Optional[str],
    score_end: Optional[str],
    logger: MetricsLogger,
    on_seed,
    fleet_resume: bool = False,
    mesh=None,
) -> list:
    """Train `pending` seeds in seed-parallel programs and score each
    group in one seed-batched scan. Returns records in `pending` order.
    ``mesh`` composes the seed axis with a device mesh (seed lanes over
    'data', cross-section over 'stock' — parallel/partition.py)."""
    import jax
    import numpy as np

    from factorvae_tpu.eval.predict import fleet_prediction_scores
    from factorvae_tpu.train.fleet import FleetTrainer

    spp = len(pending) if not seeds_per_program else max(
        1, int(seeds_per_program))
    records = []
    for g0 in range(0, len(pending), spp):
        group = list(pending[g0:g0 + spp])
        trainer = FleetTrainer(config, dataset, group, logger=logger,
                               mesh=mesh)
        state, out = trainer.fit(resume=fleet_resume)
        best_val = np.asarray(out["best_val"])
        # Score with the per-seed BEST-VALIDATION snapshot (the serial
        # selection rule). A seed whose selection never improved (NaN
        # loss stream) falls back to its FINAL-epoch params, with the
        # same warning the serial path logs for a missing checkpoint.
        scoring = out["best_params"]
        for i, seed in enumerate(group):
            if not np.isfinite(best_val[i]):
                logger.log(
                    "sweep_warning", seed=int(seed),
                    note="best-val selection never improved; scoring "
                         "FINAL-epoch params")
                scoring = jax.tree.map(
                    lambda b, p: b.at[i].set(p[i]), scoring, state.params)
        # Scoring emits NaN BY DESIGN (padded/absent stocks), so a
        # caller-armed --debug_nans guard must not trip here — the
        # serial CLI likewise scores outside its NaN context; only the
        # training epochs above run guarded.
        from factorvae_tpu.utils.profiling import debug_nans

        with debug_nans(False):
            frames = fleet_prediction_scores(
                scoring, config, dataset, start=score_start,
                end=score_end, stochastic=False, with_labels=True,
                mesh=mesh)
        for i, seed in enumerate(group):
            ic = rank_ic_frame(frames[i].dropna(), "LABEL0", "score")
            rec = {
                "seed": int(seed),
                "rank_ic": float(ic["RankIC"].iloc[0]),
                "rank_ic_ir": float(ic["RankIC_IR"].iloc[0]),
                "best_val": float(best_val[i]),
            }
            records.append(rec)
            logger.log("sweep_seed", **rec)
            if on_seed is not None:
                on_seed(rec)
    return records


def seed_sweep(
    config: Config,
    dataset: PanelDataset,
    seeds: Sequence[int],
    score_start: Optional[str] = None,
    score_end: Optional[str] = None,
    logger: Optional[MetricsLogger] = None,
    on_seed=None,
    prior_records: Optional[dict] = None,
    fleet: bool = False,
    seeds_per_program: Optional[int] = None,
    fleet_resume: bool = False,
    mesh=None,
) -> pd.DataFrame:
    """Returns a frame indexed by seed with columns
    [rank_ic, rank_ic_ir, best_val]; .attrs['summary'] holds mean/std.

    ``on_seed(rec)`` (optional) fires after each seed completes —
    including seeds adopted from ``prior_records`` — so long-running
    sweeps can persist partial results: a multi-hour CPU sweep killed at
    round end should leave its finished seeds on disk, and a resumed
    sweep's partial file must contain the adopted seeds too.

    ``prior_records`` (optional) maps seed -> an already-finished record
    (``{"rank_ic": float, ...}``, or a bare rank_ic float as older
    partial files stored) restored from such a partial file; those
    seeds are included in the output without retraining, so a restarted
    sweep resumes instead of redoing finished work.

    ``fleet=True`` trains the non-adopted seeds in seed-parallel
    programs of ``seeds_per_program`` (None/0 = one program for all of
    them) and scores each program in one seed-batched scan; the output
    frame keeps the ``seeds`` order either way. ``fleet_resume=True``
    additionally lets each group restore from its lockstep per-seed
    full-state checkpoints (FleetTrainer.fit(resume=True)) — a killed
    fleet sweep continues mid-group instead of retraining the group,
    provided ``checkpoint_every`` was on and the save_dir survived.

    ``mesh`` (optional) composes the run with a device mesh: fleet
    groups train/score with seed lanes sharded over 'data' and the
    cross-section over 'stock'; serial trainings run the sharded serial
    program (parallel/partition.py owns the placement either way).
    """
    logger = logger or MetricsLogger(echo=False)
    prior_records = prior_records or {}
    records = []
    pending = []
    for seed in seeds:
        if int(seed) in prior_records or str(seed) in prior_records:
            prev = prior_records.get(int(seed),
                                     prior_records.get(str(seed)))
            records.append(_adopted_record(seed, prev, logger, on_seed))
            continue
        if fleet:
            pending.append(int(seed))
            continue
        cfg = dataclasses.replace(
            config, train=dataclasses.replace(config.train, seed=int(seed))
        )
        trainer = Trainer(cfg, dataset, mesh=mesh, logger=logger)
        state, out = trainer.fit()
        # Score with the per-seed BEST-VALIDATION weights (the reference
        # backtest's selection rule, backtest.ipynb cell 2; the
        # checkpoint name encodes the seed so sweeps don't collide).
        best = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
        if os.path.isdir(best):
            params = load_params(best, state.params)
        else:
            logger.log("sweep_warning", seed=int(seed),
                       note=f"best-val checkpoint missing at {best}; "
                            "scoring FINAL-epoch params")
            params = state.params
        scores = generate_prediction_scores(
            params, cfg, dataset, start=score_start, end=score_end,
            stochastic=False, with_labels=True,
        )
        ic = rank_ic_frame(scores.dropna(), "LABEL0", "score")
        rec = {
            "seed": int(seed),
            "rank_ic": float(ic["RankIC"].iloc[0]),
            "rank_ic_ir": float(ic["RankIC_IR"].iloc[0]),
            "best_val": float(out["best_val"]),
        }
        records.append(rec)
        logger.log("sweep_seed", **rec)
        if on_seed is not None:
            on_seed(rec)

    if pending:
        records.extend(_fleet_records(
            config, dataset, pending, seeds_per_program,
            score_start, score_end, logger, on_seed,
            fleet_resume=fleet_resume, mesh=mesh))
        # The frame keeps the caller's seed order regardless of how the
        # fleet grouped the training (equality with the serial sweep).
        order = {int(s): i for i, s in enumerate(seeds)}
        records.sort(key=lambda r: order[r["seed"]])

    df = pd.DataFrame(records).set_index("seed")
    df.attrs["summary"] = {
        "rank_ic_mean": float(df["rank_ic"].mean()),
        "rank_ic_std": float(df["rank_ic"].std(ddof=0)),
        "rank_ic_ir_mean": float(df["rank_ic_ir"].mean()),
        # Legacy-resumed seeds may lack rank_ic_ir (NaN, skipped by
        # mean): publish the n that statistic actually covers so it
        # can't read as a num_seeds-seed figure.
        "rank_ic_ir_num_seeds": int(df["rank_ic_ir"].notna().sum()),
        "num_seeds": len(df),
    }
    logger.log("sweep_summary", **df.attrs["summary"])
    return df


# ---------------------------------------------------------------------------
# Hyper-fleet config-grid sweep (ISSUE 12)
# ---------------------------------------------------------------------------

#: grid-point keys that change the COMPILED TRACE — points sharing
#: these values share one compiled program; points differing in them
#: bucket into separate programs (the serve daemon's (arch, dtype,
#: days) bucketing rule, applied to training). `compute_dtype` rides
#: here rather than the lane axis (ISSUE 16): the training dtype
#: changes the trace (bf16 cast + loss-scale graph, train/loop.py), so
#: an {f32, bf16} x lr grid races as two shape buckets whose lanes PBT
#: can still kill independently.
SHAPE_KEYS = ("num_factors", "hidden_size", "num_portfolios",
              "compute_dtype")
#: grid-point keys that ride the lane axis as runtime scalars (lr,
#: kl_weight — train/fleet.py hyper trace) or as the established
#: per-lane seed axis.
LANE_KEYS = ("lr", "kl_weight", "seed")


def parse_hyper_grid(spec: str) -> list:
    """'1e-4:1.0,3e-4:0.1' -> [{"lr": 1e-4, "kl_weight": 1.0}, ...] —
    the lr:kl_weight token format scripts/parity_k60_sweep.py always
    used, shared by `cli.py --hyper_grid`. An optional third field
    names the training compute dtype ('1e-4:1.0:bfloat16'), bucketing
    that point into the bf16 trace (SHAPE_KEYS) — so one --hyper_grid
    races {f32, bf16} x lr in one invocation."""
    points = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        parts = tok.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"bad hyper-grid token {tok!r}: expected lr:kl_weight "
                "or lr:kl_weight:compute_dtype")
        point = {"lr": float(parts[0]), "kl_weight": float(parts[1])}
        if len(parts) == 3:
            point["compute_dtype"] = parts[2]
        points.append(point)
    return points


def point_label(point: dict) -> str:
    """Deterministic compact label for one grid point (the frame index
    and the resume key — prior_records match on it)."""
    parts = []
    for key, tag in (("lr", "lr"), ("kl_weight", "kl"),
                     ("num_factors", "K"), ("hidden_size", "H"),
                     ("num_portfolios", "M"), ("compute_dtype", "dt"),
                     ("seed", "s")):
        if key in point:
            v = point[key]
            parts.append(f"{tag}{v:g}" if isinstance(v, float)
                         else f"{tag}{v}")
    return "_".join(parts) or "base"


def shape_bucket_key(point: dict) -> tuple:
    """The shape coordinates of a grid point (None = inherit the base
    config). Pure and total: the bucket partition is a deterministic
    function of the point list alone (pinned in tests/test_hyper.py)."""
    return tuple(point.get(k) for k in SHAPE_KEYS)


def shape_buckets(points: Sequence[dict]) -> list:
    """[(bucket_key, [(index, point), ...]), ...] — buckets ordered by
    first occurrence, points kept in caller order within a bucket."""
    order: list = []
    buckets: dict = {}
    for i, p in enumerate(points):
        k = shape_bucket_key(p)
        if k not in buckets:
            buckets[k] = []
            order.append(k)
        buckets[k].append((i, p))
    return [(k, buckets[k]) for k in order]


def _point_config(config: Config, point: dict, label: str) -> Config:
    """Full per-lane Config for one grid point: shape keys land on the
    model, lane scalars on train/model, and the run_name is tagged with
    the point label so same-seed lanes write distinct artifacts
    (train/fleet.validate_lane_configs requires it)."""
    bad = sorted(set(point) - set(SHAPE_KEYS) - set(LANE_KEYS))
    if bad:
        raise ValueError(
            f"unknown grid-point key(s) {bad}: shape keys are "
            f"{list(SHAPE_KEYS)}, lane keys are {list(LANE_KEYS)}")
    model_kw = {k: point[k] for k in SHAPE_KEYS if k in point}
    if "kl_weight" in point:
        model_kw["kl_weight"] = float(point["kl_weight"])
    train_kw: dict = {"run_name": f"{config.train.run_name}_{label}"}
    if "lr" in point:
        train_kw["lr"] = float(point["lr"])
    if "seed" in point:
        train_kw["seed"] = int(point["seed"])
    return dataclasses.replace(
        config,
        model=dataclasses.replace(config.model, **model_kw),
        train=dataclasses.replace(config.train, **train_kw),
    )


def grid_sweep(
    config: Config,
    dataset: PanelDataset,
    points: Sequence[dict],
    score_start: Optional[str] = None,
    score_end: Optional[str] = None,
    logger: Optional[MetricsLogger] = None,
    on_point=None,
    prior_records: Optional[dict] = None,
    lanes_per_program: Optional[int] = None,
    mesh=None,
) -> pd.DataFrame:
    """Race a hyperparameter-config grid through hyper-fleet programs
    (ISSUE 12): each point is a dict over SHAPE_KEYS (num_factors /
    hidden_size / num_portfolios / compute_dtype — per-trace programs;
    the training dtype buckets like a shape, ISSUE 16) and LANE_KEYS
    (lr / kl_weight / seed — per-lane runtime scalars on the stacked
    TrainState, train/fleet.py). Points bucket by shape, each bucket
    trains in hyper-fleet programs of ``lanes_per_program`` lanes
    (None/0 = the whole bucket in one program), and every lane scores
    with its best-validation snapshot through the seed-batched scan.

    Returns a frame indexed by `point_label` with the point's fields
    plus [rank_ic, rank_ic_ir, best_val]; ``.attrs["summary"]`` carries
    the winner. The `seed_sweep` resume/callback contract is preserved:
    ``on_point(rec)`` fires per finished point (adopted points
    included), and ``prior_records`` (label -> record) adopts finished
    points from a prior partial file without retraining them.

    ``mesh`` composes the lane axis with the device mesh exactly like
    the seed fleet (lanes over 'data'; compose.validate rejects an
    indivisible lane count with the documented one-line
    CompositionError at construction, not mid-fit)."""
    import jax
    import numpy as np

    from factorvae_tpu.eval.predict import fleet_prediction_scores
    from factorvae_tpu.train.fleet import FleetTrainer

    logger = logger or MetricsLogger(echo=False)
    prior_records = prior_records or {}
    labels = [point_label(p) for p in points]
    dup = {v for v in labels if labels.count(v) > 1}
    if dup:
        raise ValueError(f"duplicate grid points: {sorted(dup)}")
    records: dict = {}

    for label, point in zip(labels, points):
        if label in prior_records:
            prev = dict(prior_records[label])
            rec = {"label": label, **point,
                   "rank_ic": _float_or_nan(prev.get("rank_ic")),
                   "rank_ic_ir": _float_or_nan(prev.get("rank_ic_ir")),
                   "best_val": _float_or_nan(prev.get("best_val"))}
            records[label] = rec
            logger.log("grid_point_resumed", **rec)
            if on_point is not None:
                on_point(rec)

    pending = [(lbl, p) for lbl, p in zip(labels, points)
               if lbl not in records]
    lpp = (len(pending) if not lanes_per_program
           else max(1, int(lanes_per_program)))
    for bucket_key, members in shape_buckets([p for _, p in pending]):
        mem_labels = [pending[i][0] for i, _ in members]
        bucket_points = [p for _, p in members]
        # Bucket base config: the shape overrides applied to the base —
        # ONE FleetTrainer (one compiled program per group) per shape.
        shape_kw = {k: v for k, v in zip(SHAPE_KEYS, bucket_key)
                    if v is not None}
        bucket_cfg = dataclasses.replace(
            config, model=dataclasses.replace(config.model, **shape_kw))
        logger.log("grid_bucket", shape={k: v for k, v in
                                         zip(SHAPE_KEYS, bucket_key)
                                         if v is not None},
                   points=mem_labels,
                   lanes_per_program=lpp)
        for g0 in range(0, len(bucket_points), lpp):
            group = bucket_points[g0:g0 + lpp]
            group_labels = mem_labels[g0:g0 + lpp]
            # _point_config already applied each point's shape keys,
            # and every point in this bucket carries the bucket's exact
            # shape by construction of shape_buckets — the lane cfgs
            # match bucket_cfg's model shape without a second pass.
            lane_cfgs = [_point_config(config, p, lbl)
                         for p, lbl in zip(group, group_labels)]
            trainer = FleetTrainer(bucket_cfg, dataset,
                                   lane_configs=lane_cfgs,
                                   logger=logger, mesh=mesh)
            state, out = trainer.fit()
            best_val = np.asarray(out["best_val"])
            scoring = out["best_params"]
            for i, lbl in enumerate(group_labels):
                if not np.isfinite(best_val[i]):
                    logger.log(
                        "sweep_warning", label=lbl,
                        note="best-val selection never improved; "
                             "scoring FINAL-epoch params")
                    scoring = jax.tree.map(
                        lambda b, p: b.at[i].set(p[i]), scoring,
                        state.params)
            from factorvae_tpu.utils.profiling import debug_nans

            with debug_nans(False):
                frames = fleet_prediction_scores(
                    scoring, bucket_cfg, dataset, start=score_start,
                    end=score_end, stochastic=False, with_labels=True,
                    mesh=mesh)
            for i, (lbl, point) in enumerate(zip(group_labels, group)):
                ic = rank_ic_frame(frames[i].dropna(), "LABEL0", "score")
                rec = {
                    "label": lbl, **point,
                    "rank_ic": float(ic["RankIC"].iloc[0]),
                    "rank_ic_ir": float(ic["RankIC_IR"].iloc[0]),
                    "best_val": float(best_val[i]),
                }
                records[lbl] = rec
                logger.log("grid_point", **rec)
                if on_point is not None:
                    on_point(rec)

    # caller's point order, exactly like seed_sweep's seed order
    df = pd.DataFrame([records[lbl] for lbl in labels]).set_index("label")
    finite = df["rank_ic"].dropna()
    df.attrs["summary"] = {
        "num_points": len(df),
        "num_buckets": len(shape_buckets(list(points))),
        "best_label": (str(finite.idxmax()) if len(finite) else None),
        "best_rank_ic": (float(finite.max()) if len(finite)
                         else float("nan")),
    }
    logger.log("grid_summary", **df.attrs["summary"])
    return df
