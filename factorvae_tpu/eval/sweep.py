"""Multi-seed Rank-IC sweep harness.

Bitwise RNG parity with the torch reference is impossible (different
PRNGs), so parity is *statistical*: the same Rank-IC within tolerance
across seeds (SURVEY.md §7 hard-part 3). This harness trains S seeds of a
config, scores each deterministically, and reports per-seed Rank-IC plus
the mean ± std the parity comparison needs.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import pandas as pd

from factorvae_tpu.config import Config
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.eval.metrics import rank_ic_frame
from factorvae_tpu.eval.predict import generate_prediction_scores
from factorvae_tpu.train.checkpoint import load_params
from factorvae_tpu.train.trainer import Trainer
from factorvae_tpu.utils.logging import MetricsLogger


def seed_sweep(
    config: Config,
    dataset: PanelDataset,
    seeds: Sequence[int],
    score_start: Optional[str] = None,
    score_end: Optional[str] = None,
    logger: Optional[MetricsLogger] = None,
    on_seed=None,
    prior_records: Optional[dict] = None,
) -> pd.DataFrame:
    """Returns a frame indexed by seed with columns
    [rank_ic, rank_ic_ir, best_val]; .attrs['summary'] holds mean/std.

    ``on_seed(rec)`` (optional) fires after each seed completes —
    including seeds adopted from ``prior_records`` — so long-running
    sweeps can persist partial results: a multi-hour CPU sweep killed at
    round end should leave its finished seeds on disk, and a resumed
    sweep's partial file must contain the adopted seeds too.

    ``prior_records`` (optional) maps seed -> an already-finished record
    (``{"rank_ic": float, ...}``, or a bare rank_ic float as older
    partial files stored) restored from such a partial file; those
    seeds are included in the output without retraining, so a restarted
    sweep resumes instead of redoing finished work.
    """
    logger = logger or MetricsLogger(echo=False)
    prior_records = prior_records or {}
    records = []
    for seed in seeds:
        if int(seed) in prior_records or str(seed) in prior_records:
            prev = prior_records.get(int(seed),
                                     prior_records.get(str(seed)))
            if not isinstance(prev, dict):
                prev = {"rank_ic": prev}

            def _f(v):
                # JSON round-trips our own NaN placeholders as null
                # (strict-JSON flushes serialize non-finite as null);
                # a resume of a resume must not crash on float(None).
                return float("nan") if v is None else float(v)

            rec = {
                "seed": int(seed),
                "rank_ic": _f(prev["rank_ic"]),
                "rank_ic_ir": _f(prev.get("rank_ic_ir", float("nan"))),
                "best_val": _f(prev.get("best_val", float("nan"))),
            }
            records.append(rec)
            logger.log("sweep_seed_resumed", **rec)
            # Fire on_seed for resumed seeds too (ADVICE r5): callers
            # that persist partial results inside on_seed would
            # otherwise write files missing every seed adopted from
            # prior_records — a resume-of-a-resume would then retrain
            # them. Persisting an already-finished record is idempotent.
            if on_seed is not None:
                on_seed(rec)
            continue
        cfg = dataclasses.replace(
            config, train=dataclasses.replace(config.train, seed=int(seed))
        )
        trainer = Trainer(cfg, dataset, logger=logger)
        state, out = trainer.fit()
        # Score with the per-seed BEST-VALIDATION weights (the reference
        # backtest's selection rule, backtest.ipynb cell 2; the
        # checkpoint name encodes the seed so sweeps don't collide).
        best = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
        if os.path.isdir(best):
            params = load_params(best, state.params)
        else:
            logger.log("sweep_warning", seed=int(seed),
                       note=f"best-val checkpoint missing at {best}; "
                            "scoring FINAL-epoch params")
            params = state.params
        scores = generate_prediction_scores(
            params, cfg, dataset, start=score_start, end=score_end,
            stochastic=False, with_labels=True,
        )
        ic = rank_ic_frame(scores.dropna(), "LABEL0", "score")
        rec = {
            "seed": int(seed),
            "rank_ic": float(ic["RankIC"].iloc[0]),
            "rank_ic_ir": float(ic["RankIC_IR"].iloc[0]),
            "best_val": float(out["best_val"]),
        }
        records.append(rec)
        logger.log("sweep_seed", **rec)
        if on_seed is not None:
            on_seed(rec)

    df = pd.DataFrame(records).set_index("seed")
    df.attrs["summary"] = {
        "rank_ic_mean": float(df["rank_ic"].mean()),
        "rank_ic_std": float(df["rank_ic"].std(ddof=0)),
        "rank_ic_ir_mean": float(df["rank_ic_ir"].mean()),
        # Legacy-resumed seeds may lack rank_ic_ir (NaN, skipped by
        # mean): publish the n that statistic actually covers so it
        # can't read as a num_seeds-seed figure.
        "rank_ic_ir_num_seeds": int(df["rank_ic_ir"].notna().sum()),
        "num_seeds": len(df),
    }
    logger.log("sweep_summary", **df.attrs["summary"])
    return df
