"""Ahead-of-time model export (serving artifact).

Serializes the jitted day-batched prediction function — weights baked
in — into a portable StableHLO artifact via `jax.export`. A consumer
deserializes and calls it with `(x, mask)` without the factorvae_tpu
package, flax, or the original checkpoint: the deployment story the
reference lacks entirely (its only artifact is a torch `state_dict`
that needs the full module assembly code to use, utils.py:57-67).

Artifacts are platform-tagged: exporting under a TPU backend produces a
TPU-servable function; pass `platforms=("tpu",)` to cross-export from a
CPU host.

Since ISSUE 8 every artifact opens with a validated header — one magic
line plus a JSON record carrying the CONFIG HASH (the canonical
`utils.logging.config_hash` of the full Config; the key the serving
model registry admits artifacts under, serve/registry.py), the
exporting jax version, and the call-shape facts a server needs before
deserializing (n_max, seq_len/features, stochastic/int8, platforms).
`load_exported` validates the header and fails with a ONE-LINE
actionable error on a mismatch — a stale artifact must say "re-export
me", not die in a StableHLO deserialization traceback three layers
down. Pre-ISSUE-8 headerless blobs still load (header None).

AOT cache behavior: the traceable core (`_predict_fn`) is hoisted and
lru_cached on the frozen ModelConfig, consistent with the scoring
path's jit factories (eval/predict.py) — but the jit+trace itself runs
ONCE PER `export_prediction` CALL, unavoidably: the weights are baked
into the StableHLO as constants, so there is no hashable cache key a
param tree could provide. Callers that export repeatedly should cache
the returned bytes, not call this in a loop. Donation is deliberately
omitted (unlike the scoring scan's rebuilt-per-call index/key buffers):
the serving consumer owns the input buffers, and neither input can
alias the (D, N) f32 output anyway (x differs in shape, mask in dtype).
"""

from __future__ import annotations

import functools
import json
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from factorvae_tpu.config import Config, ModelConfig
from factorvae_tpu.utils.logging import config_hash
from factorvae_tpu.models.factorvae import day_prediction

# Artifact container format: MAGIC + b"\n" + header-JSON + b"\n" + the
# serialized jax.export payload. The magic is versioned separately from
# the header's "format" field so a future container change can be told
# apart from a future header-schema change.
ARTIFACT_MAGIC = b"FVAE-AOT1"


class ArtifactError(ValueError):
    """An AOT artifact failed header validation — the message is the
    one-line actionable contract (what mismatched, what to do)."""


@functools.lru_cache(maxsize=8)
def _predict_fn(model_cfg: ModelConfig, stochastic: bool, int8: bool):
    """Traceable scoring core with params EXPLICIT: (params, x, mask) ->
    (D, N) scores. One per (config, mode), shared across exports — the
    hoistable part of the export pipeline."""
    model = day_prediction(model_cfg, stochastic=stochastic)
    key = jax.random.PRNGKey(0)  # consumed only when stochastic

    def predict(params, x, mask):
        if int8:
            from factorvae_tpu.ops.quant import dequantize_params

            params = dequantize_params(params, model_cfg.dtype)
        return model.apply(params, x, mask, rngs={"sample": key})

    return predict


def export_prediction(
    params,
    config: Config,
    n_max: int,
    stochastic: bool = False,
    platforms: Optional[Sequence[str]] = None,
    int8: bool = False,
) -> bytes:
    """Serialized prediction function: call(x (D,N,T,C), mask (D,N)) ->
    (D,N) scores. D is a fixed batch dim of 1 per call (vmap the artifact
    or loop days at serving time).

    `int8=True` bakes the weight matrices as per-channel int8 constants
    (ops/quant.py) with the dequantize folded into the program — a ~4x
    smaller artifact with the tested rank-fidelity of the int8 scoring
    path.

    See the module docstring for the AOT cache contract: one trace per
    call is inherent (weights become export constants); cache the
    returned bytes if you export the same params repeatedly."""
    from jax import export as jexport

    cfg = config.model
    predict = _predict_fn(cfg, bool(stochastic), bool(int8))

    if int8:
        from factorvae_tpu.ops.quant import quantize_params

        params = quantize_params(params)

    # graftlint: disable=JGL003 weights are baked as export-time constants, so no hashable jit cache key exists; the per-artifact trace is the documented AOT contract above
    fn = jax.jit(functools.partial(predict, params))
    args = (
        jax.ShapeDtypeStruct((1, n_max, cfg.seq_len, cfg.num_features),
                             jnp.float32),
        jax.ShapeDtypeStruct((1, n_max), jnp.bool_),
    )
    if platforms is not None:
        exp = jexport.export(fn, platforms=tuple(platforms))(*args)
    else:
        exp = jexport.export(fn)(*args)
    header = {
        "format": "factorvae-aot/1",
        # The identity the serving registry keys on (one hash function
        # repo-wide: utils/logging.config_hash — the same digest the
        # run_meta headers and checkpoint metadata produce).
        "config_hash": config_hash(config.to_dict()),
        "jax": jax.__version__,
        "n_max": int(n_max),
        "seq_len": int(cfg.seq_len),
        "num_features": int(cfg.num_features),
        "stochastic": bool(stochastic),
        "int8": bool(int8),
        "platforms": list(platforms) if platforms is not None else None,
    }
    return (ARTIFACT_MAGIC + b"\n" + json.dumps(
        header, sort_keys=True).encode() + b"\n" + bytes(exp.serialize()))


def read_artifact_header(blob: bytes) -> Optional[dict]:
    """The artifact's header dict, or None for a pre-ISSUE-8 headerless
    blob. A blob that CLAIMS the magic but carries an unparseable
    header is corrupt — ArtifactError, not a silent legacy fallback."""
    if not blob.startswith(ARTIFACT_MAGIC + b"\n"):
        return None
    rest = blob[len(ARTIFACT_MAGIC) + 1:]
    line, sep, _ = rest.partition(b"\n")
    try:
        if not sep:
            raise ValueError("missing payload")
        header = json.loads(line.decode())
        if not isinstance(header, dict):
            raise ValueError("header is not an object")
    except ValueError as e:
        raise ArtifactError(
            f"AOT artifact header is corrupt ({e}); re-export with "
            f"eval/export_aot.export_prediction or cli.py --export"
        ) from None
    return header


class LoadedArtifact:
    """A deserialized serving artifact: `.call(x, mask) -> (D, N)
    scores` plus the validated `.header` (None on legacy blobs)."""

    def __init__(self, exported, header: Optional[dict]):
        self._exported = exported
        self.header = header

    @property
    def call(self):
        return self._exported.call

    def __getattr__(self, attr):
        return getattr(self._exported, attr)


def load_exported(blob: bytes, expect_config_hash: Optional[str] = None,
                  check_jax: bool = True) -> LoadedArtifact:
    """Deserialize an exported prediction artifact; returns an object
    with `.call(x, mask)` and `.header`.

    Header validation happens BEFORE deserialization: a config-hash
    mismatch (the caller knows which model it expects —
    `expect_config_hash`, the registry admission path) or a jax-version
    skew fails with a one-line error naming the fix, instead of the
    StableHLO deserializer's traceback. `check_jax=False` opts out of
    the version gate for consumers that accept cross-version artifacts.
    Pre-ISSUE-8 headerless blobs load with `header=None` (nothing to
    validate)."""
    from jax import export as jexport

    header = read_artifact_header(blob)
    payload = blob
    if header is not None:
        payload = blob.split(b"\n", 2)[2]
        if (expect_config_hash is not None
                and header.get("config_hash") != expect_config_hash):
            raise ArtifactError(
                f"AOT artifact is for config {header.get('config_hash')}, "
                f"expected {expect_config_hash}; re-export from the "
                f"matching checkpoint (cli.py --export)")
        import jax

        if check_jax and header.get("jax") != jax.__version__:
            raise ArtifactError(
                f"AOT artifact was exported under jax "
                f"{header.get('jax')} but this runtime is "
                f"{jax.__version__}; re-export with eval/export_aot "
                f"(or pass check_jax=False to accept the skew)")
    try:
        exported = jexport.deserialize(payload)
    except Exception as e:
        raise ArtifactError(
            f"AOT artifact failed to deserialize "
            f"({type(e).__name__}: {e}); the file is not a "
            f"factorvae_tpu export or is truncated — re-export with "
            f"cli.py --export") from None
    return LoadedArtifact(exported, header)
