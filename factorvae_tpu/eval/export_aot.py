"""Ahead-of-time model export (serving artifact).

Serializes the jitted day-batched prediction function — weights baked
in — into a portable StableHLO artifact via `jax.export`. A consumer
deserializes and calls it with `(x, mask)` without the factorvae_tpu
package, flax, or the original checkpoint: the deployment story the
reference lacks entirely (its only artifact is a torch `state_dict`
that needs the full module assembly code to use, utils.py:57-67).

Artifacts are platform-tagged: exporting under a TPU backend produces a
TPU-servable function; pass `platforms=("tpu",)` to cross-export from a
CPU host.

AOT cache behavior: the traceable core (`_predict_fn`) is hoisted and
lru_cached on the frozen ModelConfig, consistent with the scoring
path's jit factories (eval/predict.py) — but the jit+trace itself runs
ONCE PER `export_prediction` CALL, unavoidably: the weights are baked
into the StableHLO as constants, so there is no hashable cache key a
param tree could provide. Callers that export repeatedly should cache
the returned bytes, not call this in a loop. Donation is deliberately
omitted (unlike the scoring scan's rebuilt-per-call index/key buffers):
the serving consumer owns the input buffers, and neither input can
alias the (D, N) f32 output anyway (x differs in shape, mask in dtype).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from factorvae_tpu.config import Config, ModelConfig
from factorvae_tpu.models.factorvae import day_prediction


@functools.lru_cache(maxsize=8)
def _predict_fn(model_cfg: ModelConfig, stochastic: bool, int8: bool):
    """Traceable scoring core with params EXPLICIT: (params, x, mask) ->
    (D, N) scores. One per (config, mode), shared across exports — the
    hoistable part of the export pipeline."""
    model = day_prediction(model_cfg, stochastic=stochastic)
    key = jax.random.PRNGKey(0)  # consumed only when stochastic

    def predict(params, x, mask):
        if int8:
            from factorvae_tpu.ops.quant import dequantize_params

            params = dequantize_params(params, model_cfg.dtype)
        return model.apply(params, x, mask, rngs={"sample": key})

    return predict


def export_prediction(
    params,
    config: Config,
    n_max: int,
    stochastic: bool = False,
    platforms: Optional[Sequence[str]] = None,
    int8: bool = False,
) -> bytes:
    """Serialized prediction function: call(x (D,N,T,C), mask (D,N)) ->
    (D,N) scores. D is a fixed batch dim of 1 per call (vmap the artifact
    or loop days at serving time).

    `int8=True` bakes the weight matrices as per-channel int8 constants
    (ops/quant.py) with the dequantize folded into the program — a ~4x
    smaller artifact with the tested rank-fidelity of the int8 scoring
    path.

    See the module docstring for the AOT cache contract: one trace per
    call is inherent (weights become export constants); cache the
    returned bytes if you export the same params repeatedly."""
    from jax import export as jexport

    cfg = config.model
    predict = _predict_fn(cfg, bool(stochastic), bool(int8))

    if int8:
        from factorvae_tpu.ops.quant import quantize_params

        params = quantize_params(params)

    # graftlint: disable=JGL003 weights are baked as export-time constants, so no hashable jit cache key exists; the per-artifact trace is the documented AOT contract above
    fn = jax.jit(functools.partial(predict, params))
    args = (
        jax.ShapeDtypeStruct((1, n_max, cfg.seq_len, cfg.num_features),
                             jnp.float32),
        jax.ShapeDtypeStruct((1, n_max), jnp.bool_),
    )
    if platforms is not None:
        exp = jexport.export(fn, platforms=tuple(platforms))(*args)
    else:
        exp = jexport.export(fn)(*args)
    return bytes(exp.serialize())


def load_exported(blob: bytes):
    """Deserialize an exported prediction artifact; returns an object with
    `.call(x, mask)`."""
    from jax import export as jexport

    return jexport.deserialize(blob)
