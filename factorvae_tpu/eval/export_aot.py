"""Ahead-of-time model export (serving artifact).

Serializes the jitted day-batched prediction function — weights baked
in — into a portable StableHLO artifact via `jax.export`. A consumer
deserializes and calls it with `(x, mask)` without the factorvae_tpu
package, flax, or the original checkpoint: the deployment story the
reference lacks entirely (its only artifact is a torch `state_dict`
that needs the full module assembly code to use, utils.py:57-67).

Artifacts are platform-tagged: exporting under a TPU backend produces a
TPU-servable function; pass `platforms=("tpu",)` to cross-export from a
CPU host.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from factorvae_tpu.config import Config
from factorvae_tpu.models.factorvae import day_prediction


def export_prediction(
    params,
    config: Config,
    n_max: int,
    stochastic: bool = False,
    platforms: Optional[Sequence[str]] = None,
    int8: bool = False,
) -> bytes:
    """Serialized prediction function: call(x (D,N,T,C), mask (D,N)) ->
    (D,N) scores. D is a fixed batch dim of 1 per call (vmap the artifact
    or loop days at serving time).

    `int8=True` bakes the weight matrices as per-channel int8 constants
    (ops/quant.py) with the dequantize folded into the program — a ~4x
    smaller artifact with the tested rank-fidelity of the int8 scoring
    path."""
    from jax import export as jexport

    cfg = config.model
    model = day_prediction(cfg, stochastic=stochastic)
    key = jax.random.PRNGKey(0)  # used only when stochastic

    if int8:
        from factorvae_tpu.ops.quant import dequantize_params, quantize_params

        qparams = quantize_params(params)

    def predict(x, mask):
        p = dequantize_params(qparams, cfg.dtype) if int8 else params
        return model.apply(p, x, mask, rngs={"sample": key})

    fn = jax.jit(predict)
    args = (
        jax.ShapeDtypeStruct((1, n_max, cfg.seq_len, cfg.num_features),
                             jnp.float32),
        jax.ShapeDtypeStruct((1, n_max), jnp.bool_),
    )
    if platforms is not None:
        exp = jexport.export(fn, platforms=tuple(platforms))(*args)
    else:
        exp = jexport.export(fn)(*args)
    return bytes(exp.serialize())


def load_exported(blob: bytes):
    """Deserialize an exported prediction artifact; returns an object with
    `.call(x, mask)`."""
    from jax import export as jexport

    return jexport.deserialize(blob)
