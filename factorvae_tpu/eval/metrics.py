"""Rank-IC evaluation with the reference's DataFrame API.

`RankIC(df, column1, column2)` mirrors reference utils.py:113-129: per-day
Spearman rank correlation between two columns of a (datetime, instrument)
frame, returning a one-row DataFrame with mean RankIC and the information
ratio IR = mean/std (population std). The per-day correlations run on
device via ops.stats (average-rank Spearman, scipy-equivalent).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

import jax.numpy as jnp

from factorvae_tpu.ops.stats import masked_spearman, rank_ic_summary


def rank_ic_frame(
    df: pd.DataFrame, column1: str = "LABEL0", column2: str = "score"
) -> pd.DataFrame:
    """Reference-API Rank-IC: one-row DataFrame {'RankIC', 'RankIC_IR'}."""
    ic = daily_rank_ic(df, column1, column2)
    if len(ic) == 0:
        return pd.DataFrame({"RankIC": [np.nan], "RankIC_IR": [np.nan]})
    mean, ir = rank_ic_summary(jnp.asarray(ic.values), jnp.ones(len(ic), bool))
    return pd.DataFrame({"RankIC": [float(mean)], "RankIC_IR": [float(ir)]})


# Alias with the reference's exact callable name (utils.py:113).
RankIC = rank_ic_frame


def daily_rank_ic(
    df: pd.DataFrame, column1: str = "LABEL0", column2: str = "score"
) -> pd.Series:
    """Per-day Rank-IC series (index: datetime)."""
    dates = df.index.get_level_values(0)
    unique_dates = dates.unique()
    d = len(unique_dates)
    # Vectorized (D, N_max) scatter: factorize rows into (day, slot) pairs —
    # no per-day pandas loop on the scoring path (the round-1 loop was
    # O(days * stocks) host work).
    day_codes = unique_dates.get_indexer(dates)
    slots = df.groupby(level=0).cumcount().to_numpy()
    n_max = int(slots.max()) + 1 if len(df) else 0
    a = np.full((d, n_max), np.nan, np.float32)
    b = np.full((d, n_max), np.nan, np.float32)
    a[day_codes, slots] = df[column1].to_numpy()
    b[day_codes, slots] = df[column2].to_numpy()
    mask = np.isfinite(a) & np.isfinite(b)
    ic = masked_spearman(
        jnp.nan_to_num(jnp.asarray(a)), jnp.nan_to_num(jnp.asarray(b)),
        jnp.asarray(mask),
    )
    return pd.Series(np.asarray(ic), index=unique_dates, name="rank_ic")
