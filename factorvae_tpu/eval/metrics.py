"""Rank-IC evaluation with the reference's DataFrame API.

`RankIC(df, column1, column2)` mirrors reference utils.py:113-129: per-day
Spearman rank correlation between two columns of a (datetime, instrument)
frame, returning a one-row DataFrame with mean RankIC and the information
ratio IR = mean/std (population std). The per-day correlations run on
device via ops.stats (average-rank Spearman, scipy-equivalent).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

import jax.numpy as jnp

from factorvae_tpu.ops.stats import masked_spearman, rank_ic_summary


def rank_ic_frame(
    df: pd.DataFrame, column1: str = "LABEL0", column2: str = "score"
) -> pd.DataFrame:
    """Reference-API Rank-IC: one-row DataFrame {'RankIC', 'RankIC_IR'}."""
    ic = daily_rank_ic(df, column1, column2)
    if len(ic) == 0:
        return pd.DataFrame({"RankIC": [np.nan], "RankIC_IR": [np.nan]})
    mean, ir = rank_ic_summary(jnp.asarray(ic.values), jnp.ones(len(ic), bool))
    return pd.DataFrame({"RankIC": [float(mean)], "RankIC_IR": [float(ir)]})


# Alias with the reference's exact callable name (utils.py:113).
RankIC = rank_ic_frame


def labeled_holdout_days(dataset, n: int = 1,
                         min_labels: int = 3) -> list:
    """The newest `n` day indices whose cross-sections carry at least
    `min_labels` finite labels — the ONE definition of the holdout
    both the walk-forward refit A/B (wf/operator) and the promotion
    gate (serve/daemon.admit) judge Rank-IC on; a drifted copy in
    either would silently desynchronize what the two sides compare.
    Possibly empty (the callers own the error message)."""
    days = dataset.split_days(None, None)
    labels = dataset.day_labels(days)
    ok = (np.isfinite(labels)
          & dataset.valid[days]).sum(axis=1) >= int(min_labels)
    idx = np.nonzero(ok)[0]
    return [int(days[i]) for i in idx[-max(1, int(n)):]]


def panel_rank_ic(scores: np.ndarray, labels: np.ndarray,
                  valid: np.ndarray) -> float:
    """Mean per-day Rank-IC over padded (D, N_max) score/label panels,
    judged by `masked_spearman` (average-rank scipy semantics) with
    non-finite entries masked out. NaN when no day has a defined
    correlation — the walk-forward fidelity gate's judge
    (serve/daemon.admit, wf/operator)."""
    scores = np.asarray(scores, np.float32)
    labels = np.asarray(labels, np.float32)
    mask = (np.asarray(valid, bool) & np.isfinite(scores)
            & np.isfinite(labels))
    ic = np.asarray(masked_spearman(
        jnp.nan_to_num(jnp.asarray(scores)),
        jnp.nan_to_num(jnp.asarray(labels)),
        jnp.asarray(mask)))
    return float(np.nanmean(ic)) if np.isfinite(ic).any() \
        else float("nan")


def daily_rank_ic(
    df: pd.DataFrame, column1: str = "LABEL0", column2: str = "score"
) -> pd.Series:
    """Per-day Rank-IC series (index: datetime)."""
    dates = df.index.get_level_values(0)
    unique_dates = dates.unique()
    d = len(unique_dates)
    # Vectorized (D, N_max) scatter: factorize rows into (day, slot) pairs —
    # no per-day pandas loop on the scoring path (the round-1 loop was
    # O(days * stocks) host work).
    day_codes = unique_dates.get_indexer(dates)
    slots = df.groupby(level=0).cumcount().to_numpy()
    n_max = int(slots.max()) + 1 if len(df) else 0
    a = np.full((d, n_max), np.nan, np.float32)
    b = np.full((d, n_max), np.nan, np.float32)
    a[day_codes, slots] = df[column1].to_numpy()
    b[day_codes, slots] = df[column2].to_numpy()
    mask = np.isfinite(a) & np.isfinite(b)
    ic = masked_spearman(
        jnp.nan_to_num(jnp.asarray(a)), jnp.nan_to_num(jnp.asarray(b)),
        jnp.asarray(mask),
    )
    return pd.Series(np.asarray(ic), index=unique_dates, name="rank_ic")
