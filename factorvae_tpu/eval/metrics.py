"""Rank-IC evaluation with the reference's DataFrame API.

`RankIC(df, column1, column2)` mirrors reference utils.py:113-129: per-day
Spearman rank correlation between two columns of a (datetime, instrument)
frame, returning a one-row DataFrame with mean RankIC and the information
ratio IR = mean/std (population std). The per-day correlations run on
device via ops.stats (average-rank Spearman, scipy-equivalent).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

import jax.numpy as jnp

from factorvae_tpu.ops.stats import masked_spearman, rank_ic_summary


def rank_ic_frame(
    df: pd.DataFrame, column1: str = "LABEL0", column2: str = "score"
) -> pd.DataFrame:
    """Reference-API Rank-IC: one-row DataFrame {'RankIC', 'RankIC_IR'}."""
    ic = daily_rank_ic(df, column1, column2)
    if len(ic) == 0:
        return pd.DataFrame({"RankIC": [np.nan], "RankIC_IR": [np.nan]})
    mean, ir = rank_ic_summary(jnp.asarray(ic.values), jnp.ones(len(ic), bool))
    return pd.DataFrame({"RankIC": [float(mean)], "RankIC_IR": [float(ir)]})


# Alias with the reference's exact callable name (utils.py:113).
RankIC = rank_ic_frame


def daily_rank_ic(
    df: pd.DataFrame, column1: str = "LABEL0", column2: str = "score"
) -> pd.Series:
    """Per-day Rank-IC series (index: datetime)."""
    dates = df.index.get_level_values(0)
    unique_dates = dates.unique()
    n_max = int(df.groupby(level=0).size().max()) if len(df) else 0
    d = len(unique_dates)
    a = np.full((d, n_max), np.nan, np.float32)
    b = np.full((d, n_max), np.nan, np.float32)
    for i, date in enumerate(unique_dates):
        day = df.loc[date]
        k = len(day)
        a[i, :k] = day[column1].to_numpy()
        b[i, :k] = day[column2].to_numpy()
    mask = np.isfinite(a) & np.isfinite(b)
    ic = masked_spearman(
        jnp.nan_to_num(jnp.asarray(a)), jnp.nan_to_num(jnp.asarray(b)),
        jnp.asarray(mask),
    )
    return pd.Series(np.asarray(ic), index=unique_dates, name="rank_ic")
