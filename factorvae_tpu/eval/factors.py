"""Factor decomposition over a date range.

Everything the reference's forward returns per day (vae_loss,
reconstruction, factor_mu/sigma, pred_mu/sigma — module.py:270) plus the
decoder's internals (alpha, beta exposures), extracted as aligned pandas
artifacts for factor analysis: which latent factors the posterior loads
on, how the prior tracks it, and each stock's exposures — the
interpretability surface of a dynamic factor model.

Host-transfer discipline (JGL001): each chunk's outputs cross the
device->host boundary ONCE, as a single `jax.device_get` of the whole
output pytree; the frame-building loops below index host numpy arrays.
The original path called `float()` per row *and per factor* on device
arrays — one blocking device round-trip per scalar, ~K x D + 3 x D
dispatches per chunk for zero extra information. The emitted frames are
bitwise identical (pinned by tests/test_analysis.py): `float()` of a
numpy f32 scalar widens exactly like `float()` of the same device
scalar.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from factorvae_tpu.config import Config, ModelConfig
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.data.windows import gather_day
from factorvae_tpu.models.factorvae import day_forward


@functools.lru_cache(maxsize=8)
def _chunk_runner(model_cfg: ModelConfig, seq_len: int):
    """Jitted (params, values, last_valid, next_valid, day_idx (B,), key)
    -> (out, alpha_mu, alpha_sigma, beta) for one day-chunk. Cached on
    the (frozen) ModelConfig like eval/predict's scorer factories, so
    repeated `decompose` calls with one config reuse one compiled
    program; params and the panel are runtime arguments, not compile
    payload (see train/loop.py)."""
    from factorvae_tpu.models.decoder import AlphaLayer, BetaLayer
    from factorvae_tpu.models.extractor import FeatureExtractor

    model = day_forward(model_cfg, train=False)

    @jax.jit
    def run_chunk(params, values, last_valid, next_valid, day_idx, key):
        inner = params["params"]["model"]

        def one(d):
            return gather_day(values, last_valid, next_valid, d, seq_len)

        x, y, mask = jax.vmap(one)(jnp.maximum(day_idx, 0))
        mask = mask & (day_idx >= 0)[:, None]
        k1, k2 = jax.random.split(key)
        out = model.apply(
            params, x, jnp.nan_to_num(y), mask,
            rngs={"sample": k1, "dropout": k2},
        )

        # decoder internals per stock (vmapped over days)
        def internals(xd):
            latent = FeatureExtractor(model_cfg).apply(
                {"params": inner["feature_extractor"]}, xd
            )
            amu, asig = AlphaLayer(model_cfg).apply(
                {"params": inner["factor_decoder"]["alpha_layer"]}, latent
            )
            beta = BetaLayer(model_cfg).apply(
                {"params": inner["factor_decoder"]["beta_layer"]}, latent
            )
            return amu, asig, beta

        amu, asig, beta = jax.vmap(internals)(x)
        return out, amu, asig, beta

    return run_chunk


def decompose(
    params,
    config: Config,
    dataset: PanelDataset,
    start: Optional[str] = None,
    end: Optional[str] = None,
    seed: int = 0,
    chunk: int = 32,
) -> dict:
    """Returns a dict of frames over [start, end]:

    - 'factors': per-day K-factor stats, MultiIndex (datetime, factor),
      columns [post_mu, post_sigma, prior_mu, prior_sigma] — posterior
      vs prior trajectories (the KL's two sides).
    - 'exposures': per (datetime, instrument) factor exposures beta (K
      columns) plus the idiosyncratic alpha_mu/alpha_sigma.
    - 'loss': per-day [loss, recon, kl].
    """
    run_chunk = _chunk_runner(config.model, config.data.seq_len)

    days = dataset.split_days(start, end)
    k_factors = config.model.num_factors
    rows_f, rows_l, exp_frames = [], [], []
    base = jax.random.PRNGKey(seed)
    for c0 in range(0, len(days), chunk):
        sel = days[c0 : c0 + chunk]
        padded = np.full(chunk, -1, np.int32)
        padded[: len(sel)] = sel
        # ONE host sync for the whole chunk: the output pytree lands as
        # numpy; every scalar below is a host index, not a device fetch.
        out, amu, asig, beta = jax.device_get(run_chunk(
            params, dataset.values, dataset.last_valid, dataset.next_valid,
            jnp.asarray(padded), jax.random.fold_in(base, c0)
        ))
        for j, d in enumerate(sel):
            date = dataset.dates[int(d)]
            for kf in range(k_factors):
                rows_f.append((
                    date, kf,
                    float(out.factor_mu[j, kf]), float(out.factor_sigma[j, kf]),
                    float(out.pred_mu[j, kf]), float(out.pred_sigma[j, kf]),
                ))
            rows_l.append((date, float(out.loss[j]), float(out.recon_loss[j]),
                           float(out.kl[j])))
            valid = dataset.valid[int(d)]
            idx = pd.MultiIndex.from_product(
                [[date], dataset.instruments[valid[: len(dataset.instruments)]]],
                names=["datetime", "instrument"],
            )
            ef = pd.DataFrame(
                beta[j][valid],
                index=idx,
                columns=[f"beta_{kf}" for kf in range(k_factors)],
            )
            ef["alpha_mu"] = amu[j][valid]
            ef["alpha_sigma"] = asig[j][valid]
            exp_frames.append(ef)

    factors = pd.DataFrame(
        rows_f,
        columns=["datetime", "factor", "post_mu", "post_sigma", "prior_mu",
                 "prior_sigma"],
    ).set_index(["datetime", "factor"])
    loss = pd.DataFrame(
        rows_l, columns=["datetime", "loss", "recon", "kl"]
    ).set_index("datetime")
    exposures = pd.concat(exp_frames) if exp_frames else pd.DataFrame()
    return {"factors": factors, "exposures": exposures, "loss": loss}
