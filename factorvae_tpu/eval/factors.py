"""Factor decomposition over a date range.

Everything the reference's forward returns per day (vae_loss,
reconstruction, factor_mu/sigma, pred_mu/sigma — module.py:270) plus the
decoder's internals (alpha, beta exposures), extracted as aligned pandas
artifacts for factor analysis: which latent factors the posterior loads
on, how the prior tracks it, and each stock's exposures — the
interpretability surface of a dynamic factor model.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from factorvae_tpu.config import Config
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.data.windows import gather_day
from factorvae_tpu.models.factorvae import day_forward


def decompose(
    params,
    config: Config,
    dataset: PanelDataset,
    start: Optional[str] = None,
    end: Optional[str] = None,
    seed: int = 0,
    chunk: int = 32,
) -> dict:
    """Returns a dict of frames over [start, end]:

    - 'factors': per-day K-factor stats, MultiIndex (datetime, factor),
      columns [post_mu, post_sigma, prior_mu, prior_sigma] — posterior
      vs prior trajectories (the KL's two sides).
    - 'exposures': per (datetime, instrument) factor exposures beta (K
      columns) plus the idiosyncratic alpha_mu/alpha_sigma.
    - 'loss': per-day [loss, recon, kl].
    """
    cfg = config.model
    seq_len = config.data.seq_len
    model = day_forward(cfg, train=False)

    from factorvae_tpu.models.decoder import AlphaLayer, BetaLayer
    from factorvae_tpu.models.extractor import FeatureExtractor

    inner = params["params"]["model"]

    @jax.jit
    def run_chunk(day_idx, key):
        def one(d):
            return gather_day(
                dataset.values, dataset.last_valid, dataset.next_valid, d, seq_len
            )

        x, y, mask = jax.vmap(one)(jnp.maximum(day_idx, 0))
        mask = mask & (day_idx >= 0)[:, None]
        k1, k2 = jax.random.split(key)
        out = model.apply(
            params, x, jnp.nan_to_num(y), mask,
            rngs={"sample": k1, "dropout": k2},
        )
        # decoder internals per stock (vmapped over days)
        def internals(xd):
            latent = FeatureExtractor(cfg).apply(
                {"params": inner["feature_extractor"]}, xd
            )
            amu, asig = AlphaLayer(cfg).apply(
                {"params": inner["factor_decoder"]["alpha_layer"]}, latent
            )
            beta = BetaLayer(cfg).apply(
                {"params": inner["factor_decoder"]["beta_layer"]}, latent
            )
            return amu, asig, beta

        amu, asig, beta = jax.vmap(internals)(x)
        return out, amu, asig, beta

    days = dataset.split_days(start, end)
    k_factors = cfg.num_factors
    rows_f, rows_l, exp_frames = [], [], []
    base = jax.random.PRNGKey(seed)
    for c0 in range(0, len(days), chunk):
        sel = days[c0 : c0 + chunk]
        padded = np.full(chunk, -1, np.int32)
        padded[: len(sel)] = sel
        out, amu, asig, beta = run_chunk(
            jnp.asarray(padded), jax.random.fold_in(base, c0)
        )
        for j, d in enumerate(sel):
            date = dataset.dates[int(d)]
            for kf in range(k_factors):
                rows_f.append((
                    date, kf,
                    float(out.factor_mu[j, kf]), float(out.factor_sigma[j, kf]),
                    float(out.pred_mu[j, kf]), float(out.pred_sigma[j, kf]),
                ))
            rows_l.append((date, float(out.loss[j]), float(out.recon_loss[j]),
                           float(out.kl[j])))
            valid = dataset.valid[int(d)]
            idx = pd.MultiIndex.from_product(
                [[date], dataset.instruments[valid[: len(dataset.instruments)]]],
                names=["datetime", "instrument"],
            )
            ef = pd.DataFrame(
                np.asarray(beta[j])[valid],
                index=idx,
                columns=[f"beta_{kf}" for kf in range(k_factors)],
            )
            ef["alpha_mu"] = np.asarray(amu[j])[valid]
            ef["alpha_sigma"] = np.asarray(asig[j])[valid]
            exp_frames.append(ef)

    factors = pd.DataFrame(
        rows_f,
        columns=["datetime", "factor", "post_mu", "post_sigma", "prior_mu",
                 "prior_sigma"],
    ).set_index(["datetime", "factor"])
    loss = pd.DataFrame(
        rows_l, columns=["datetime", "loss", "recon", "kl"]
    ).set_index("datetime")
    exposures = pd.concat(exp_frames) if exp_frames else pd.DataFrame()
    return {"factors": factors, "exposures": exposures, "loss": loss}
