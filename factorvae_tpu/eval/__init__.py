from factorvae_tpu.eval.metrics import RankIC, daily_rank_ic, rank_ic_frame
from factorvae_tpu.eval.predict import (
    export_scores,
    generate_prediction_scores,
    predict_panel,
)

__all__ = [
    "RankIC",
    "daily_rank_ic",
    "export_scores",
    "generate_prediction_scores",
    "predict_panel",
    "rank_ic_frame",
]
