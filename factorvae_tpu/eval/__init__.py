from factorvae_tpu.eval.backtest import (
    AccountBacktestResult,
    BacktestResult,
    risk_analysis,
    simulate_topk_account,
    topk_dropout_backtest,
)
from factorvae_tpu.eval.export_aot import export_prediction, load_exported
from factorvae_tpu.eval.factors import decompose
from factorvae_tpu.eval.metrics import RankIC, daily_rank_ic, rank_ic_frame
from factorvae_tpu.eval.plots import report_graph
from factorvae_tpu.eval.predict import (
    export_scores,
    generate_prediction_scores,
    predict_panel,
)
from factorvae_tpu.eval.sweep import seed_sweep

__all__ = [
    "AccountBacktestResult",
    "BacktestResult",
    "risk_analysis",
    "simulate_topk_account",
    "RankIC",
    "daily_rank_ic",
    "decompose",
    "export_prediction",
    "export_scores",
    "load_exported",
    "generate_prediction_scores",
    "predict_panel",
    "rank_ic_frame",
    "report_graph",
    "seed_sweep",
    "topk_dropout_backtest",
]
