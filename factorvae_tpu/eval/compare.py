"""Score-file parity comparison — the BASELINE protocol tool.

BASELINE.md: "The scores/*.csv artifacts are the reproducible ground
truth: join them with regenerated labels and run RankIC to pin the exact
parity number." This module does exactly that for any two score files
(e.g. a reference `scores/free20_*.csv` and this framework's export):
join each with labels, compute per-day Rank-IC, and report the parity
delta against the ±0.002 target.

CLI:
    python -m factorvae_tpu.eval.compare REF.csv OURS.csv \
        --labels panel.pkl [--tolerance 0.002]
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from factorvae_tpu.eval.metrics import daily_rank_ic


def load_scores(path: str) -> pd.DataFrame:
    """Read a score CSV (reference schema: datetime,instrument,score)."""
    df = pd.read_csv(path, parse_dates=["datetime"])
    return df.set_index(["datetime", "instrument"]).sort_index()


def labels_from_panel(path: str) -> pd.Series:
    """LABEL0 series from a reference-schema pickle."""
    from factorvae_tpu.data.panel import load_frame

    return load_frame(path)["LABEL0"]


def compare_scores(
    ref: pd.DataFrame,
    ours: pd.DataFrame,
    labels: pd.Series,
    tolerance: float = 0.002,
) -> dict:
    """Rank-IC of both score sets against shared labels + parity verdict.

    Only (datetime, instrument) pairs present in a score file AND the
    labels contribute to that file's Rank-IC (the reference notebook's
    inner merge, backtest.ipynb cell 5).
    """
    out = {}
    for name, scores in (("reference", ref), ("ours", ours)):
        joined = scores.join(labels.rename("LABEL0"), how="inner").dropna()
        ic = daily_rank_ic(joined, "LABEL0", "score")
        out[f"{name}_rank_ic"] = float(ic.mean())
        std = float(ic.std(ddof=0))
        out[f"{name}_rank_ic_ir"] = float(ic.mean() / std) if std else np.nan
        out[f"{name}_days"] = int(len(ic))
    out["delta_rank_ic"] = out["ours_rank_ic"] - out["reference_rank_ic"]
    out["tolerance"] = tolerance
    out["within_tolerance"] = bool(abs(out["delta_rank_ic"]) <= tolerance)
    return out


def main(argv=None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("reference_csv")
    p.add_argument("ours_csv")
    p.add_argument("--labels", required=True,
                   help="reference-schema panel pickle supplying LABEL0")
    p.add_argument("--tolerance", type=float, default=0.002)
    args = p.parse_args(argv)

    result = compare_scores(
        load_scores(args.reference_csv),
        load_scores(args.ours_csv),
        labels_from_panel(args.labels),
        tolerance=args.tolerance,
    )
    print(json.dumps(result, indent=2))
    return 0 if result["within_tolerance"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
