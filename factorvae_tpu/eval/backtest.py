"""Daily top-k/drop-n backtest on prediction scores.

Capability parity with the reference's backtest notebook (backtest.ipynb
cell 6), which drives qlib's `TopkDropoutStrategy(topk=50, n_drop=10)`
through `SimulatorExecutor` with open/close costs 5bp/15bp against the
CSI300 benchmark and reads cumulative/excess return, max drawdown and
turnover off `report_graph` (BASELINE.md's headline numbers).

This is a self-contained vectorized simulator of that strategy class —
no qlib dependency — so the framework can produce the headline metrics
directly from a scores DataFrame. Semantics:

- Each day, rank stocks by score; hold an equal-weight portfolio of
  `topk` names. At most `n_drop` of the currently-held names (the
  worst-ranked ones) are swapped for the best-ranked unheld names —
  qlib's TopkDropout turnover limiter.
- Daily portfolio return = mean next-period return of holdings, minus
  transaction costs: `open_cost` per bought name + `close_cost` per sold
  name, each as a fraction of that name's (equal-weight) notional
  1/topk. The reported `turnover` is the buy-side traded fraction.
- Outputs both with-cost and without-cost curves, excess vs a benchmark
  series when given, max drawdown, and mean daily turnover.

Runnable directly on an exported score CSV (the reference's
score→notebook handoff, without the notebook):

    python -m factorvae_tpu.eval.backtest SCORES.csv \\
        [--labels panel.pkl] [--topk 50 --n_drop 10] [--plot out.png]

Two simulators are provided:

- `topk_dropout_backtest` — the fast equal-weight screener (above).
- `simulate_topk_account` — full-fidelity account simulation of the
  reference's exchange config (backtest.ipynb cell 6): cash/position
  accounting from `account=1e8`, per-order `min_cost`, `limit_threshold`
  trade rejection, and qlib's 0.95 risk-degree cash buffer; its report
  frame mirrors `report_normal_df` (return gross-of-cost + a separate
  cost-rate column) so `risk_analysis` reproduces the cell-8 annualized
  excess-return table (w/ and w/o cost).

Validation boundary (VERDICT r3 weak-#6): this simulator is validated
against hand-computed scenario tests authored in this repo
(tests/test_backtest.py), NOT differentially against qlib's own
`TopkDropoutStrategy`/`SimulatorExecutor` — qlib and its data bundle are
absent from the build sandbox (zero egress). The scenarios encode qlib's
documented order-generation semantics (comb-ranking drop rule,
suspended-holding NaN-last ranking, limit rejection via the prior-day
change, min_cost, risk degree), but a qlib differential run remains
pending data access and should be the first check run where qlib is
available; see docs/qlib_handoff.md for the handoff procedure.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import pandas as pd


@dataclasses.dataclass
class BacktestResult:
    daily_return: pd.Series          # net of cost
    daily_return_wo_cost: pd.Series
    turnover: pd.Series              # traded fraction per day (one side)
    cumulative_return: float
    cumulative_return_wo_cost: float
    excess_return: Optional[float]
    excess_return_wo_cost: Optional[float]
    max_drawdown: float
    mean_turnover: float

    def summary(self) -> dict:
        return {
            "cumulative_return": self.cumulative_return,
            "cumulative_return_wo_cost": self.cumulative_return_wo_cost,
            "excess_return": self.excess_return,
            "excess_return_wo_cost": self.excess_return_wo_cost,
            "max_drawdown": self.max_drawdown,
            "mean_turnover": self.mean_turnover,
        }


def _max_drawdown(curve: np.ndarray) -> float:
    if not len(curve):
        return 0.0
    # include the initial capital of 1.0 so a drawdown from inception counts
    peak = np.maximum.accumulate(np.concatenate([[1.0], curve]))[1:]
    return float(np.min(curve / peak - 1.0))


def topk_dropout_backtest(
    scores: pd.DataFrame,
    score_col: str = "score",
    label_col: str = "LABEL0",
    topk: int = 50,
    n_drop: int = 10,
    open_cost: float = 0.0005,      # 5 bp  (backtest.ipynb cell 6)
    close_cost: float = 0.0015,     # 15 bp
    benchmark: Optional[pd.Series] = None,
) -> BacktestResult:
    """scores: (datetime, instrument)-indexed frame with a score column and
    a realized next-period return column (the LABEL0 the exporter merges,
    as notebook cell 5 does). `benchmark`: optional per-day benchmark
    returns indexed by datetime."""
    df = scores.dropna(subset=[score_col, label_col])
    dates = df.index.get_level_values(0).unique().sort_values()

    held: set = set()
    rets, rets_wo, turns = [], [], []
    for date in dates:
        day = df.loc[date]
        ranked = day[score_col].sort_values(ascending=False)
        universe = list(ranked.index)
        if not held:
            new_held = set(universe[:topk])
        else:
            # currently-held names in today's score order (worst last);
            # `universe` is already ranked, so one filtered pass suffices
            alive_ranked = [s for s in universe if s in held]
            candidates = [s for s in universe if s not in held]
            n_swap = min(n_drop, len(candidates), len(alive_ranked))
            # refill slots lost to delisted/missing names, then swap n_drop
            keep = alive_ranked[: max(0, len(alive_ranked) - n_swap)]
            refill = topk - len(keep)
            new_held = set(keep) | set(candidates[:refill])
        buys = len(new_held - held)
        sells = len(held - new_held)
        turnover = buys / max(topk, 1)
        gross = float(day.loc[sorted(new_held), label_col].mean()) if new_held else 0.0
        cost = (buys * open_cost + sells * close_cost) / max(topk, 1)
        rets_wo.append(gross)
        rets.append(gross - cost)
        turns.append(turnover)
        held = new_held

    daily = pd.Series(rets, index=dates, name="return")
    daily_wo = pd.Series(rets_wo, index=dates, name="return_wo_cost")
    turn = pd.Series(turns, index=dates, name="turnover")
    curve = (1.0 + daily).cumprod()
    curve_wo = (1.0 + daily_wo).cumprod()
    cum = float(curve.iloc[-1] - 1.0) if len(curve) else 0.0
    cum_wo = float(curve_wo.iloc[-1] - 1.0) if len(curve_wo) else 0.0

    excess = excess_wo = None
    if benchmark is not None:
        b = benchmark.reindex(dates).fillna(0.0)
        bench_cum = float((1.0 + b).prod() - 1.0)
        excess = cum - bench_cum
        excess_wo = cum_wo - bench_cum

    return BacktestResult(
        daily_return=daily,
        daily_return_wo_cost=daily_wo,
        turnover=turn,
        cumulative_return=cum,
        cumulative_return_wo_cost=cum_wo,
        excess_return=excess,
        excess_return_wo_cost=excess_wo,
        max_drawdown=_max_drawdown(curve.to_numpy()),
        mean_turnover=float(turn.iloc[1:].mean()) if len(turn) > 1 else 0.0,
    )


# ---------------------------------------------------------------------------
# Full-fidelity account simulation (backtest.ipynb cells 6 & 8 semantics)
# ---------------------------------------------------------------------------

# qlib annualization scaler for daily CN-market frequency (238 trading
# days/year — qlib.contrib.evaluate.risk_analysis's day default).
TRADING_DAYS_PER_YEAR = 238


def risk_analysis(r: pd.Series, N: int = TRADING_DAYS_PER_YEAR) -> dict:
    """qlib `risk_analysis` parity (contrib.evaluate, mode='sum'): mean,
    std (ddof=1), annualized return = mean*N, IR = mean/std*sqrt(N), and
    max drawdown of the CUMSUM curve (qlib's default 'sum' mode — not the
    compounded curve used by `_max_drawdown` above)."""
    r = r.dropna()
    if len(r) == 0:
        return {k: float("nan") for k in (
            "mean", "std", "annualized_return", "information_ratio",
            "max_drawdown")}
    mean = float(r.mean())
    std = float(r.std(ddof=1))
    cum = r.cumsum()
    mdd = float((cum - cum.cummax()).min())
    return {
        "mean": mean,
        "std": std,
        "annualized_return": mean * N,
        "information_ratio": (mean / std * float(np.sqrt(N))) if std > 0
                             else float("nan"),
        "max_drawdown": mdd,
    }


@dataclasses.dataclass
class AccountBacktestResult:
    """Account-level simulation output mirroring qlib's portfolio metrics.

    `report` mirrors `report_normal_df` (backtest.ipynb cell 6): columns
    account / return / turnover / cost / bench / cash / value, where
    `return` is GROSS of cost and `cost` is the day's cost as a fraction
    of start-of-day account value — so cell 8's
    `risk_analysis(return - bench - cost)` applies verbatim.
    """

    report: pd.DataFrame
    risk_excess_without_cost: dict
    risk_excess_with_cost: dict
    final_positions: dict = dataclasses.field(default_factory=dict)

    def analysis_frame(self) -> pd.DataFrame:
        """The cell-8 table: a (analysis, risk) x metric frame."""
        return pd.concat({
            "excess_return_without_cost": pd.DataFrame(
                {"risk": self.risk_excess_without_cost}),
            "excess_return_with_cost": pd.DataFrame(
                {"risk": self.risk_excess_with_cost}),
        })

    def summary(self) -> dict:
        end = self.report["account"].iloc[-1] if len(self.report) else np.nan
        start = self.report["account"].iloc[0] if len(self.report) else np.nan
        return {
            "final_account": float(end),
            "annualized_excess_return_with_cost":
                self.risk_excess_with_cost["annualized_return"],
            "annualized_excess_return_without_cost":
                self.risk_excess_without_cost["annualized_return"],
            "information_ratio_with_cost":
                self.risk_excess_with_cost["information_ratio"],
            "max_drawdown_with_cost":
                self.risk_excess_with_cost["max_drawdown"],
            "mean_turnover": float(self.report["turnover"].mean())
                             if len(self.report) else np.nan,
        }


def simulate_topk_account(
    scores: pd.DataFrame,
    score_col: str = "score",
    label_col: str = "LABEL0",
    topk: int = 50,
    n_drop: int = 10,
    account: float = 1e8,
    open_cost: float = 0.0005,
    close_cost: float = 0.0015,
    min_cost: float = 5.0,
    limit_threshold: Optional[float] = 0.095,
    risk_degree: float = 0.95,
    benchmark: Optional[pd.Series] = None,
) -> AccountBacktestResult:
    """TopkDropoutStrategy + SimulatorExecutor analogue with real cash and
    position accounting (backtest.ipynb cell 6 exchange_kwargs).

    Semantics per trading day t (scores dated t; the reference label is
    `Ref($close,-2)/Ref($close,-1)-1`, i.e. the close(t+1)->close(t+2)
    return earned by a position entered at close(t+1)):

    - Strategy (qlib TopkDropoutStrategy, method_buy='top'/
      method_sell='bottom'): rank held names and the top
      `n_drop + topk - held` candidates together; sell the held names
      that fall below rank `topk` in that combined ranking (at most
      `n_drop` by construction), buy the best-ranked candidates to
      refill freed + empty slots. A held name that still outranks every
      candidate is NOT dropped.
    - Exchange: an order is REJECTED when the name moves through
      `limit_threshold` on the execution day — buys at limit-up
      (change >= +thr), sells at limit-down (change <= -thr). The
      execution-day (close(t)->close(t+1)) change of a day-t decision is
      exactly the name's label at t-1, so the limit check uses the label
      shifted one day; names missing from today's frame are suspended
      (unsellable, value carried at 0 return), while an in-frame name
      with a NaN score but finite label ranks NaN-last yet deals
      normally (the signal is missing, not the market). First-day names
      with no prior label are assumed tradable.
    - Costs: per executed order, `max(traded_value * rate, min_cost)`
      with the open/close rates of cell 6; deducted from cash.
    - Cash: sells credit proceeds minus cost; buys split
      `cash * risk_degree` equally (qlib BaseSignalStrategy.get_risk_degree
      = 0.95) across accepted buy orders.
    - Mark to market: every held position earns its day-t label; account
      value = cash + sum(position values). Positions drift from equal
      weight exactly as in qlib (no daily rebalance of held names).
    """
    df = scores.dropna(subset=[score_col])
    # Trading days = every day present in the input frame, INCLUDING days
    # where every score is NaN (all-suspended / no-signal days): qlib's
    # executor still steps those days — holdings mark to market against
    # the day's labels and no orders are generated. Deriving the calendar
    # from the post-dropna frame would silently delete such a day and
    # with it a full day of portfolio return.
    dates = scores.index.get_level_values(0).unique().sort_values()
    scored_dates = set(df.index.get_level_values(0))
    # Names present in the frame per day, scored or not: an in-frame name
    # with a NaN score but a finite label DID trade that day (the signal
    # is missing, not the market) — qlib ranks it NaN-last and the
    # exchange fills its sell. Only a name absent from the day's frame
    # entirely is suspended.
    names_by_date = {
        d: set(g.index.get_level_values(1))
        for d, g in scores.groupby(level=0)}
    if len(dates) == 0:
        empty = pd.DataFrame(
            columns=["account", "return", "turnover", "cost", "cash",
                     "value", "bench"],
            index=pd.DatetimeIndex([], name="datetime"))
        nan_risk = risk_analysis(pd.Series([], dtype=float))
        return AccountBacktestResult(
            report=empty, risk_excess_without_cost=nan_risk,
            risk_excess_with_cost=dict(nan_risk))

    # (day, name) -> label / prior-day label (execution-day change proxy).
    labels = scores[label_col]
    by_name = labels.sort_index().reset_index()
    by_name.columns = ["datetime", "instrument", "label"]
    by_name["prev"] = by_name.groupby("instrument")["label"].shift(1)
    by_name["prev_date"] = by_name.groupby("instrument")["datetime"].shift(1)
    # Only a CONSECUTIVE prior trading day is a valid execution-day change:
    # a name returning from a suspension gap must not be limit-checked
    # against a stale, weeks-old move.
    cal = {d: i for i, d in enumerate(
        labels.index.get_level_values(0).unique().sort_values())}
    prev_label = {
        (d, i): v
        for d, i, v, pd_ in zip(by_name["datetime"], by_name["instrument"],
                                by_name["prev"], by_name["prev_date"])
        if np.isfinite(v)
        and pd_ in cal and cal[d] - cal[pd_] == 1
    }

    cash = float(account)
    pos: dict = {}                  # name -> market value
    rows = []
    for date in dates:
        if date in scored_dates:
            day = df.loc[date]
            # Deterministic tie-break (r3 hardening): a stable sort on
            # the instrument-sorted frame breaks equal scores by
            # instrument name, so runs are reproducible where qlib's
            # quicksort order would be platform-defined.
            ranked = day[score_col].sort_index().sort_values(
                ascending=False, kind="mergesort")
        else:
            # All-NaN score day: CHOSEN INTERPRETATION (pending the qlib
            # differential, docs/qlib_handoff.md first-checks list): we
            # model qlib's strategy as emitting no trade decision at all
            # — no sells even from a drifted (above-topk) book, nothing
            # bought; positions only mark to market below. qlib's
            # TopkDropoutStrategy ranks with na_position='last' and
            # could conceivably still emit sells from an all-NaN
            # ranking, so this branch is the first scenario to diff
            # against real qlib when data access lands.
            ranked = pd.Series(dtype=float)
        universe = list(ranked.index)
        day_names = set(universe)
        in_frame = names_by_date.get(date, day_names)
        start_value = cash + sum(pos.values())

        def tradable(name, side):
            # Suspension (qlib Exchange volume==0): a held name absent
            # from today's frame ENTIRELY cannot transact on the
            # execution day — it can still be *selected* for sale
            # (below), as qlib's strategy ranks it, but the order is
            # rejected here. An in-frame name whose score is NaN is NOT
            # suspended: the market traded, only the signal is missing.
            if name not in in_frame and side == "sell":
                return False
            # No finite label at t means no close(t+1)->close(t+2) path:
            # the name cannot be dealt on the execution day (suspension/
            # delisting straddling it). qlib's volume==0 rejection is
            # side-independent, so BOTH buys and sells are refused; the
            # position stays marked at its carried value, exactly like a
            # suspended holding.
            if name in in_frame:
                lab = labels.get((date, name))
                if lab is None or not np.isfinite(lab):
                    return False
            if limit_threshold is None:
                return True
            chg = prev_label.get((date, name))
            if chg is None:
                return True
            return chg < limit_threshold if side == "buy" \
                else chg > -limit_threshold

        # --- strategy: target holdings (qlib comb ranking) --------------
        # qlib TopkDropoutStrategy ranks CURRENT holdings by today's
        # score with missing/suspended names ranked NaN-last (worst):
        # they occupy sell slots (and are then rejected by the exchange)
        # rather than silently passing the slot to the next-worst scored
        # name — a real divergence fixed in r3 (VERDICT r2 #5).
        held_scored = [s for s in universe if s in pos]     # today's order
        held_unscored = sorted(s for s in pos if s not in day_names)
        held_ranked = held_scored + held_unscored           # NaN ranks last
        candidates = [s for s in universe if s not in pos]
        n_held = len(pos)
        today_cand = candidates[: n_drop + max(0, topk - n_held)]
        cand_set = set(today_cand)
        # comb = holdings + candidates in score order, unscored holdings
        # at the bottom (qlib's pd.concat([last, today]).sort_values with
        # NaN last); sells are the held names falling below rank topk —
        # at most n_drop of them by construction of |today_cand|.
        comb = [s for s in universe if s in pos or s in cand_set]
        comb += held_unscored
        below_topk = set(comb[topk:])
        want_sell = [s for s in held_ranked if s in below_topk]
        # Unclamped qlib sizing (len(sell) + topk - held): a portfolio
        # drifted above topk (blocked sell + executed buy) buys fewer
        # than it sells and self-corrects back to topk.
        want_buy = today_cand[: max(0, len(want_sell) + topk - n_held)]
        if date not in scored_dates:
            # No signal today -> qlib generates no trade decision: even a
            # drifted above-topk book must not shed its (arbitrarily
            # ranked) unscored holdings.
            want_sell, want_buy = [], []

        # --- exchange: sells first (frees cash), limit/suspension aware -
        cost_today = 0.0
        traded = 0.0
        for name in want_sell:
            if not tradable(name, "sell"):
                continue
            v = pos.pop(name)
            fee = max(v * close_cost, min_cost) if v > 0 else 0.0
            cash += v - fee
            cost_today += fee
            traded += v
        buys = [n for n in want_buy if tradable(n, "buy")]
        if buys:
            per = cash * risk_degree / len(buys)
            for name in buys:
                fee = max(per * open_cost, min_cost)
                if per <= 0 or cash < per + fee:
                    continue
                cash -= per + fee
                cost_today += fee
                pos[name] = per
                traded += per

        # --- mark to market against today's labels ----------------------
        for name in list(pos):
            lab = labels.get((date, name))
            if lab is not None and np.isfinite(lab):
                pos[name] *= 1.0 + float(lab)
        end_value = cash + sum(pos.values())

        gross_ret = (end_value - start_value + cost_today) / start_value
        rows.append({
            "datetime": date,
            "account": end_value,
            "return": gross_ret,
            "turnover": traded / start_value,
            "cost": cost_today / start_value,
            "cash": cash,
            "value": sum(pos.values()),
        })

    report = pd.DataFrame(rows).set_index("datetime")
    if benchmark is not None:
        report["bench"] = benchmark.reindex(report.index).fillna(0.0)
    else:
        report["bench"] = 0.0

    excess_wo = report["return"] - report["bench"]
    excess_w = excess_wo - report["cost"]
    return AccountBacktestResult(
        report=report,
        risk_excess_without_cost=risk_analysis(excess_wo),
        risk_excess_with_cost=risk_analysis(excess_w),
        final_positions=dict(pos),
    )




def main(argv=None) -> int:
    """CLI: full backtest suite over an exported score CSV.

    Reproduces the reference's backtest notebook outputs (cells 6-8)
    from a `scores/...csv` artifact: TopkDropout screener headline
    metrics, the account-simulation summary, the annualized
    excess-return risk table, and optionally the report_graph figure.
    """
    import argparse
    import json

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("scores_csv", help="CSV with datetime,instrument,score"
                                      "[,LABEL0] (eval.export_scores output)")
    p.add_argument("--labels", default=None,
                   help="reference-schema panel pickle supplying LABEL0 "
                        "when the CSV has none")
    p.add_argument("--topk", type=int, default=50)
    p.add_argument("--n_drop", type=int, default=10)
    p.add_argument("--account", type=float, default=1e8)
    p.add_argument("--open_cost", type=float, default=0.0005)
    p.add_argument("--close_cost", type=float, default=0.0015)
    p.add_argument("--min_cost", type=float, default=5.0)
    p.add_argument("--limit_threshold", type=float, default=0.095)
    p.add_argument("--benchmark", default=None, metavar="CSV",
                   help="per-day benchmark returns (columns: datetime, "
                        "return) — the CSI300 series of notebook cell 6. "
                        "Without it the excess tables are vs zero (i.e. "
                        "absolute returns), NOT comparable to the "
                        "reference's cell-8 numbers")
    p.add_argument("--plot", default=None, metavar="PNG",
                   help="write the report_graph 4-panel figure here")
    args = p.parse_args(argv)

    df = pd.read_csv(args.scores_csv, parse_dates=["datetime"])
    df = df.set_index(["datetime", "instrument"]).sort_index()
    if "LABEL0" not in df.columns:
        if not args.labels:
            p.error("scores CSV has no LABEL0 column; pass --labels")
        from factorvae_tpu.data.panel import load_frame

        df = df.join(load_frame(args.labels)["LABEL0"], how="inner")
        if len(df) == 0:
            p.error("joining --labels matched ZERO rows — do the "
                    "instrument/date conventions of the CSV and the "
                    "panel agree?")
    # Do NOT pre-drop NaN rows here: the account simulator derives the
    # trading calendar from the full frame (an all-NaN-score day is a
    # no-trade day that still marks to market) and models in-frame
    # NaN-label names as undealable. Refuse only frames where score and
    # label never co-occur on a row (e.g. a misaligned --labels join) —
    # marginal non-NaN counts alone would let that run silently.
    if not (df["score"].notna() & df["LABEL0"].notna()).any():
        p.error("no scored rows with labels to backtest")

    benchmark = None
    if args.benchmark:
        b = pd.read_csv(args.benchmark, parse_dates=["datetime"])
        benchmark = b.set_index("datetime")["return"].sort_index()

    # the screener needs labeled rows; the account simulator keeps
    # NaN-label rows (rankable, but undealable on the execution day —
    # both order sides rejected — and mark-to-market skipped)
    screener = topk_dropout_backtest(
        df.dropna(subset=["score", "LABEL0"]),
        topk=args.topk, n_drop=args.n_drop,
        open_cost=args.open_cost, close_cost=args.close_cost,
        benchmark=benchmark)
    acct = simulate_topk_account(
        df, topk=args.topk, n_drop=args.n_drop, account=args.account,
        open_cost=args.open_cost, close_cost=args.close_cost,
        min_cost=args.min_cost, limit_threshold=args.limit_threshold,
        benchmark=benchmark)
    out = {
        "screener": {k: v for k, v in screener.summary().items()
                     if v is not None},
        "account": acct.summary(),
        "excess_return_without_cost": acct.risk_excess_without_cost,
        "excess_return_with_cost": acct.risk_excess_with_cost,
        "benchmark": args.benchmark or "none (excess == absolute return)",
    }
    if args.plot:
        from factorvae_tpu.eval.plots import report_graph

        out["plot"] = report_graph(acct.report, args.plot)

    def _clean(o):
        """Strict JSON: numpy scalars -> python, NaN/inf -> null."""
        if isinstance(o, dict):
            return {k: _clean(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [_clean(v) for v in o]
        if isinstance(o, (np.floating, np.integer)):
            o = float(o)
        if isinstance(o, float) and not np.isfinite(o):
            return None
        return o

    print(json.dumps(_clean(out), indent=2))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
