"""Daily top-k/drop-n backtest on prediction scores.

Capability parity with the reference's backtest notebook (backtest.ipynb
cell 6), which drives qlib's `TopkDropoutStrategy(topk=50, n_drop=10)`
through `SimulatorExecutor` with open/close costs 5bp/15bp against the
CSI300 benchmark and reads cumulative/excess return, max drawdown and
turnover off `report_graph` (BASELINE.md's headline numbers).

This is a self-contained vectorized simulator of that strategy class —
no qlib dependency — so the framework can produce the headline metrics
directly from a scores DataFrame. Semantics:

- Each day, rank stocks by score; hold an equal-weight portfolio of
  `topk` names. At most `n_drop` of the currently-held names (the
  worst-ranked ones) are swapped for the best-ranked unheld names —
  qlib's TopkDropout turnover limiter.
- Daily portfolio return = mean next-period return of holdings, minus
  transaction costs: `open_cost` per bought name + `close_cost` per sold
  name, each as a fraction of that name's (equal-weight) notional
  1/topk. The reported `turnover` is the buy-side traded fraction.
- Outputs both with-cost and without-cost curves, excess vs a benchmark
  series when given, max drawdown, and mean daily turnover.

The reference's full-fidelity path (limit thresholds, cash accounting,
exchange calendars) remains qlib's job, exactly as in the reference; use
qlib on the exported score CSVs for that.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import pandas as pd


@dataclasses.dataclass
class BacktestResult:
    daily_return: pd.Series          # net of cost
    daily_return_wo_cost: pd.Series
    turnover: pd.Series              # traded fraction per day (one side)
    cumulative_return: float
    cumulative_return_wo_cost: float
    excess_return: Optional[float]
    excess_return_wo_cost: Optional[float]
    max_drawdown: float
    mean_turnover: float

    def summary(self) -> dict:
        return {
            "cumulative_return": self.cumulative_return,
            "cumulative_return_wo_cost": self.cumulative_return_wo_cost,
            "excess_return": self.excess_return,
            "excess_return_wo_cost": self.excess_return_wo_cost,
            "max_drawdown": self.max_drawdown,
            "mean_turnover": self.mean_turnover,
        }


def _max_drawdown(curve: np.ndarray) -> float:
    if not len(curve):
        return 0.0
    # include the initial capital of 1.0 so a drawdown from inception counts
    peak = np.maximum.accumulate(np.concatenate([[1.0], curve]))[1:]
    return float(np.min(curve / peak - 1.0))


def topk_dropout_backtest(
    scores: pd.DataFrame,
    score_col: str = "score",
    label_col: str = "LABEL0",
    topk: int = 50,
    n_drop: int = 10,
    open_cost: float = 0.0005,      # 5 bp  (backtest.ipynb cell 6)
    close_cost: float = 0.0015,     # 15 bp
    benchmark: Optional[pd.Series] = None,
) -> BacktestResult:
    """scores: (datetime, instrument)-indexed frame with a score column and
    a realized next-period return column (the LABEL0 the exporter merges,
    as notebook cell 5 does). `benchmark`: optional per-day benchmark
    returns indexed by datetime."""
    df = scores.dropna(subset=[score_col, label_col])
    dates = df.index.get_level_values(0).unique().sort_values()

    held: set = set()
    rets, rets_wo, turns = [], [], []
    for date in dates:
        day = df.loc[date]
        ranked = day[score_col].sort_values(ascending=False)
        universe = list(ranked.index)
        if not held:
            new_held = set(universe[:topk])
        else:
            # currently-held names in today's score order (worst last);
            # `universe` is already ranked, so one filtered pass suffices
            alive_ranked = [s for s in universe if s in held]
            candidates = [s for s in universe if s not in held]
            n_swap = min(n_drop, len(candidates), len(alive_ranked))
            # refill slots lost to delisted/missing names, then swap n_drop
            keep = alive_ranked[: max(0, len(alive_ranked) - n_swap)]
            refill = topk - len(keep)
            new_held = set(keep) | set(candidates[:refill])
        buys = len(new_held - held)
        sells = len(held - new_held)
        turnover = buys / max(topk, 1)
        gross = float(day.loc[sorted(new_held), label_col].mean()) if new_held else 0.0
        cost = (buys * open_cost + sells * close_cost) / max(topk, 1)
        rets_wo.append(gross)
        rets.append(gross - cost)
        turns.append(turnover)
        held = new_held

    daily = pd.Series(rets, index=dates, name="return")
    daily_wo = pd.Series(rets_wo, index=dates, name="return_wo_cost")
    turn = pd.Series(turns, index=dates, name="turnover")
    curve = (1.0 + daily).cumprod()
    curve_wo = (1.0 + daily_wo).cumprod()
    cum = float(curve.iloc[-1] - 1.0) if len(curve) else 0.0
    cum_wo = float(curve_wo.iloc[-1] - 1.0) if len(curve_wo) else 0.0

    excess = excess_wo = None
    if benchmark is not None:
        b = benchmark.reindex(dates).fillna(0.0)
        bench_cum = float((1.0 + b).prod() - 1.0)
        excess = cum - bench_cum
        excess_wo = cum_wo - bench_cum

    return BacktestResult(
        daily_return=daily,
        daily_return_wo_cost=daily_wo,
        turnover=turn,
        cumulative_return=cum,
        cumulative_return_wo_cost=cum_wo,
        excess_return=excess,
        excess_return_wo_cost=excess_wo,
        max_drawdown=_max_drawdown(curve.to_numpy()),
        mean_turnover=float(turn.iloc[1:].mean()) if len(turn) > 1 else 0.0,
    )


