"""Inference scoring: per-day predictions over a date range.

Capability parity with reference utils.py:70-93 / backtest.ipynb cell 1
(`generate_prediction_scores`): run `prediction()` day by day and emit a
(datetime, instrument)-indexed `score` DataFrame aligned via the sampler's
index. Here the per-day loop is a chunked, jitted day-batched apply over
the HBM-resident panel; scores come back as one (D, N_max) array and are
flattened against the validity mask.

The reference's predictions are stochastic at inference (module.py:123
draws a reparameterized sample; SURVEY.md §3.3) — reproduced when
`stochastic=True`; `stochastic=False` (default from the config) scores
with the distribution mean, which is deterministic and what you want for
a reproducible backtest.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from factorvae_tpu.config import Config, ModelConfig
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.models.factorvae import day_prediction


@functools.lru_cache(maxsize=32)
def _score_chunk_fn(
    model_cfg: ModelConfig,
    seq_len: int,
    stochastic: Optional[bool],
    int8: bool,
):
    """Jitted chunk scorer, cached so repeated predict_panel calls (seed
    sweeps, benchmarks, chunked exports) reuse the compiled program
    instead of re-tracing a fresh closure every call. ModelConfig is a
    frozen dataclass, so it is its own cache key."""
    model = day_prediction(model_cfg, stochastic=stochastic)
    compute_dtype = model_cfg.dtype

    from factorvae_tpu.data.windows import gather_day

    # The panel arrays are explicit jit arguments (not closed over) so
    # they never enter the compile payload — see train/loop.py. `params`
    # is also an argument: as a QTensor tree it crosses the jit boundary
    # as (int8, scale) pairs and inflates in VMEM at the consumer matmul.
    @jax.jit
    def score_chunk(p, values, last_valid, next_valid, day_idx, key):
        if int8:
            from factorvae_tpu.ops.quant import dequantize_params

            p = dequantize_params(p, compute_dtype)

        def one(d):
            return gather_day(values, last_valid, next_valid, d, seq_len)

        x, _, mask = jax.vmap(one)(jnp.maximum(day_idx, 0))
        mask = mask & (day_idx >= 0)[:, None]
        return model.apply(p, x, mask, rngs={"sample": key})

    return score_chunk


def predict_panel(
    params,
    config: Config,
    dataset: PanelDataset,
    days: np.ndarray,
    stochastic: Optional[bool] = None,
    seed: int = 0,
    chunk: int = 32,
    int8: bool = False,
) -> np.ndarray:
    """(len(days), N_max) float scores; padded/absent entries are NaN.

    `int8=True` stores the weight matrices in HBM as per-channel int8
    (ops/quant.py) and dequantizes them inside the compiled program —
    4x smaller parameter residency for a read-only workload; score
    fidelity vs the float path is rank-correlation ~1 (tested)."""
    if int8:
        from factorvae_tpu.ops.quant import quantize_params

        params = quantize_params(params)

    score_chunk = _score_chunk_fn(
        config.model, config.data.seq_len, stochastic, int8)

    out = np.full((len(days), dataset.n_max), np.nan, np.float32)
    base = jax.random.PRNGKey(seed)
    for c0 in range(0, len(days), chunk):
        sel = days[c0 : c0 + chunk]
        padded = np.full(chunk, -1, np.int32)
        padded[: len(sel)] = sel
        scores = score_chunk(
            params, dataset.values, dataset.last_valid, dataset.next_valid,
            jnp.asarray(padded), jax.random.fold_in(base, c0))
        out[c0 : c0 + len(sel)] = np.asarray(scores)[: len(sel)]
    return out


def generate_prediction_scores(
    params,
    config: Config,
    dataset: PanelDataset,
    start: Optional[str] = None,
    end: Optional[str] = None,
    stochastic: Optional[bool] = None,
    seed: int = 0,
    with_labels: bool = False,
    int8: bool = False,
) -> pd.DataFrame:
    """Scores DataFrame with MultiIndex (datetime, instrument) and a
    'score' column (plus 'LABEL0' when with_labels=True, matching the
    merge the backtest notebook performs in cell 5)."""
    days = dataset.split_days(start, end)
    scores = predict_panel(params, config, dataset, days, stochastic, seed,
                           int8=int8)
    idx = dataset.index_frame(days)
    valid = dataset.valid[days]                      # (D, N_max)
    flat_scores = scores[valid]
    df = pd.DataFrame({"score": flat_scores}, index=idx)
    if with_labels:
        labels = np.asarray(dataset.values[:, :, -1]).T[days]  # (D, N_max)
        df["LABEL0"] = labels[valid]
    return df


def export_scores(df: pd.DataFrame, config: Config, out_dir: str = "./scores") -> str:
    """CSV export under the reference's score naming scheme
    (scores/readme.md:2-8; see Config.score_name)."""
    import os

    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, config.score_name() + ".csv")
    df.reset_index().to_csv(path, index=False)
    return path
