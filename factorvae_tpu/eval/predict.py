"""Inference scoring: per-day predictions over a date range.

Capability parity with reference utils.py:70-93 / backtest.ipynb cell 1
(`generate_prediction_scores`): run `prediction()` day by day and emit a
(datetime, instrument)-indexed `score` DataFrame aligned via the sampler's
index. Here the whole scoring pass is ONE jitted program: a `lax.scan`
over day-chunks gathers each chunk's windows from the HBM-resident panel,
applies the day-batched prediction model, and stacks the (D, N_max)
scores on device — a single dispatch and a single device->host sync per
call, instead of the per-chunk Python dispatch + re-pad + `np.asarray`
sync the round-1..5 chunk loop paid (which lost to the reference torch
loop at the k60 preset shapes on CPU; PERF.md round 5).

Deterministic inference (`stochastic=False`, the reproducible-backtest
mode) takes a fast path that threads no RNG at all — the prediction
graph draws neither sample nor dropout noise, so the scan carries only
the day indices.

`predict_panel_fleet` is the seed-batched variant (train/fleet.py): S
stacked param trees ride one day-chunk scan — the panel, day indices
and keys broadcast — so a seed sweep's whole scoring pass is a single
dispatch producing S score frames.

The reference's predictions are stochastic at inference (module.py:123
draws a reparameterized sample; SURVEY.md §3.3) — reproduced when
`stochastic=True` with the exact same per-chunk RNG stream as the chunk
loop (`fold_in(base, chunk_start)`), so both implementations produce
bitwise-identical scores (tested); the loop survives as
`impl="chunk_loop"` for A/B timing.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from factorvae_tpu.config import Config, ModelConfig
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.models.factorvae import day_prediction
# Scoring jits go through the compile watchdog like the trainer jits
# (obs/watchdog.py): pure passthrough without an installed timeline;
# with one, every cache miss lands a `compile` record in RUN.jsonl so a
# scoring pass's program bill is part of the same trajectory.
from factorvae_tpu.obs.watchdog import watch_jit


def _deterministic(model_cfg: ModelConfig, stochastic: Optional[bool]) -> bool:
    return not (model_cfg.stochastic_inference if stochastic is None
                else stochastic)


def _make_chunk_scorer(model_cfg: ModelConfig, seq_len: int,
                       stochastic: Optional[bool]):
    """(params, panel..., days (B,), key) -> (B, N_max) scores. Shared by
    the scan path (as the scan body) and the chunk-loop path (jitted
    directly). The deterministic fast path passes no rngs at all."""
    model = day_prediction(model_cfg, stochastic=stochastic)
    det = _deterministic(model_cfg, stochastic)

    from factorvae_tpu.data.windows import gather_day

    def chunk_scores(p, values, last_valid, next_valid, days, key):
        def one(d):
            return gather_day(values, last_valid, next_valid, d, seq_len)

        x, _, mask = jax.vmap(one)(jnp.maximum(days, 0))
        mask = mask & (days >= 0)[:, None]
        if det:
            return model.apply(p, x, mask)
        return model.apply(p, x, mask, rngs={"sample": key})

    return chunk_scores


@functools.lru_cache(maxsize=32)
def _score_chunk_fn(
    model_cfg: ModelConfig,
    seq_len: int,
    stochastic: Optional[bool],
    int8: bool,
):
    """Jitted single-chunk scorer (the `impl="chunk_loop"` path), cached
    so repeated calls reuse the compiled program. ModelConfig is a frozen
    dataclass, so it is its own cache key."""
    chunk_scores = _make_chunk_scorer(model_cfg, seq_len, stochastic)
    compute_dtype = model_cfg.dtype

    # The panel arrays are explicit jit arguments (not closed over) so
    # they never enter the compile payload — see train/loop.py. `params`
    # is also an argument: as a QTensor tree it crosses the jit boundary
    # as (int8, scale) pairs and inflates in VMEM at the consumer matmul.
    @jax.jit
    def score_chunk(p, values, last_valid, next_valid, day_idx, key):
        if int8:
            from factorvae_tpu.ops.quant import dequantize_params

            p = dequantize_params(p, compute_dtype)
        return chunk_scores(p, values, last_valid, next_valid, day_idx, key)

    return watch_jit(score_chunk, "score_chunk")


@functools.lru_cache(maxsize=32)
def _score_chunk_fleet_fn(
    model_cfg: ModelConfig,
    seq_len: int,
    stochastic: Optional[bool],
    int8: bool = False,
):
    """Seed-batched single-chunk scorer for STREAM-resident datasets:
    S stacked param trees x one prefetched mini-panel chunk, panel and
    key broadcast — the per-chunk twin of `_score_scan_fleet_fn`.
    `int8=True` takes stacked QTensor trees (a seed axis on q and s
    alike) and dequantizes inside the compiled program, like the serial
    scorer — the multi-model serving dispatch (serve/daemon.py) buckets
    int8 registry entries through this path."""
    chunk_scores = _make_chunk_scorer(model_cfg, seq_len, stochastic)
    compute_dtype = model_cfg.dtype

    @jax.jit
    def score_chunk_fleet(stacked_p, values, last_valid, next_valid,
                          day_idx, key):
        if int8:
            from factorvae_tpu.ops.quant import dequantize_params

            stacked_p = dequantize_params(stacked_p, compute_dtype)

        def one_seed(p):
            return chunk_scores(p, values, last_valid, next_valid,
                                day_idx, key)

        return jax.vmap(one_seed)(stacked_p)

    return watch_jit(score_chunk_fleet, "score_chunk_fleet")


def _stream_chunks(dataset, days: np.ndarray, chunk: int, placement=None):
    """ChunkStream of (local day_idx (chunk,), mini-panel) for a scoring
    pass over a stream-resident dataset — the same chunk partitioning
    and -1 padding as `_scan_inputs`/the chunk loop, remapped onto
    relocatable mini-panels (data/windows.chunk_mini_panel) so the
    jitted scorer runs the identical in-graph gather."""
    from factorvae_tpu.data.stream import ChunkStream
    from factorvae_tpu.data.windows import chunk_mini_panel

    starts = list(range(0, len(days), chunk))

    def make_chunk(i):
        c0 = starts[i]
        sel = days[c0:c0 + chunk]
        padded = np.full(chunk, -1, np.int32)
        padded[:len(sel)] = sel
        local_days, cvalues, clv, cnv = chunk_mini_panel(
            dataset.values_np, dataset.last_valid_np, dataset.next_valid_np,
            padded, dataset.seq_len)
        return local_days, (cvalues, clv, cnv)

    return starts, ChunkStream(make_chunk, len(starts), placement=placement)


def _predict_stream(params, config, dataset, days, stochastic, seed,
                    chunk, int8=False, stacked=False, mesh=None):
    """Scoring pass over a STREAM-resident dataset: per-chunk mini-panels
    double-buffered to the device, scored by the chunk scorer with the
    chunk loop's exact per-chunk RNG stream (`fold_in(base, c0)`), so
    scores are bitwise the HBM paths' (pinned in tests/test_stream.py).
    `stacked=True` scores S stacked param trees per chunk (fleet).
    ``mesh`` places each mini-panel per the panel partition rules
    (cross-section over 'stock', day indices replicated) so the sharded
    scorer consumes pre-sharded slabs — mesh x stream scoring stays
    bitwise mesh x hbm scoring."""
    n_days = len(days)
    lead = ()
    if stacked:
        lead = (int(jax.tree.leaves(params)[0].shape[0]),)
        score_chunk = _score_chunk_fleet_fn(
            config.model, config.data.seq_len, stochastic, int8)
    else:
        score_chunk = _score_chunk_fn(
            config.model, config.data.seq_len, stochastic, int8)
    placement = None
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from factorvae_tpu.parallel.sharding import chunk_placement

        # Scoring day chunks are 1-D (chunk,) and replicated; only the
        # mini-panel shards (the stacked fleet params already carry
        # their seed-axis sharding from training).
        placement = chunk_placement(mesh, order_spec=P())
        from factorvae_tpu.parallel import partition
        from factorvae_tpu.parallel.multihost import global_put

        # Params must live on the mesh's device set too (host-loaded
        # params scoring a sharded chunk would mix device sets): serial
        # trees replicate, stacked trees keep their seed-axis rule.
        # Re-placing an already-correctly-placed tree is a no-op.
        specs = partition.params_partition_specs(params, stacked=stacked)
        params = jax.tree.map(
            lambda x, s: global_put(x, jax.sharding.NamedSharding(mesh, s)),
            params, specs)
    base = jax.random.PRNGKey(seed)
    out = np.full(lead + (n_days, dataset.n_max), np.nan, np.float32)
    starts, chunks = _stream_chunks(dataset, days, chunk,
                                    placement=placement)
    for c0, (day_idx, (cvalues, clv, cnv)) in zip(starts, chunks):
        n_sel = min(chunk, n_days - c0)
        scores = score_chunk(params, cvalues, clv, cnv, day_idx,
                             jax.random.fold_in(base, c0))
        out[..., c0:c0 + n_sel, :] = np.asarray(scores)[..., :n_sel, :]
    return out


@functools.lru_cache(maxsize=32)
def _score_scan_fleet_fn(
    model_cfg: ModelConfig,
    seq_len: int,
    stochastic: Optional[bool],
    int8: bool = False,
):
    """Seed-batched whole-pass scorer (train/fleet.py counterpart): S
    stacked param trees x ONE day-chunk scan -> (S, n_chunks, chunk,
    N_max) scores in a single dispatch. The panel, day indices and key
    buffer are broadcast (in_axes=None) — every seed scores the same
    days with the same RNG stream, exactly what `seed_sweep` does
    serially — so HBM holds one panel copy while every matmul in the
    scan body gains an S-fold leading batch axis. `int8=True` takes
    stacked QTensor trees and dequantizes in-program (the serving
    dispatch's int8 bucket; serial scorers already do the same)."""
    chunk_scores = _make_chunk_scorer(model_cfg, seq_len, stochastic)
    compute_dtype = model_cfg.dtype

    @jax.jit
    def score_scan_fleet(stacked_p, values, last_valid, next_valid,
                         day_idx, keys):
        if int8:
            from factorvae_tpu.ops.quant import dequantize_params

            stacked_p = dequantize_params(stacked_p, compute_dtype)

        def one_seed(p):
            def body(carry, inp):
                days, key = inp
                return carry, chunk_scores(
                    p, values, last_valid, next_valid, days, key)

            _, scores = jax.lax.scan(body, 0, (day_idx, keys))
            return scores

        return jax.vmap(one_seed)(stacked_p)

    return watch_jit(score_scan_fleet, "score_scan_fleet")


@functools.lru_cache(maxsize=32)
def _score_scan_fn(
    model_cfg: ModelConfig,
    seq_len: int,
    stochastic: Optional[bool],
    int8: bool,
):
    """Whole-pass jitted scorer: lax.scan over (S, chunk) day indices ->
    (S, chunk, N_max) scores, one dispatch for the entire date range.

    The day-index and per-chunk key buffers are donated — they are
    rebuilt per call and XLA may reuse them in place (donation is a
    no-op on backends without aliasing support, e.g. CPU)."""
    chunk_scores = _make_chunk_scorer(model_cfg, seq_len, stochastic)
    compute_dtype = model_cfg.dtype
    donate = (4, 5) if jax.default_backend() != "cpu" else ()

    @functools.partial(jax.jit, donate_argnums=donate)
    def score_scan(p, values, last_valid, next_valid, day_idx, keys):
        if int8:
            from factorvae_tpu.ops.quant import dequantize_params

            p = dequantize_params(p, compute_dtype)

        def body(carry, inp):
            days, key = inp
            return carry, chunk_scores(
                p, values, last_valid, next_valid, days, key)

        _, scores = jax.lax.scan(body, 0, (day_idx, keys))
        return scores

    return watch_jit(score_scan, "score_scan")


def _scan_inputs(days: np.ndarray, chunk: int, base: jax.Array,
                 deterministic: bool):
    """(day_idx (n_chunks, chunk), keys) for the whole-pass scan — ONE
    definition of the chunk padding (-1 = pad) and the per-chunk RNG
    stream, shared by the serial and fleet scan paths: their equality
    contract (S=1 bitwise, S>1 f32-close, tests/test_fleet.py) depends
    on these staying identical."""
    n_days = len(days)
    n_chunks = -(-n_days // chunk)
    padded = np.full(n_chunks * chunk, -1, np.int32)
    padded[:n_days] = days
    day_idx = jnp.asarray(padded.reshape(n_chunks, chunk))
    if deterministic:
        # The fast path's scan body never reads the keys — don't pay
        # one fold_in dispatch per chunk building a buffer of them.
        keys = jnp.zeros((n_chunks, *base.shape), base.dtype)
    else:
        # One vmapped dispatch for the whole key buffer, bitwise-equal
        # to per-chunk fold_in(base, c0) (pinned by tests/test_eval.py).
        keys = jax.vmap(lambda c0: jax.random.fold_in(base, c0))(
            jnp.arange(0, n_chunks * chunk, chunk, dtype=jnp.int32))
    return day_idx, keys


def predict_panel(
    params,
    config: Config,
    dataset: PanelDataset,
    days: np.ndarray,
    stochastic: Optional[bool] = None,
    seed: int = 0,
    chunk: int = 32,
    int8: bool = False,
    impl: str = "scan",
    mesh=None,
) -> np.ndarray:
    """(len(days), N_max) float scores; padded/absent entries are NaN.

    ``mesh`` only matters for STREAM-resident datasets: each prefetched
    mini-panel chunk is placed per the panel partition rules
    (parallel/partition.py) so the sharded scorer runs on pre-sharded
    slabs. HBM datasets were already re-placed by shard_dataset and
    need nothing here.

    `impl="scan"` (default) runs the whole pass as one jitted scan over
    day-chunks; `impl="chunk_loop"` is the pre-overhaul per-chunk
    dispatch loop, kept for A/B timing and pinned exactly equal by
    tests/test_eval.py (same RNG stream: chunk c0 uses
    `fold_in(PRNGKey(seed), c0)` on both paths).

    `int8=True` stores the weight matrices in HBM as per-channel int8
    (ops/quant.py) and dequantizes them inside the compiled program —
    4x smaller parameter residency for a read-only workload; score
    fidelity vs the float path is rank-correlation ~1 (tested)."""
    if impl not in ("scan", "chunk_loop"):
        raise ValueError(f"impl must be 'scan' or 'chunk_loop'; got {impl!r}")
    if int8:
        # Idempotent: a warm serving registry entry arrives pre-quantized
        # (one quantization at admission, not one per request).
        from factorvae_tpu.ops.quant import ensure_quantized

        params = ensure_quantized(params)

    n_days = len(days)
    if getattr(dataset, "residency", "hbm") == "stream":
        # Stream-resident panel: prefetched mini-panel chunks through
        # the chunk scorer (same structure either impl would run; the
        # RNG stream and chunk partitioning match both, which are
        # mutually bitwise anyway).
        if n_days == 0:
            return np.full((0, dataset.n_max), np.nan, np.float32)
        return _predict_stream(params, config, dataset, days, stochastic,
                               seed, chunk, int8=int8, mesh=mesh)
    base = jax.random.PRNGKey(seed)

    if impl == "chunk_loop":
        score_chunk = _score_chunk_fn(
            config.model, config.data.seq_len, stochastic, int8)
        out = np.full((n_days, dataset.n_max), np.nan, np.float32)
        for c0 in range(0, n_days, chunk):
            sel = days[c0 : c0 + chunk]
            padded = np.full(chunk, -1, np.int32)
            padded[: len(sel)] = sel
            scores = score_chunk(
                params, dataset.values, dataset.last_valid,
                dataset.next_valid, jnp.asarray(padded),
                jax.random.fold_in(base, c0))
            out[c0 : c0 + len(sel)] = np.asarray(scores)[: len(sel)]
        return out

    if n_days == 0:
        return np.full((0, dataset.n_max), np.nan, np.float32)
    day_idx, keys = _scan_inputs(
        days, chunk, base, _deterministic(config.model, stochastic))
    score_scan = _score_scan_fn(
        config.model, config.data.seq_len, stochastic, int8)
    scores = score_scan(params, dataset.values, dataset.last_valid,
                        dataset.next_valid, day_idx, keys)
    out = np.asarray(scores, dtype=np.float32).reshape(
        -1, dataset.n_max)
    return out[:n_days]


def predict_panel_fleet(
    stacked_params,
    config: Config,
    dataset: PanelDataset,
    days: np.ndarray,
    stochastic: Optional[bool] = None,
    seed: int = 0,
    chunk: int = 32,
    num_seeds: Optional[int] = None,
    mesh=None,
    int8: bool = False,
) -> np.ndarray:
    """(S, len(days), N_max) scores for S stacked param trees (leading
    seed axis on every leaf, as train/fleet.py produces) in ONE
    dispatch. Per-seed rows equal `predict_panel` on the unstacked tree:
    bitwise at S=1 (which routes through the serial scan — vmap's
    batched-dot reassociation would break the oracle), f32-close at S>1
    (pinned by tests/test_fleet.py). `seed` is the SCORING seed (the
    RNG stream of the stochastic path), shared across the fleet like
    the serial sweep shares it across solo runs. `int8=True` expects
    stacked QTensor trees (or quantizes dense ones) and dequantizes
    in-program — the serving dispatch's int8 bucket."""
    if int8:
        from factorvae_tpu.ops.quant import ensure_quantized

        stacked_params = ensure_quantized(stacked_params)
    s = num_seeds
    if s is None:
        leaf = jax.tree.leaves(stacked_params)[0]
        s = int(leaf.shape[0])
    if s == 1:
        one = jax.tree.map(lambda x: x[0], stacked_params)
        return predict_panel(one, config, dataset, days, stochastic, seed,
                             chunk=chunk, mesh=mesh, int8=int8)[None]

    n_days = len(days)
    if n_days == 0:
        return np.full((s, 0, dataset.n_max), np.nan, np.float32)
    if getattr(dataset, "residency", "hbm") == "stream":
        return _predict_stream(stacked_params, config, dataset, days,
                               stochastic, seed, chunk, int8=int8,
                               stacked=True, mesh=mesh)
    base = jax.random.PRNGKey(seed)
    day_idx, keys = _scan_inputs(
        days, chunk, base, _deterministic(config.model, stochastic))
    score_scan = _score_scan_fleet_fn(
        config.model, config.data.seq_len, stochastic, int8)
    scores = score_scan(stacked_params, dataset.values, dataset.last_valid,
                        dataset.next_valid, day_idx, keys)
    out = np.asarray(scores, dtype=np.float32).reshape(
        s, -1, dataset.n_max)
    return out[:, :n_days]


def _frame_pieces(dataset: PanelDataset, days: np.ndarray,
                  with_labels: bool):
    """(index, valid mask, flat labels-or-None) shared by the serial and
    fleet frame builders — one definition of the score-frame schema."""
    idx = dataset.index_frame(days)
    valid = dataset.valid[days]                      # (D, N_max)
    labels = (dataset.day_labels(days)[valid] if with_labels else None)
    return idx, valid, labels


def _score_frame(scores: np.ndarray, idx, valid, labels) -> pd.DataFrame:
    """(D, N_max) scores -> the (datetime, instrument)-indexed frame
    (plus LABEL0 when labels are given)."""
    df = pd.DataFrame({"score": scores[valid]}, index=idx)
    if labels is not None:
        df["LABEL0"] = labels
    return df


def fleet_prediction_scores(
    stacked_params,
    config: Config,
    dataset: PanelDataset,
    start: Optional[str] = None,
    end: Optional[str] = None,
    stochastic: Optional[bool] = None,
    seed: int = 0,
    with_labels: bool = False,
    mesh=None,
) -> list:
    """Per-seed score DataFrames (same schema as
    `generate_prediction_scores` — shared frame builder) from one
    seed-batched scoring pass: S frames for the price of one program
    dispatch."""
    days = dataset.split_days(start, end)
    scores = predict_panel_fleet(stacked_params, config, dataset, days,
                                 stochastic, seed, mesh=mesh)
    idx, valid, labels = _frame_pieces(dataset, days, with_labels)
    return [_score_frame(scores[i], idx, valid, labels)
            for i in range(scores.shape[0])]


def generate_prediction_scores(
    params,
    config: Config,
    dataset: PanelDataset,
    start: Optional[str] = None,
    end: Optional[str] = None,
    stochastic: Optional[bool] = None,
    seed: int = 0,
    with_labels: bool = False,
    int8: bool = False,
    mesh=None,
) -> pd.DataFrame:
    """Scores DataFrame with MultiIndex (datetime, instrument) and a
    'score' column (plus 'LABEL0' when with_labels=True, matching the
    merge the backtest notebook performs in cell 5)."""
    days = dataset.split_days(start, end)
    scores = predict_panel(params, config, dataset, days, stochastic, seed,
                           int8=int8, mesh=mesh)
    idx, valid, labels = _frame_pieces(dataset, days, with_labels)
    return _score_frame(scores, idx, valid, labels)


def export_scores(df: pd.DataFrame, config: Config, out_dir: str = "./scores") -> str:
    """CSV export under the reference's score naming scheme
    (scores/readme.md:2-8; see Config.score_name)."""
    import os

    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, config.score_name() + ".csv")
    df.reset_index().to_csv(path, index=False)
    return path
