"""Backtest report figure — the reference's `report_graph` artifact.

The reference's notebook renders qlib's `analysis_position.report_graph`
(backtest.ipynb cell 7) and ships the output as `backtest.png` /
`backtest_plotly/*.png` (SURVEY.md §2.2): cumulative strategy/benchmark
return, drawdown, excess return w/ and w/o cost, and daily turnover.
`report_graph` here reproduces that artifact from an
`AccountBacktestResult.report` frame (the `report_normal_df` analogue),
with no qlib or plotly dependency — matplotlib only, and importable
without matplotlib until called.

Design notes: one y-axis per panel (never dual-axis); Okabe–Ito
colorblind-safe hues assigned in fixed order with linestyle as the
secondary encoding (the palette validator isn't runnable in this image
— Okabe–Ito is the published CVD-safe reference set); recessive grid;
legends on every multi-series panel.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd

# Okabe-Ito: fixed assignment, never cycled
_C_STRATEGY = "#0072B2"   # blue
_C_BENCH = "#999999"      # gray
_C_NOCOST = "#E69F00"     # orange
_C_EXCESS = "#009E73"     # green
_GRID = dict(color="#d0d0d0", linewidth=0.6, alpha=0.7)


def report_graph(
    report: pd.DataFrame,
    path: str,
    title: Optional[str] = None,
) -> str:
    """Render the 4-panel backtest report to `path` (PNG).

    `report` is an `AccountBacktestResult.report` frame: datetime index,
    columns return / bench / cost / turnover (account/cash/value are
    not plotted). Returns `path`.
    """
    # Render through an explicit Agg canvas — no pyplot, no global
    # backend switch (a notebook caller's inline/Qt backend is untouched)
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure

    net = report["return"] - report["cost"]
    cum = (1.0 + net).cumprod() - 1.0
    cum_wo = (1.0 + report["return"]).cumprod() - 1.0
    cum_bench = (1.0 + report["bench"]).cumprod() - 1.0
    curve = 1.0 + cum
    drawdown = curve / np.maximum.accumulate(
        np.concatenate([[1.0], curve.to_numpy()]))[1:] - 1.0
    ex_wo = (report["return"] - report["bench"]).cumsum()
    ex_w = (report["return"] - report["bench"] - report["cost"]).cumsum()

    fig = Figure(figsize=(9, 10))
    FigureCanvasAgg(fig)
    axes = fig.subplots(4, 1, sharex=True)
    ax = axes[0]
    ax.plot(cum.index, cum, color=_C_STRATEGY, lw=1.6, label="strategy")
    ax.plot(cum_wo.index, cum_wo, color=_C_NOCOST, lw=1.2, ls="--",
            label="strategy w/o cost")
    ax.plot(cum_bench.index, cum_bench, color=_C_BENCH, lw=1.4,
            label="benchmark")
    ax.set_ylabel("cumulative return")
    ax.legend(frameon=False, fontsize=8)

    ax = axes[1]
    ax.fill_between(drawdown.index, drawdown, 0.0, color=_C_STRATEGY,
                    alpha=0.35, lw=0)
    ax.plot(drawdown.index, drawdown, color=_C_STRATEGY, lw=1.0)
    ax.set_ylabel("drawdown")

    ax = axes[2]
    ax.plot(ex_wo.index, ex_wo, color=_C_EXCESS, lw=1.4,
            label="excess w/o cost")
    ax.plot(ex_w.index, ex_w, color=_C_EXCESS, lw=1.2, ls="--",
            label="excess w/ cost")
    ax.set_ylabel("cumulative excess")
    ax.legend(frameon=False, fontsize=8)

    ax = axes[3]
    ax.plot(report.index, report["turnover"], color=_C_STRATEGY, lw=1.0)
    ax.set_ylabel("turnover")

    for ax in axes:
        ax.grid(True, **_GRID)
        for side in ("top", "right"):
            ax.spines[side].set_visible(False)
    if title:
        fig.suptitle(title, fontsize=11)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    return path
