"""Per-stock feature extractor.

Capability parity with reference module.py:10-31 (`FeatureExtractor`):
LayerNorm(C) -> Linear(C->C) -> LeakyReLU -> 1-layer GRU over T ->
last hidden state, giving the per-stock latent e in (N, H).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from factorvae_tpu.config import ModelConfig
from factorvae_tpu.models.layers import GRU, Dense, StackedGRU, layer_norm


class FeatureExtractor(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        """x: (N, T, C) firm characteristics -> (N, H) stock latents.

        Padded stocks produce garbage latents that downstream masked
        reductions ignore; keeping them in the batch keeps every matmul a
        full, static-shape MXU op.
        """
        cfg = self.cfg
        dtype = cfg.dtype
        x = x.astype(dtype)
        x = layer_norm(x, dtype=dtype)                       # module.py:26
        x = Dense(
            cfg.num_features, torch_init=cfg.torch_init, dtype=dtype, name="proj"
        )(x)                                                 # module.py:27
        x = nn.leaky_relu(x, negative_slope=cfg.leaky_relu_slope)  # module.py:28
        # Single-layer (the reference default, module.py:20) keeps the flat
        # gru/{input_proj,hidden_kernel,hidden_bias} param layout so
        # existing checkpoints restore unchanged; L>1 nests per-layer.
        if cfg.gru_layers == 1:
            gru = GRU(
                cfg.hidden_size, torch_init=cfg.torch_init, dtype=dtype,
                use_pallas=cfg.use_pallas_gru, name="gru",
            )
        else:
            gru = StackedGRU(
                cfg.hidden_size,
                num_layers=cfg.gru_layers,
                torch_init=cfg.torch_init,
                dtype=dtype,
                name="gru",
            )
        latent = gru(x)                                      # module.py:30-31
        return latent.astype(jnp.float32)
