"""Posterior factor encoder.

Capability parity with reference module.py:33-67 (`FactorEncoder`):
stock latents -> M portfolio weights via Linear + softmax over the *stock*
axis (the reference's annotated "BUG Fixed: dim=1 -> dim=0" at
module.py:38), portfolio returns y_p = W^T y, then mu/sigma heads with
Softplus -> posterior (mu_post, sigma_post) in (K,).

The softmax over stocks becomes a masked softmax so padded stocks carry
exactly zero portfolio weight; the portfolio matmul then needs no separate
masking.

`day_batched` is the cross-day-flattened variant (VERDICT r2 #2): the
per-stock portfolio Dense runs on the full (B, N, H) block in one matmul;
only the stock-axis softmax and the portfolio contraction stay per-day.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from factorvae_tpu.config import ModelConfig
from factorvae_tpu.models.layers import Dense
from factorvae_tpu.ops.masked import masked_softmax


class FactorEncoder(nn.Module):
    cfg: ModelConfig

    def setup(self):
        cfg = self.cfg
        self.portfolio = Dense(cfg.num_portfolios, torch_init=cfg.torch_init)
        self.mu = Dense(cfg.num_factors, torch_init=cfg.torch_init)
        self.sigma = Dense(cfg.num_factors, torch_init=cfg.torch_init)

    def __call__(self, latent: jnp.ndarray, returns: jnp.ndarray, mask: jnp.ndarray):
        """latent: (N, H), returns: (N,), mask: (N,) -> ((K,), (K,))."""
        w = self.portfolio(latent)                            # module.py:56
        w = masked_softmax(w, mask[:, None], axis=0)          # module.py:57 (dim=0)
        returns = jnp.where(mask, returns, 0.0)
        y_p = w.T @ returns                                   # module.py:64, (M,)
        mu = self.mu(y_p)
        sigma = nn.softplus(self.sigma(y_p))                  # module.py:44-50
        return mu, sigma

    def day_batched(
        self, latent: jnp.ndarray, returns: jnp.ndarray, mask: jnp.ndarray
    ):
        """latent: (B, N, H), returns/mask: (B, N) -> ((B, K), (B, K)).

        Same math as `__call__` per day; the Dense layers see the whole
        (B·N | B) row block so the MXU is fed B-fold-taller matmuls.
        """
        w = self.portfolio(latent)                            # (B, N, M)
        w = masked_softmax(w, mask[..., None], axis=1)        # softmax over stocks
        returns = jnp.where(mask, returns, 0.0)
        y_p = jnp.einsum("bnm,bn->bm", w, returns)            # (B, M)
        mu = self.mu(y_p)
        sigma = nn.softplus(self.sigma(y_p))
        return mu, sigma
