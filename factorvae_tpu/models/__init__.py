from factorvae_tpu.models.decoder import AlphaLayer, BetaLayer, FactorDecoder
from factorvae_tpu.models.encoder import FactorEncoder
from factorvae_tpu.models.extractor import FeatureExtractor
from factorvae_tpu.models.factorvae import (
    FactorVAE,
    FactorVAEOutput,
    day_forward,
    day_prediction,
)
from factorvae_tpu.models.layers import GRU, Dense
from factorvae_tpu.models.predictor import FactorPredictor

__all__ = [
    "AlphaLayer",
    "BetaLayer",
    "Dense",
    "FactorDecoder",
    "FactorEncoder",
    "FactorPredictor",
    "FactorVAE",
    "FactorVAEOutput",
    "FeatureExtractor",
    "GRU",
    "day_forward",
    "day_prediction",
]
