"""Prior factor predictor: K-head attention over the stock cross-section.

Capability parity with reference module.py:125-188 (`AttentionLayer` x K +
`FactorPredictor`), re-designed for the MXU: the reference iterates K
independent single-head attention modules in a Python loop
(module.py:172-178) — K up to 96 sequential kernel launches, its single
worst accelerator-utilization sin (SURVEY.md §3.5). Here all K heads run
as three batched einsums over a (K, H, H) weight stack; the math per head
is identical because the reference heads share nothing but their input.

Faithfully preserved quirks:
- scores = q . K^T / sqrt(H + 1e-6)  (module.py:140-142)
- the odd op order dropout(0.1) -> ReLU -> softmax-over-stocks
  (module.py:144-146)
- NaN/Inf guard: a head whose attention weights go non-finite contributes
  a zero context vector (module.py:149-150)
- a single learned query vector per head, init ~ N(0,1) (module.py:129)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from factorvae_tpu.config import ModelConfig
from factorvae_tpu.models.layers import Dense, torch_uniform_init
from factorvae_tpu.ops.masked import masked_softmax


class FactorPredictor(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, latent: jnp.ndarray, mask: jnp.ndarray, *, train: bool = False):
        """latent: (N, H), mask: (N,) -> prior (mu_prior, sigma_prior), each (K,)."""
        cfg = self.cfg
        k, h = cfg.num_factors, cfg.hidden_size

        query = self.param("query", nn.initializers.normal(1.0), (k, h))
        init = torch_uniform_init(h) if cfg.torch_init else nn.initializers.lecun_normal()
        w_key = self.param("key_kernel", init, (k, h, h))
        b_key = self.param("key_bias", init, (k, h))
        w_val = self.param("value_kernel", init, (k, h, h))
        b_val = self.param("value_bias", init, (k, h))

        from factorvae_tpu.ops.pallas.select import (
            pallas_attention_wins,
            resolve,
        )

        use_pallas = resolve(
            cfg.use_pallas_attention,
            pallas_attention_wins(latent.shape[0], h, k),
        )
        if use_pallas:
            # Fused Pallas kernel: never materializes the (K, N, H)
            # key/value stacks in HBM, and is differentiable (custom VJP
            # with flash-style recompute backward), so it serves inference
            # AND training. The reference's score dropout (module.py:144,
            # applied before the ReLU) is a tiny (K, N) keep-mask drawn
            # outside the kernel from the flax 'dropout' rng.
            from factorvae_tpu.ops.pallas.attention_grad import fused_attention

            dropout_mask = None
            if train and cfg.dropout_rate > 0.0:
                keep_p = 1.0 - cfg.dropout_rate
                keep = jax.random.bernoulli(
                    self.make_rng("dropout"), keep_p, (k, latent.shape[0])
                )
                dropout_mask = keep.astype(jnp.float32) / keep_p
            context = fused_attention(
                latent, mask.astype(jnp.float32), query, w_key, b_key,
                w_val, b_val, dropout_mask,
            )
        else:
            # All K per-head Linears at once: (N,H) x (K,H,H) -> (K,N,H).
            keys = jnp.einsum("nh,khj->knj", latent, w_key) + b_key[:, None, :]
            values = jnp.einsum("nh,khj->knj", latent, w_val) + b_val[:, None, :]

            scores = jnp.einsum("kh,knh->kn", query, keys)
            scores = scores / jnp.sqrt(jnp.float32(h) + 1e-6)   # module.py:142
            scores = nn.Dropout(cfg.dropout_rate)(scores, deterministic=not train)
            scores = nn.relu(scores)                            # module.py:145
            attn = masked_softmax(scores, mask[None, :], axis=-1)  # module.py:146

            # Per-head NaN/Inf guard -> zero context (module.py:149-150).
            # Keyed off the *scores*: a non-finite score makes the
            # reference's softmax weights non-finite for the whole head;
            # our masked softmax zeroes them silently, so without this the
            # NaN would re-enter through 0 * NaN in the value contraction.
            bad = jnp.any(
                ~jnp.isfinite(jnp.where(mask[None, :], scores, 0.0)),
                axis=-1, keepdims=True,
            )
            attn = jnp.where(bad, 0.0, attn)
            context = jnp.where(
                bad, 0.0, jnp.einsum("kn,knh->kh", attn, jnp.nan_to_num(values))
            )                                                   # (K, H)

        h_multi = Dense(h, torch_init=cfg.torch_init, name="proj")(context)
        h_multi = nn.leaky_relu(h_multi, negative_slope=cfg.leaky_relu_slope)
        mu = Dense(1, torch_init=cfg.torch_init, name="mu")(h_multi)[:, 0]
        sigma = nn.softplus(Dense(1, torch_init=cfg.torch_init, name="sigma")(h_multi))[
            :, 0
        ]                                                       # module.py:181-187
        return mu, sigma
