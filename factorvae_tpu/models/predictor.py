"""Prior factor predictor: K-head attention over the stock cross-section.

Capability parity with reference module.py:125-188 (`AttentionLayer` x K +
`FactorPredictor`), re-designed for the MXU: the reference iterates K
independent single-head attention modules in a Python loop
(module.py:172-178) — K up to 96 sequential kernel launches, its single
worst accelerator-utilization sin (SURVEY.md §3.5). Here all K heads run
as three batched einsums over a (K, H, H) weight stack; the math per head
is identical because the reference heads share nothing but their input.
`day_batched` additionally carries a leading day axis through every einsum
(VERDICT r2 #2 cross-day flattening), so B days' heads land on the MXU as
one contraction instead of B.

Faithfully preserved quirks:
- scores = q . K^T / sqrt(H + 1e-6)  (module.py:140-142)
- the odd op order dropout(0.1) -> ReLU -> softmax-over-stocks
  (module.py:144-146)
- NaN/Inf guard: a head whose attention weights go non-finite contributes
  a zero context vector (module.py:149-150)
- a single learned query vector per head, init ~ N(0,1) (module.py:129)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from factorvae_tpu.config import ModelConfig
from factorvae_tpu.models.layers import Dense, torch_uniform_init
from factorvae_tpu.ops.masked import masked_softmax


class FactorPredictor(nn.Module):
    cfg: ModelConfig

    def setup(self):
        cfg = self.cfg
        k, h = cfg.num_factors, cfg.hidden_size
        init = (
            torch_uniform_init(h) if cfg.torch_init
            else nn.initializers.lecun_normal()
        )
        self.query = self.param("query", nn.initializers.normal(1.0), (k, h))
        self.key_kernel = self.param("key_kernel", init, (k, h, h))
        self.key_bias = self.param("key_bias", init, (k, h))
        self.value_kernel = self.param("value_kernel", init, (k, h, h))
        self.value_bias = self.param("value_bias", init, (k, h))
        self.proj = Dense(h, torch_init=cfg.torch_init)
        self.mu = Dense(1, torch_init=cfg.torch_init)
        self.sigma = Dense(1, torch_init=cfg.torch_init)

    def _heads(self, context: jnp.ndarray):
        """Shared head MLP (module.py:181-187); context (..., K, H)."""
        cfg = self.cfg
        h_multi = self.proj(context)
        h_multi = nn.leaky_relu(h_multi, negative_slope=cfg.leaky_relu_slope)
        mu = self.mu(h_multi)[..., 0]
        sigma = nn.softplus(self.sigma(h_multi))[..., 0]
        return mu, sigma

    def _use_pallas(self, n: int) -> bool:
        from factorvae_tpu.plan import pallas_attention_wins, resolve

        cfg = self.cfg
        return resolve(
            cfg.use_pallas_attention,
            pallas_attention_wins(n, cfg.hidden_size, cfg.num_factors),
        )

    def _dropout_mask(self, shape):
        """Reference score dropout (module.py:144, before the ReLU) as an
        explicit inverted-scale keep-mask from the flax 'dropout' rng —
        shared by the Pallas path (drawn outside the kernel) and the
        batched einsum path (one draw covers all days; iid either way)."""
        cfg = self.cfg
        keep_p = 1.0 - cfg.dropout_rate
        keep = jax.random.bernoulli(self.make_rng("dropout"), keep_p, shape)
        return keep.astype(jnp.float32) / keep_p

    def __call__(self, latent: jnp.ndarray, mask: jnp.ndarray, *, train: bool = False):
        """latent: (N, H), mask: (N,) -> prior (mu_prior, sigma_prior), each (K,)."""
        cfg = self.cfg
        k, h = cfg.num_factors, cfg.hidden_size

        if self._use_pallas(latent.shape[0]):
            # Fused Pallas kernel: never materializes the (K, N, H)
            # key/value stacks in HBM, and is differentiable (custom VJP
            # with flash-style recompute backward), so it serves inference
            # AND training.
            from factorvae_tpu.ops.pallas.attention_grad import fused_attention

            dropout_mask = None
            if train and cfg.dropout_rate > 0.0:
                dropout_mask = self._dropout_mask((k, latent.shape[0]))
            context = fused_attention(
                latent, mask.astype(jnp.float32), self.query,
                self.key_kernel, self.key_bias,
                self.value_kernel, self.value_bias, dropout_mask,
            )
        else:
            # All K per-head Linears at once: (N,H) x (K,H,H) -> (K,N,H).
            keys = (jnp.einsum("nh,khj->knj", latent, self.key_kernel)
                    + self.key_bias[:, None, :])
            values = (jnp.einsum("nh,khj->knj", latent, self.value_kernel)
                      + self.value_bias[:, None, :])

            scores = jnp.einsum("kh,knh->kn", self.query, keys)
            scores = scores / jnp.sqrt(jnp.float32(h) + 1e-6)   # module.py:142
            if train and cfg.dropout_rate > 0.0:                # module.py:144
                scores = scores * self._dropout_mask(scores.shape)
            scores = nn.relu(scores)                            # module.py:145
            attn = masked_softmax(scores, mask[None, :], axis=-1)  # module.py:146

            # Per-head NaN/Inf guard -> zero context (module.py:149-150).
            # Keyed off the *scores*: a non-finite score makes the
            # reference's softmax weights non-finite for the whole head;
            # our masked softmax zeroes them silently, so without this the
            # NaN would re-enter through 0 * NaN in the value contraction.
            bad = jnp.any(
                ~jnp.isfinite(jnp.where(mask[None, :], scores, 0.0)),
                axis=-1, keepdims=True,
            )
            attn = jnp.where(bad, 0.0, attn)
            context = jnp.where(
                bad, 0.0, jnp.einsum("kn,knh->kh", attn, jnp.nan_to_num(values))
            )                                                   # (K, H)

        return self._heads(context)

    def day_batched(
        self, latent: jnp.ndarray, mask: jnp.ndarray, *, train: bool = False
    ):
        """latent: (B, N, H), mask: (B, N) -> ((B, K), (B, K)).

        Identical per-day math to `__call__`; the key/value/score einsums
        and the head MLP contract over B days at once. The stock-axis
        softmax and the per-(day, head) non-finite guard remain day-local
        reductions, as they must.
        """
        cfg = self.cfg
        k, h = cfg.num_factors, cfg.hidden_size
        b, n = latent.shape[0], latent.shape[1]

        if self._use_pallas(n):
            # The kernel is single-day; batch it with a plain vmap (its
            # custom VJP and pallas_call both carry batching rules) —
            # exactly what the nn.vmap day lift did before flattening.
            from factorvae_tpu.ops.pallas.attention_grad import fused_attention

            dropout_mask = None
            if train and cfg.dropout_rate > 0.0:
                dropout_mask = self._dropout_mask((b, k, n))
            query, wk, bk = self.query, self.key_kernel, self.key_bias
            wv, bv = self.value_kernel, self.value_bias
            if dropout_mask is None:
                context = jax.vmap(
                    lambda lat, m: fused_attention(
                        lat, m, query, wk, bk, wv, bv, None)
                )(latent, mask.astype(jnp.float32))
            else:
                context = jax.vmap(
                    lambda lat, m, dm: fused_attention(
                        lat, m, query, wk, bk, wv, bv, dm)
                )(latent, mask.astype(jnp.float32), dropout_mask)
        else:
            keys = (jnp.einsum("bnh,khj->bknj", latent, self.key_kernel)
                    + self.key_bias[None, :, None, :])
            values = (jnp.einsum("bnh,khj->bknj", latent, self.value_kernel)
                      + self.value_bias[None, :, None, :])

            scores = jnp.einsum("kh,bknh->bkn", self.query, keys)
            scores = scores / jnp.sqrt(jnp.float32(h) + 1e-6)
            if train and cfg.dropout_rate > 0.0:
                scores = scores * self._dropout_mask(scores.shape)
            scores = nn.relu(scores)
            attn = masked_softmax(scores, mask[:, None, :], axis=-1)

            bad = jnp.any(
                ~jnp.isfinite(jnp.where(mask[:, None, :], scores, 0.0)),
                axis=-1, keepdims=True,
            )                                                   # (B, K, 1)
            attn = jnp.where(bad, 0.0, attn)
            context = jnp.where(
                bad, 0.0,
                jnp.einsum("bkn,bknh->bkh", attn, jnp.nan_to_num(values)),
            )                                                   # (B, K, H)

        return self._heads(context)
