"""Shared building blocks: initializers, Dense, LayerNorm, GRU.

The GRU is the TPU-idiomatic replacement for the reference's cuDNN
``nn.GRU`` (module.py:20): the input-side projection for *all* T steps is
hoisted out of the recurrence into one large matmul (MXU-friendly), and the
recurrence itself is a `lax.scan` whose per-step work is a single
(N,H)x(H,3H) matmul — T is only 20-60, so the scan is cheap and XLA
unrolls/fuses it well.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


def torch_uniform_init(fan_in: int) -> Callable:
    """U(-1/sqrt(fan_in), +1/sqrt(fan_in)).

    The scale torch uses for both nn.Linear (kaiming_uniform(a=sqrt(5)) on
    the weight plus U(+-1/sqrt(fan_in)) on the bias) and nn.GRU parameters,
    so training dynamics start from the same parameter scale as the
    reference without copying any code.
    """
    bound = 1.0 / (fan_in**0.5)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)

    return init


class Dense(nn.Module):
    """nn.Dense with torch-scale init (see `torch_uniform_init`)."""

    features: int
    use_bias: bool = True
    torch_init: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        if self.torch_init:
            kinit = torch_uniform_init(fan_in)
            binit = torch_uniform_init(fan_in)
        else:
            kinit = nn.initializers.lecun_normal()
            binit = nn.initializers.zeros_init()
        return nn.Dense(
            self.features,
            use_bias=self.use_bias,
            kernel_init=kinit,
            bias_init=binit,
            dtype=self.dtype,
        )(x)


def layer_norm(x, dtype=None):
    """LayerNorm with torch defaults (eps=1e-5, elementwise affine)."""
    return nn.LayerNorm(epsilon=1e-5, dtype=dtype)(x)


class StackedGRU(nn.Module):
    """Multi-layer GRU (torch nn.GRU(num_layers=L) semantics): each layer
    consumes the full hidden sequence of the previous one; returns the top
    layer's last hidden state. The reference always uses L=1
    (module.py:20) but exposes num_layers; parity for L>1 is kept here.
    """

    hidden_size: int
    num_layers: int = 1
    torch_init: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for layer in range(self.num_layers):
            last = layer == self.num_layers - 1
            gru = GRU(
                self.hidden_size,
                torch_init=self.torch_init,
                dtype=self.dtype,
                return_sequence=not last,
                name=f"layer_{layer}",
            )
            x = gru(x)
        return x


class GRU(nn.Module):
    """Single-layer GRU over the time axis, returning the last hidden state.

    Gate equations and weight layout follow the standard (torch) GRU:

        r = sigmoid(x W_ir + b_ir + h W_hr + b_hr)
        z = sigmoid(x W_iz + b_iz + h W_hz + b_hz)
        n = tanh  (x W_in + b_in + r * (h W_hn + b_hn))
        h' = (1 - z) * n + z * h

    Input: (N, T, C). Output: (N, H) — the hidden state after the last
    step, i.e. the reference's ``stock_latent[:, -1, :]`` (module.py:30-31)
    — or the full (N, T, H) hidden sequence with return_sequence=True
    (used by StackedGRU's intermediate layers).
    """

    hidden_size: int
    torch_init: bool = True
    dtype: Optional[jnp.dtype] = None
    return_sequence: bool = False
    # Fused Pallas recurrence kernel (ops/pallas/gru.py): whole-sequence
    # VMEM-resident scan with custom-VJP BPTT. Last-hidden output only.
    # False | True | "auto" (per-shape measured choice, factorvae_tpu/plan).
    use_pallas: Any = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        n, t, c = x.shape
        h_dim = self.hidden_size
        init = (
            torch_uniform_init(h_dim)
            if self.torch_init
            else nn.initializers.lecun_normal()
        )
        # Input projection for all T steps in one matmul (N*T, C)x(C, 3H).
        xi = Dense(
            3 * h_dim, torch_init=self.torch_init, dtype=self.dtype, name="input_proj"
        )(x)
        w_h = self.param("hidden_kernel", init, (h_dim, 3 * h_dim))
        b_h = self.param(
            "hidden_bias",
            init if self.torch_init else nn.initializers.zeros_init(),
            (3 * h_dim,),
        )
        dtype = self.dtype or x.dtype

        from factorvae_tpu.plan import pallas_gru_wins, resolve

        use_pallas = resolve(
            self.use_pallas, pallas_gru_wins(n, t, h_dim))
        if use_pallas and not self.return_sequence:
            from factorvae_tpu.ops.pallas.gru import backward_fits, gru_scan

            if backward_fits(n, t, h_dim):
                return gru_scan(xi.astype(jnp.float32), w_h, b_h).astype(dtype)
            # A divisor-free (prime) T forces the kernel's full-sequence
            # backward, whose VMEM footprint grows linearly in T and can
            # exceed the scoped budget on a real chip (ADVICE r2); the
            # XLA scan below is always safe, so it overrides even an
            # explicit use_pallas=True.
            import warnings

            warnings.warn(
                f"pallas GRU backward does not fit VMEM at T={t}, H={h_dim} "
                "(divisor-free sequence length); using the XLA scan path",
                stacklevel=2,
            )

        w_h = w_h.astype(dtype)
        b_h = b_h.astype(dtype)

        def step(h, xi_t):
            gh = h @ w_h + b_h
            r = jax.nn.sigmoid(xi_t[:, :h_dim] + gh[:, :h_dim])
            z = jax.nn.sigmoid(xi_t[:, h_dim : 2 * h_dim] + gh[:, h_dim : 2 * h_dim])
            nn_ = jnp.tanh(xi_t[:, 2 * h_dim :] + r * gh[:, 2 * h_dim :])
            h_new = (1.0 - z) * nn_ + z * h
            return h_new, h_new if self.return_sequence else None

        h0 = jnp.zeros((n, h_dim), dtype=dtype)
        h_last, seq = jax.lax.scan(step, h0, jnp.swapaxes(xi, 0, 1))
        if self.return_sequence:
            return jnp.swapaxes(seq, 0, 1)  # (N, T, H)
        return h_last
