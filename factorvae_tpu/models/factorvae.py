"""The FactorVAE top-level model.

Capability parity with reference module.py:234-278 (`FactorVAE`): wires
extractor, posterior encoder, decoder and prior predictor; the training
loss is reconstruction + KL(posterior || prior) summed over K. The model
operates on ONE trading day's padded cross-section; day batching is done
with `nn.vmap` (see `day_forward` / `day_prediction`) so the per-day
cross-stock reductions stay local to a day.

Loss parity notes (SURVEY.md §7 hard-parts):
- 'mse' mode reproduces module.py:261 exactly: MSE between the single
  reparameterized sample and the labels (a mean over stocks), while the KL
  is a *sum* over K — the scale imbalance is intentional.
- 'nll' mode is the paper's analytic Gaussian reconstruction likelihood.
"""

from __future__ import annotations

from typing import Optional

import flax.struct
import jax
import jax.numpy as jnp
from flax import linen as nn

from factorvae_tpu.config import ModelConfig
from factorvae_tpu.models.decoder import FactorDecoder
from factorvae_tpu.models.encoder import FactorEncoder
from factorvae_tpu.models.extractor import FeatureExtractor
from factorvae_tpu.models.predictor import FactorPredictor
from factorvae_tpu.ops.kl import gaussian_kl_sum
from factorvae_tpu.ops.masked import masked_gaussian_nll, masked_mse


@flax.struct.dataclass
class FactorVAEOutput:
    """Everything the reference forward returns (module.py:270), plus the
    loss decomposition."""

    loss: jnp.ndarray
    recon_loss: jnp.ndarray
    kl: jnp.ndarray
    reconstruction: jnp.ndarray      # (N,) sampled returns
    factor_mu: jnp.ndarray           # (K,) posterior mean
    factor_sigma: jnp.ndarray        # (K,) posterior std
    pred_mu: jnp.ndarray             # (K,) prior mean
    pred_sigma: jnp.ndarray          # (K,) prior std


class FactorVAE(nn.Module):
    cfg: ModelConfig

    def setup(self):
        self.feature_extractor = FeatureExtractor(self.cfg)
        self.factor_encoder = FactorEncoder(self.cfg)
        self.factor_decoder = FactorDecoder(self.cfg)
        self.factor_predictor = FactorPredictor(self.cfg)

    def __call__(
        self,
        x: jnp.ndarray,
        returns: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        *,
        train: bool = False,
    ) -> FactorVAEOutput:
        """One day's padded cross-section.

        x: (N, T, C) characteristics; returns: (N,) next-period returns;
        mask: (N,) validity (None -> all valid). Needs rngs: 'sample'
        (reparameterization) and, when train=True, 'dropout'.
        """
        cfg = self.cfg
        if mask is None:
            mask = jnp.ones(x.shape[0], dtype=bool)
        # Labels can be NaN on inference panels (forward-looking label
        # missing); zero them for the encoder's portfolio matmul and
        # exclude them from the loss below — the ETL's DropnaLabel
        # guarantees the reference never sees one in training
        # (data/make_dataset.py:55).
        loss_mask = mask & jnp.isfinite(returns)
        returns = jnp.where(loss_mask, returns, 0.0)

        latent = self.feature_extractor(x)                          # module.py:254
        factor_mu, factor_sigma = self.factor_encoder(latent, returns, mask)
        sample, (recon_mu, recon_sigma) = self.factor_decoder(
            latent, factor_mu, factor_sigma, sample=True
        )                                                           # module.py:256
        pred_mu, pred_sigma = self.factor_predictor(latent, mask, train=train)

        if cfg.recon_loss == "mse":
            recon = masked_mse(sample, returns, loss_mask)          # module.py:261
        elif cfg.recon_loss == "nll":
            recon = masked_gaussian_nll(recon_mu, recon_sigma, returns, loss_mask)
        else:
            raise ValueError(f"unknown recon_loss {cfg.recon_loss!r}")
        kl = gaussian_kl_sum(factor_mu, factor_sigma, pred_mu, pred_sigma)
        #                                                           module.py:264-268
        return FactorVAEOutput(
            loss=recon + cfg.kl_weight * kl,
            recon_loss=recon,
            kl=kl,
            reconstruction=jnp.where(mask, sample, 0.0),
            factor_mu=factor_mu,
            factor_sigma=factor_sigma,
            pred_mu=pred_mu,
            pred_sigma=pred_sigma,
        )

    def day_batched_forward(
        self,
        x: jnp.ndarray,
        returns: jnp.ndarray,
        mask: jnp.ndarray,
        *,
        train: bool = False,
    ) -> FactorVAEOutput:
        """Day-batched forward with cross-day flattening (VERDICT r2 #2).

        x: (B, N, T, C); returns/mask: (B, N). Same math as `__call__`
        vmapped over days, but the day-independent per-stock segment —
        LayerNorm -> Dense -> GRU in the extractor, the alpha/beta heads,
        the portfolio/key/value projections — runs on the flattened
        (B·N, ...) block so the MXU sees one B-fold-taller matmul instead
        of B row-starved ones (the round-2 trace showed 8 separate
        N=360-row matmuls per step at days_per_step=8). Only the genuinely
        day-local reductions — stock-axis softmaxes, portfolio
        contraction, attention, losses — keep the day axis.
        """
        cfg = self.cfg
        b, n = x.shape[0], x.shape[1]
        loss_mask = mask & jnp.isfinite(returns)
        returns = jnp.where(loss_mask, returns, 0.0)

        latent = self.feature_extractor(
            x.reshape((b * n,) + x.shape[2:])
        ).reshape(b, n, -1)                                     # module.py:254
        factor_mu, factor_sigma = self.factor_encoder.day_batched(
            latent, returns, mask)                              # module.py:255
        sample, (recon_mu, recon_sigma) = self.factor_decoder.day_batched(
            latent, factor_mu, factor_sigma, sample=True)       # module.py:256
        pred_mu, pred_sigma = self.factor_predictor.day_batched(
            latent, mask, train=train)                          # module.py:257

        if cfg.recon_loss == "mse":
            recon = jax.vmap(masked_mse)(sample, returns, loss_mask)
        elif cfg.recon_loss == "nll":
            recon = jax.vmap(masked_gaussian_nll)(
                recon_mu, recon_sigma, returns, loss_mask)
        else:
            raise ValueError(f"unknown recon_loss {cfg.recon_loss!r}")
        kl = jax.vmap(gaussian_kl_sum)(
            factor_mu, factor_sigma, pred_mu, pred_sigma)
        return FactorVAEOutput(
            loss=recon + cfg.kl_weight * kl,
            recon_loss=recon,
            kl=kl,
            reconstruction=jnp.where(mask, sample, 0.0),
            factor_mu=factor_mu,
            factor_sigma=factor_sigma,
            pred_mu=pred_mu,
            pred_sigma=pred_sigma,
        )

    def day_batched_prediction(
        self,
        x: jnp.ndarray,
        mask: jnp.ndarray,
        *,
        stochastic: Optional[bool] = None,
    ) -> jnp.ndarray:
        """Day-batched `prediction` (module.py:273-278) with the same
        cross-day flattening as `day_batched_forward`: (B, N, T, C) ->
        (B, N) scores, NaN on padded stocks."""
        cfg = self.cfg
        b, n = x.shape[0], x.shape[1]
        if stochastic is None:
            stochastic = cfg.stochastic_inference
        latent = self.feature_extractor(
            x.reshape((b * n,) + x.shape[2:])
        ).reshape(b, n, -1)
        pred_mu, pred_sigma = self.factor_predictor.day_batched(
            latent, mask, train=False)
        y_pred, _ = self.factor_decoder.day_batched(
            latent, pred_mu, pred_sigma, sample=stochastic)
        return jnp.where(mask, y_pred, jnp.nan)

    def prediction(
        self,
        x: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        *,
        stochastic: Optional[bool] = None,
    ) -> jnp.ndarray:
        """Inference path (module.py:273-278): extractor -> prior ->
        decoder(prior), i.e. predicts without future returns.

        stochastic=True reproduces the reference's sample-at-inference
        behavior (module.py:123; needs the 'sample' rng); False returns the
        distribution mean. Default comes from the config.
        """
        cfg = self.cfg
        if mask is None:
            mask = jnp.ones(x.shape[0], dtype=bool)
        if stochastic is None:
            stochastic = cfg.stochastic_inference
        latent = self.feature_extractor(x)
        pred_mu, pred_sigma = self.factor_predictor(latent, mask, train=False)
        y_pred, _ = self.factor_decoder(
            latent, pred_mu, pred_sigma, sample=stochastic
        )
        return jnp.where(mask, y_pred, jnp.nan)


class _DayForward(nn.Module):
    """Per-day forward wrapper with the train flag baked in as an attribute
    (flax's nn.vmap does not thread call kwargs, so `train` cannot be a
    kwarg of the vmapped call)."""

    cfg: ModelConfig
    train_mode: bool = False

    @nn.compact
    def __call__(self, x, returns, mask):
        return FactorVAE(self.cfg, name="model")(
            x, returns, mask, train=self.train_mode
        )


class _DayPrediction(nn.Module):
    cfg: ModelConfig
    stochastic: Optional[bool] = None

    @nn.compact
    def __call__(self, x, mask):
        return FactorVAE(self.cfg, name="model").prediction(
            x, mask, stochastic=self.stochastic
        )


def _lift(module_cls):
    """Lift a per-day wrapper over a leading day axis: parameters are
    shared across days; the 'sample' and 'dropout' rngs are split per day
    so each day draws independent noise — the vmapped equivalent of the
    reference looping days in its hot loop (train_model.py:17-32)."""
    return nn.vmap(
        module_cls,
        in_axes=0,
        out_axes=0,
        variable_axes={"params": None},
        split_rngs={"params": False, "sample": True, "dropout": True},
    )


class _FlatDayForward(nn.Module):
    """Cross-day-flattened day batch (VERDICT r2 #2). Same param tree as
    the nn.vmap lift (the inner module is named 'model' either way), so
    checkpoints and the train/eval/prediction variants stay
    interchangeable across both modes."""

    cfg: ModelConfig
    train_mode: bool = False

    @nn.compact
    def __call__(self, x, returns, mask):
        return FactorVAE(self.cfg, name="model").day_batched_forward(
            x, returns, mask, train=self.train_mode
        )


class _FlatDayPrediction(nn.Module):
    cfg: ModelConfig
    stochastic: Optional[bool] = None

    @nn.compact
    def __call__(self, x, mask):
        return FactorVAE(self.cfg, name="model").day_batched_prediction(
            x, mask, stochastic=self.stochastic
        )


def day_forward(cfg: ModelConfig, train: bool):
    """Day-batched training/eval forward: apply(params, x, y, mask) with
    leading day axis on all three. Parameters are interchangeable between
    the train/eval variants and with `day_prediction` (same inner module
    name).

    cfg.flatten_days=True (default) takes the cross-day-flattened path;
    False keeps the per-day nn.vmap lift (the pre-round-3 layout, useful
    for A/B timing — both produce identical deterministic outputs, pinned
    by tests/test_models.py::TestFlattenedDayBatch)."""
    if cfg.flatten_days:
        return _FlatDayForward(cfg, train_mode=train)
    return _lift(_DayForward)(cfg, train_mode=train)


def day_prediction(cfg: ModelConfig, stochastic: Optional[bool] = None):
    """Day-batched inference: apply(params, x, mask) -> (D, N) scores."""
    if cfg.flatten_days:
        return _FlatDayPrediction(cfg, stochastic=stochastic)
    return _lift(_DayPrediction)(cfg, stochastic=stochastic)


def load_model(config, checkpoint_path=None, n_max: int = 8):
    """Inference-model factory + optional weight restore — the analogue of
    reference utils.load_model (utils.py:57-67), which mirrors main.py's
    module assembly for the scoring path.

    `config` is a full Config (or a ModelConfig via Config(model=...)).
    Returns (model, params): the day-batched *prediction* module
    (apply(params, x, mask) -> (D, N) scores; no future returns needed)
    and either freshly initialized params or the checkpoint's weights.
    The parameter template is initialized through the full forward variant
    so the tree covers every submodule (including the posterior encoder,
    which the prediction path itself never touches) and matches saved
    training checkpoints exactly.
    """
    import jax
    import jax.numpy as jnp

    from factorvae_tpu.config import Config

    if not isinstance(config, Config):
        config = Config(model=config)
    cfg = config.model
    template_model = day_forward(cfg, train=False)
    # Trainer.init_state's key schedule (split 3): a fresh factory init is
    # bitwise the trainer's params, and no stream reuses a key (JGL002 —
    # the old path fed the SAME key to params/sample/dropout).
    key = jax.random.PRNGKey(config.train.seed)
    k_param, k_sample, k_drop = jax.random.split(key, 3)
    x = jnp.zeros((1, n_max, cfg.seq_len, cfg.num_features))
    params = template_model.init(
        {"params": k_param, "sample": k_sample, "dropout": k_drop},
        x, jnp.zeros((1, n_max)), jnp.ones((1, n_max), bool),
    )
    if checkpoint_path is not None:
        from factorvae_tpu.train.checkpoint import load_params

        params = load_params(checkpoint_path, params)
    return day_prediction(cfg), params
