"""Alpha/beta heads and the factor decoder.

Capability parity with reference module.py:69-123 (`AlphaLayer`,
`BetaLayer`, `FactorDecoder`): idiosyncratic return head (alpha), factor
exposures (beta), and the combination

    mu    = alpha_mu + beta @ factor_mu
    sigma = sqrt(alpha_sigma^2 + beta^2 @ factor_sigma^2 + 1e-6)

with the zero-sigma guard (module.py:117, a `where` here instead of the
in-place masked write) and a reparameterized sample mu + eps*sigma
(module.py:103-105,123). The reference samples even at inference; that
behavior is preserved behind ``ModelConfig.stochastic_inference``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from factorvae_tpu.config import ModelConfig
from factorvae_tpu.models.layers import Dense


class AlphaLayer(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, latent: jnp.ndarray):
        """latent: (..., N, H) -> (alpha_mu, alpha_sigma), each (..., N).

        Shape-generic on purpose: the flattened day-batched decoder feeds
        the whole (B, N, H) block through in one matmul (VERDICT r2 #2)."""
        cfg = self.cfg
        h = Dense(cfg.hidden_size, torch_init=cfg.torch_init, name="proj")(latent)
        h = nn.leaky_relu(h, negative_slope=cfg.leaky_relu_slope)   # module.py:80-81
        mu = Dense(1, torch_init=cfg.torch_init, name="mu")(h)[..., 0]
        sigma = nn.softplus(
            Dense(1, torch_init=cfg.torch_init, name="sigma")(h)
        )[..., 0]
        return mu, sigma


class BetaLayer(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, latent: jnp.ndarray) -> jnp.ndarray:
        """latent: (N, H) -> factor exposures beta (N, K)  (module.py:92-94)."""
        return Dense(
            self.cfg.num_factors, torch_init=self.cfg.torch_init, name="beta"
        )(latent)


class FactorDecoder(nn.Module):
    cfg: ModelConfig

    def setup(self):
        self.alpha_layer = AlphaLayer(self.cfg)
        self.beta_layer = BetaLayer(self.cfg)

    def distribution(self, latent, factor_mu, factor_sigma):
        """Per-stock return distribution (mu, sigma), each (..., N).

        Shape-generic: latent (..., N, H) with factors (..., K) — the
        single-day path passes (N, H)/(K,), the cross-day-flattened path
        (B, N, H)/(B, K); both share this one copy of the
        reference-pinned combine math."""
        alpha_mu, alpha_sigma = self.alpha_layer(latent)
        beta = self.beta_layer(latent)
        factor_sigma = jnp.where(factor_sigma == 0.0, 1e-6, factor_sigma)  # :117
        mu = alpha_mu + jnp.einsum("...nk,...k->...n", beta, factor_mu)    # :120
        sigma = jnp.sqrt(
            alpha_sigma**2
            + jnp.einsum("...nk,...k->...n", beta**2, factor_sigma**2)
            + 1e-6
        )                                                                  # :121
        return mu, sigma

    def __call__(self, latent, factor_mu, factor_sigma, *, sample: bool = True):
        """Returns a reparameterized sample (and the distribution).

        sample=False returns the mean as the prediction (deterministic
        inference mode; the reference always samples, module.py:123).
        """
        mu, sigma = self.distribution(latent, factor_mu, factor_sigma)
        if sample:
            eps = jax.random.normal(self.make_rng("sample"), sigma.shape)  # :103-105
            return mu + eps * sigma, (mu, sigma)
        return mu, (mu, sigma)

    def day_batched(self, latent, factor_mu, factor_sigma, *, sample: bool = True):
        """Cross-day-flattened decode (VERDICT r2 #2): latent (B, N, H),
        factor_mu/sigma (B, K) -> sample (B, N) + distribution.

        The alpha/beta heads inside `distribution` are day-independent
        per-stock Denses, so they see the whole (B, N, H) block as one
        tall matmul; only the (B, N, K) x (B, K) factor combination is
        day-local — elementwise-plus-reduction, not a launch-bound
        matmul. One (B, N) eps draw replaces the per-day split rngs
        (iid either way)."""
        mu, sigma = self.distribution(latent, factor_mu, factor_sigma)
        if sample:
            eps = jax.random.normal(self.make_rng("sample"), sigma.shape)
            return mu + eps * sigma, (mu, sigma)
        return mu, (mu, sigma)
