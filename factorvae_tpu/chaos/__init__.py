"""Deterministic, seeded fault injection for self-healing runs.

The observatory (PR 5/7) *detects* non-finite grads, divergence and torn
streams; this package exists to *exercise the recovery* those signals
should trigger, reproducibly. A `ChaosPlan` is an explicit list of
`Fault`s — each targeted by coordinates (epoch, seed lane, chunk index,
checkpoint step, ...) and bounded by a fire count — installed either
in-process (`install` / the `active` context manager) or through the
`FACTORVAE_CHAOS` env var (JSON; the subprocess path the kill-mid-save
tests use). Injection points across the stack ask `fault(kind, ...)`
and act only on a match:

    kind                 injection point            recovery exercised
    ------------------------------------------------------------------
    nan_grads            train step gradients       in-graph all-finite
                         (train/loop.py; per-seed   gate skips the
                         lanes in fleets)           update; host
                                                    rollback + lr backoff
    kill_mid_save        Checkpointer.save, after   atomic step commit +
                         the write is enqueued      manifest verify +
                         (SIGKILL-hard)             group-resume rewind
    corrupt_checkpoint   host-side byte flips       sha256 manifest ->
    corrupt_artifact     (ops.corrupt_file /        quarantine, restore
                         corrupt_checkpoint_step)   falls back
    torn_jsonl           ops.tear_jsonl             obs.timeline/report
                                                    torn-tail tolerance
    stream_fail          ChunkStream._produce       bounded exponential-
    stream_stall         (worker thread)            backoff retry
    serve_cold_fail      registry tombstone         cold-start retry +
                         cold-start reload          backoff window
    serve_stall          registry.score             per-request deadline
                                                    + circuit breaker
    serve_malformed      (no hook needed: the       {"ok": false}
                         bench/tests feed garbage)  responses

Walk-forward cycle-stage coordinates (factorvae_tpu/wf, ISSUE 14) —
each stage of the nightly append->judge->refit->promote->verify loop
gets its own fault class, timed by the MTTR harness (bench --chaos):

    kill_mid_append      data/append.py, step=0     orphan-slab overwrite
                         before the slab write or   + idempotent append
                         step=1 between slab and    re-run off the cycle
                         manifest commit (SIGKILL)  journal
    corrupt_append_slab  data/append.py, after the  sha256 validation
                         slab lands, before the     BEFORE manifest
                         manifest commit            commit: append aborts,
                                                    store untouched, retry
    kill_mid_refit       wf/operator.py, step=0     journaled refit stage
                         before the refit fit or    re-runs; candidate
                         step=1 after it, before    checkpoints resume the
                         the journal commit         fit bitwise
    kill_between_admit_  serve/daemon.admit, after  promote stage re-runs:
    and_drain            candidate admission +      re-admission is
                         gate verdict, before the   idempotent, the alias
                         alias flip / incumbent     still points at the
                         drain                      incumbent (serving
                                                    never stopped)
    fidelity_gate_reject serve/daemon.admit forces  candidate retired +
                         the gate verdict to        logged; incumbent
                         reject                     keeps serving

Serving scale-out fault class (serve/pool.py, ISSUE 15):

    kill_worker          WorkerPool watcher tick    router reroutes the
                         (request=worker index):    worker's sticky
                         the worker process is      models to surviving
                         SIGKILLed mid-tick         workers; the pool
                                                    respawns it from the
                                                    shared AOT store +
                                                    compile cache (zero
                                                    recompiles) and
                                                    replays fan-out
                                                    admits

Multi-host serving fault class (serve/pool.py remote slots, ISSUE 17):

    kill_remote_worker   WorkerPool watcher tick    router reroutes; the
                         (request=worker index):    pool respawns the
                         a REMOTE worker's agent    agent, which re-joins
                         process is SIGKILLed       through the full cold
                         (the simulated host dies)  path — artifact
                                                    downloads off the
                                                    content-addressed
                                                    store, digest verify,
                                                    re-registration on
                                                    the same host:port

Opt-in and zero-cost when off: with no plan installed and no env var,
`fault()` is a None check — no allocation, no locking, no jax import —
and every in-graph injection is gated at TRACE time (`has_fault`), so
the compiled programs of a chaos-free run are byte-identical to a
pre-chaos build (pinned in tests/test_chaos.py).

Determinism: faults fire on exact coordinate matches, `times` bounds
how often (the consumption is what lets a retry/rollback find clean
ground — exactly how transient real-world faults behave), and byte
corruption draws from `numpy.default_rng(fault.rng_seed)`. Two runs of
the same plan against the same workload inject identically.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
from typing import Iterator, List, Optional, Sequence

from factorvae_tpu.chaos import ops  # noqa: F401  (re-export: chaos.ops)

KINDS = (
    "nan_grads",
    "kill_mid_save",
    "corrupt_checkpoint",
    "corrupt_artifact",
    "torn_jsonl",
    "stream_fail",
    "stream_stall",
    "serve_cold_fail",
    "serve_stall",
    "serve_malformed",
    # walk-forward cycle-stage classes (factorvae_tpu/wf)
    "kill_mid_append",
    "corrupt_append_slab",
    "kill_mid_refit",
    "kill_between_admit_and_drain",
    "fidelity_gate_reject",
    # serving scale-out class (serve/pool.py, ISSUE 15)
    "kill_worker",
    # multi-host serving class (serve/pool.py remote slots, ISSUE 17)
    "kill_remote_worker",
)

# Coordinate fields a Fault can pin (-1 / "" = wildcard, matches any).
_COORDS = ("epoch", "step", "lane", "chunk", "request")


@dataclasses.dataclass
class Fault:
    """One injected fault. Coordinates default to wildcard; `times`
    bounds how many matching queries fire (-1 = every match — a
    permanent fault; the default 1 is a transient)."""

    kind: str
    epoch: int = -1
    step: int = -1
    lane: int = -1           # fleet seed lane (-1 = all lanes)
    chunk: int = -1          # ChunkStream chunk index
    request: int = -1        # serve request index
    times: int = 1
    delay_s: float = 0.0     # stall faults: injected latency
    rng_seed: int = 0        # corruption determinism
    path: str = ""           # corruption target (informational)

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown chaos fault kind {self.kind!r}; "
                f"choose from {KINDS}")

    def matches(self, coords: dict) -> bool:
        """A pinned coordinate must be PRESENT in the query and equal:
        a fault pinned to lane=2 must not fire at an injection point
        that has no lane (the serial trainer), and a fault pinned to a
        coordinate no injection point supplies simply never fires —
        pins narrow, they never widen."""
        for k in _COORDS:
            pin = getattr(self, k)
            if pin == -1:
                continue
            if k not in coords or int(coords[k]) != int(pin):
                return False
        return True


class ChaosPlan:
    """A seeded list of faults plus their consumption state. `find` is
    thread-safe (stream workers and the serve dispatch pool query from
    their own threads) and CONSUMES one firing per match, so the plan's
    injection history (`fired`) is itself a deterministic artifact.
    The plan lock is a LEAF in the project's lock order (injection
    points call `fault()` while holding their subsystem's lock —
    registry, stream — so `find` must never acquire one back;
    analysis/sanitize.py verifies the composed graph stays acyclic)."""

    def __init__(self, faults: Sequence[Fault], seed: int = 0):
        self.faults: List[Fault] = list(faults)
        self.seed = int(seed)
        self._remaining = [f.times for f in self.faults]
        self.fired: List[dict] = []
        self._lock = threading.Lock()

    # ---- query -----------------------------------------------------------

    def find(self, kind: str, **coords) -> Optional[Fault]:
        """First live fault of `kind` matching `coords`, consuming one
        firing; None otherwise."""
        with self._lock:
            for i, f in enumerate(self.faults):
                if f.kind != kind or self._remaining[i] == 0:
                    continue
                if not f.matches(coords):
                    continue
                if self._remaining[i] > 0:
                    self._remaining[i] -= 1
                self.fired.append({"kind": kind, **coords})
                return f
        return None

    def has(self, kind: str) -> bool:
        """Non-consuming: is any fault of `kind` installed (live or
        spent)? Trace-time gates key on this so the compiled program is
        stable for the whole run, not per-epoch."""
        return any(f.kind == kind for f in self.faults)

    # ---- (de)serialization ----------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "faults": [dataclasses.asdict(f) for f in self.faults],
        })

    @classmethod
    def from_json(cls, blob: str) -> "ChaosPlan":
        d = json.loads(blob)
        return cls([Fault(**f) for f in d.get("faults", [])],
                   seed=int(d.get("seed", 0)))


# ---------------------------------------------------------------------------
# process-wide registry (the zero-cost-off gate)

ENV_VAR = "FACTORVAE_CHAOS"

_PLAN: Optional[ChaosPlan] = None
_ENV_CHECKED = False


def install(plan: Optional[ChaosPlan]) -> Optional[ChaosPlan]:
    """Install the process-wide chaos plan (None = off); returns the
    previous plan so callers can restore it."""
    global _PLAN, _ENV_CHECKED
    prev = _PLAN
    _PLAN = plan
    _ENV_CHECKED = True   # an explicit install wins over the env var
    return prev


def current_plan() -> Optional[ChaosPlan]:
    """The installed plan, checking FACTORVAE_CHAOS once lazily (the
    subprocess activation path: a child that never queries never pays
    even the env read)."""
    global _PLAN, _ENV_CHECKED
    if _PLAN is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        blob = os.environ.get(ENV_VAR)
        if blob:
            _PLAN = ChaosPlan.from_json(blob)
    return _PLAN


def fault(kind: str, **coords) -> Optional[Fault]:
    """The injection-point query: None unless a live matching fault is
    installed. With chaos off this is a None check."""
    plan = _PLAN if _ENV_CHECKED else current_plan()
    return None if plan is None else plan.find(kind, **coords)


def has_fault(kind: str) -> bool:
    """Non-consuming trace-time gate (see ChaosPlan.has)."""
    plan = _PLAN if _ENV_CHECKED else current_plan()
    return plan is not None and plan.has(kind)


@contextlib.contextmanager
def active(plan: ChaosPlan) -> Iterator[ChaosPlan]:
    """Scoped install for tests/bench: restores the previous plan (and
    re-arms the env check) on exit."""
    global _ENV_CHECKED
    prev_checked = _ENV_CHECKED
    prev = install(plan)
    try:
        yield plan
    finally:
        install(prev)
        _ENV_CHECKED = prev_checked


def child_env(plan: ChaosPlan, env: Optional[dict] = None) -> dict:
    """Environment dict for a subprocess that should run under `plan`
    (the kill-mid-save harness: the fault must fire in the CHILD)."""
    out = dict(os.environ if env is None else env)
    out[ENV_VAR] = plan.to_json()
    return out
