"""Host-side fault operators: deterministic byte corruption, torn JSONL
tails, and the hard-kill the kill-mid-save harness uses.

These are the DESTRUCTIVE half of the chaos harness — pure host file
operations, no jax — used by tests and `bench.py --chaos` to create the
on-disk states the recovery machinery (checkpoint manifests/quarantine,
obs torn-tail tolerance) must survive. Every operator is seeded and
returns what it did, so a failing recovery test can print the exact
bytes it flipped.
"""

from __future__ import annotations

import os
import signal
from typing import List, Optional

import numpy as np


def corrupt_file(path: str, rng_seed: int = 0, n_bytes: int = 16) -> List[int]:
    """Flip `n_bytes` deterministically-chosen bytes of `path` in place
    (XOR 0xFF — never a no-op flip). Returns the corrupted offsets."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {path}")
    rng = np.random.default_rng(rng_seed)
    offsets = sorted(set(
        int(o) for o in rng.integers(0, size, size=min(n_bytes, size))))
    with open(path, "r+b") as fh:
        for off in offsets:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0xFF]))
    return offsets


def _payload_files(step_dir: str) -> List[str]:
    """Data-carrying files of a committed checkpoint step, largest
    first (corrupting metadata-only sidecars would miss the arrays the
    integrity story is about)."""
    out = []
    for root, _, names in os.walk(step_dir):
        for n in names:
            if n == "manifest.json":
                continue
            p = os.path.join(root, n)
            if os.path.getsize(p) > 0:
                out.append(p)
    return sorted(out, key=os.path.getsize, reverse=True)


def corrupt_checkpoint_step(directory: str, step: int,
                            rng_seed: int = 0,
                            n_bytes: int = 16) -> str:
    """Corrupt the largest payload file of one committed step directory
    (the orbax layout `directory/step/...`). Returns the file hit."""
    step_dir = os.path.join(os.path.abspath(directory), str(step))
    files = _payload_files(step_dir)
    if not files:
        raise FileNotFoundError(
            f"no payload files under {step_dir} — is step {step} "
            f"committed?")
    corrupt_file(files[0], rng_seed=rng_seed, n_bytes=n_bytes)
    return files[0]


def tear_jsonl(path: str, keep_frac: float = 0.6,
               rng_seed: int = 0) -> int:
    """Tear a JSONL stream the way an async kill does: truncate the
    file MID-LINE, leaving a partial record as the new tail. The cut
    point is a seeded draw inside the final kept line. Returns the new
    byte size."""
    with open(path, "rb") as fh:
        data = fh.read()
    lines = data.splitlines(keepends=True)
    if not lines:
        raise ValueError(f"cannot tear empty stream {path}")
    keep = max(1, int(len(lines) * keep_frac))
    head = b"".join(lines[:keep - 1])
    last = lines[keep - 1]
    rng = np.random.default_rng(rng_seed)
    # cut strictly inside the line body: at least 1 byte survives, at
    # least the newline (and one byte) is lost — a genuine torn record
    cut = int(rng.integers(1, max(2, len(last) - 1)))
    with open(path, "wb") as fh:
        fh.write(head + last[:cut])
    return len(head) + cut


def kill_now(sig: Optional[int] = None) -> None:
    """Hard-kill this process (default SIGKILL): no atexit, no flushed
    buffers, no orbax finalize — the crash the checkpoint commit
    machinery must make survivable."""
    os.kill(os.getpid(), signal.SIGKILL if sig is None else sig)
