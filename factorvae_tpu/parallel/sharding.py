"""Partition specs and sharding helpers for the FactorVAE training step.

Since PR 6 every placement here is DERIVED from the named-regex
partition-rule tables in `parallel/partition.py` (the one sharding
story); these helpers survive as the thin mesh-bound conveniences the
Trainer/bench paths call. Layout summary (see mesh.py for the axes):

    panel values (N, D, C+1)   -> P('stock', None, None)   HBM-resident shards
    fill maps    (D, N)        -> P(None, 'stock')
    day order    (S, B)        -> P(None, 'data')
    batch x      (B, N, T, C)  -> P('data', 'stock')
    batch y/mask (B, N)        -> P('data', 'stock')
    params / opt state         -> replicated P()

GSPMD then inserts the collectives: gradient all-reduce over 'data'
(day-level data parallelism) and max/sum reductions over 'stock' for the
masked softmaxes (module.py:38,57,146 semantics) and the portfolio matmul
(module.py:64). Stacked fleet states lay their seed axis over 'data'
instead (partition.FLEET_STATE_RULES); see docs/sharding.md.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from factorvae_tpu.parallel.mesh import STOCK_AXIS, batch_axes
from factorvae_tpu.parallel.partition import (
    order_partition_spec,
    panel_partition_specs,
)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def panel_shardings(mesh: Mesh) -> Tuple[NamedSharding, NamedSharding, NamedSharding]:
    """(values, last_valid, next_valid) placements — the PANEL_RULES
    table bound to this mesh."""
    return tuple(
        NamedSharding(mesh, s) for s in panel_partition_specs()
    )


def order_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, order_partition_spec(mesh))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(batch_axes(mesh), STOCK_AXIS))


def make_batch_constraint(mesh: Mesh) -> Callable:
    """Constraint applied inside the jitted step right after the day-batch
    gather, pinning the (B, N, ...) layout so GSPMD doesn't re-replicate
    the batch. On a hierarchical ('host','data','stock') mesh the B axis
    shards over BOTH batch axes, so the gradient all-reduce groups span
    hosts (DCN) while the 'stock' groups stay within one host (ICI)."""
    b = batch_axes(mesh)
    x_s = NamedSharding(mesh, P(b, STOCK_AXIS, None, None))
    v_s = NamedSharding(mesh, P(b, STOCK_AXIS))

    def constrain(x, y, mask):
        return (
            jax.lax.with_sharding_constraint(x, x_s),
            jax.lax.with_sharding_constraint(y, v_s),
            jax.lax.with_sharding_constraint(mask, v_s),
        )

    return constrain


def shard_dataset(mesh: Mesh, dataset) -> None:
    """Re-place a PanelDataset's device arrays onto the mesh in-place.

    Goes through multihost.global_put so a mesh spanning several
    processes (a pod slice) works identically: every process holds the
    same host panel and materializes its addressable shards.

    Stream-resident datasets (panel_residency='stream') round-trip
    CLEANLY: the panel is host-pinned numpy by design and never holds a
    device array to re-place — the stream path ships each prefetched
    mini-panel chunk pre-sharded instead (data/stream.py placement,
    built from the SAME panel rule table), so this is a documented
    no-op, not a mid-run AttributeError.
    """
    if getattr(dataset, "residency", "hbm") == "stream":
        return
    from factorvae_tpu.parallel.multihost import global_put

    v_s, lv_s, nv_s = panel_shardings(mesh)
    dataset.values = global_put(dataset.values, v_s)
    dataset.last_valid = global_put(dataset.last_valid, lv_s)
    dataset.next_valid = global_put(dataset.next_valid, nv_s)


def chunk_placement(mesh: Mesh, stacked: bool = False,
                    order_spec=None) -> Callable:
    """Placement function for ChunkStream under a mesh: device_put each
    prefetched chunk `(order_local, (values, last_valid, next_valid))`
    with its rule-table sharding, so each host ships only its
    addressable slice of the mini-panel (multihost.global_put) instead
    of a full replicated copy per chunk.

    `stacked=True` is the fleet-stream layout: per-seed mini-panel
    stacks (S, ...) whose leading axis rides the seed ('data') axis and
    per-seed local orders (S, k, B). `order_spec` overrides the order
    placement — the fleet's SHARED validation chunks pair a broadcast
    mini-panel with the stacked eval-order spec
    (partition.eval_order_partition_spec)."""
    from factorvae_tpu.parallel.multihost import global_put

    pan = tuple(NamedSharding(mesh, s)
                for s in panel_partition_specs(stacked=stacked))
    ord_s = NamedSharding(
        mesh, order_spec if order_spec is not None
        else order_partition_spec(mesh, stacked=stacked))

    def place(chunk):
        order_local, panel = chunk
        return (
            global_put(order_local, ord_s),
            tuple(global_put(a, s) for a, s in zip(panel, pan)),
        )

    return place
