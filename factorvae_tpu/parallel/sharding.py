"""Partition specs and sharding helpers for the FactorVAE training step.

Layout summary (see mesh.py for the axes):

    panel values (N, D, C+1)   -> P('stock', None, None)   HBM-resident shards
    fill maps    (D, N)        -> P(None, 'stock')
    day order    (S, B)        -> P(None, 'data')
    batch x      (B, N, T, C)  -> P('data', 'stock')
    batch y/mask (B, N)        -> P('data', 'stock')
    params / opt state         -> replicated P()

GSPMD then inserts the collectives: gradient all-reduce over 'data'
(day-level data parallelism) and max/sum reductions over 'stock' for the
masked softmaxes (module.py:38,57,146 semantics) and the portfolio matmul
(module.py:64).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from factorvae_tpu.parallel.mesh import STOCK_AXIS, batch_axes


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def panel_shardings(mesh: Mesh) -> Tuple[NamedSharding, NamedSharding, NamedSharding]:
    """(values, last_valid, next_valid) placements."""
    return (
        NamedSharding(mesh, P(STOCK_AXIS, None, None)),
        NamedSharding(mesh, P(None, STOCK_AXIS)),
        NamedSharding(mesh, P(None, STOCK_AXIS)),
    )


def order_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(None, batch_axes(mesh)))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(batch_axes(mesh), STOCK_AXIS))


def make_batch_constraint(mesh: Mesh) -> Callable:
    """Constraint applied inside the jitted step right after the day-batch
    gather, pinning the (B, N, ...) layout so GSPMD doesn't re-replicate
    the batch. On a hierarchical ('host','data','stock') mesh the B axis
    shards over BOTH batch axes, so the gradient all-reduce groups span
    hosts (DCN) while the 'stock' groups stay within one host (ICI)."""
    b = batch_axes(mesh)
    x_s = NamedSharding(mesh, P(b, STOCK_AXIS, None, None))
    v_s = NamedSharding(mesh, P(b, STOCK_AXIS))

    def constrain(x, y, mask):
        return (
            jax.lax.with_sharding_constraint(x, x_s),
            jax.lax.with_sharding_constraint(y, v_s),
            jax.lax.with_sharding_constraint(mask, v_s),
        )

    return constrain


def shard_dataset(mesh: Mesh, dataset) -> None:
    """Re-place a PanelDataset's device arrays onto the mesh in-place.

    Goes through multihost.global_put so a mesh spanning several
    processes (a pod slice) works identically: every process holds the
    same host panel and materializes its addressable shards."""
    from factorvae_tpu.parallel.multihost import global_put

    v_s, lv_s, nv_s = panel_shardings(mesh)
    dataset.values = global_put(dataset.values, v_s)
    dataset.last_valid = global_put(dataset.last_valid, lv_s)
    dataset.next_valid = global_put(dataset.next_valid, nv_s)
