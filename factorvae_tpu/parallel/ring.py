"""Ring attention over the sharded stock cross-section.

For this model family the "long axis" is the stock universe, not time
(SURVEY.md §5: T=20-60 while N reaches ~800 on CSI800 and beyond on
bigger universes), so the ring/context-parallel treatment applies to the
cross-section: shard the N stocks over a mesh axis and compute the
FactorPredictor's K-head attention (reference module.py:140-153
semantics: scaled scores -> ReLU -> softmax over stocks -> weighted
values) without ever gathering the full cross-section on one device.

Mechanics (flash-attention-style online softmax around the ring):
each device holds its local (n_local, H) key/value/mask chunk; the K
query vectors are replicated. At every ring step a device computes the
partial scores against its current chunk, folds them into running
(max, denominator, weighted-accumulator) statistics with the usual
rescaling, and passes the chunk to its ring neighbour via
`lax.ppermute`. After `ring_size` steps every device holds the exact
(K, H) context — identical (up to fp reassociation) to the dense masked
softmax, which is what the test asserts.

At CSI-scale N this is a teaching/validation path (one chip holds the
whole cross-section easily); it becomes the real mechanism when the
universe or feature width outgrows a single chip's HBM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def ring_cross_section_attention(
    query: jnp.ndarray,       # (K, H) replicated
    key_local: jnp.ndarray,   # (n_local, H) shared or (K, n_local, H) per-head
    value_local: jnp.ndarray, # same leading shape as key_local
    mask_local: jnp.ndarray,  # (n_local,) bool
    axis_name: str,
    relu_scores: bool = True,
    scale: float | None = None,
    guard_nonfinite: bool = False,
) -> jnp.ndarray:
    """Exact masked softmax attention over the ring; returns (K, H).

    relu_scores=True keeps the reference's quirky ReLU-before-softmax
    (module.py:145); scale defaults to 1/sqrt(H + 1e-6) (module.py:142).

    2-D key/value chunks are one set shared by every query head; 3-D
    (K, n_local, H) chunks are per-head keys/values — the real
    FactorPredictor's layout (each reference AttentionLayer has its own
    key/value Linears, module.py:131-137).

    guard_nonfinite=True reproduces the reference's per-head NaN/Inf
    guard (module.py:149-150, same keying as models/predictor.py): a head
    with any non-finite score over the valid cross-section yields a zero
    context. The flag is tracked through the online-softmax fold, so the
    guard is exact even though each device only ever sees one chunk of
    scores at a time.
    """
    k_heads, h_dim = query.shape
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.float32(h_dim) + 1e-6)
    per_head = key_local.ndim == 3
    ring_size = lax.psum(1, axis_name)
    right = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    def scores_for(chunk_k, chunk_mask):
        if per_head:
            s = jnp.einsum("kh,knh->kn", query, chunk_k) * scale
        else:
            s = (query @ chunk_k.T) * scale                  # (K, n_local)
        if relu_scores:
            s = jnp.maximum(s, 0.0)
        return jnp.where(chunk_mask[None, :], s, _NEG_INF)

    def fold(stats, ck, cv, cm):
        m, l, acc, bad = stats
        s = scores_for(ck, cm)                               # (K, n)
        # masked-off positions hold the finite _NEG_INF sentinel, so any
        # non-finite entry here came from a *valid* stock's score
        bad = bad | jnp.any(~jnp.isfinite(s), axis=-1)
        # masked rows of the value chunk may be NaN (padded stocks); they
        # get weight 0 below, but 0 * NaN would still poison the
        # accumulator (same hazard the dense path neutralizes with
        # nan_to_num, models/predictor.py)
        cv = jnp.where((cm[None, :, None] if per_head else cm[:, None]), cv, 0.0)
        chunk_max = jnp.max(s, axis=-1)                      # (K,)
        m_new = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - m_new)                            # rescale old stats
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(cm[None, :], p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        if per_head:
            acc_new = acc * corr[:, None] + jnp.einsum("kn,knh->kh", p, cv)
        else:
            acc_new = acc * corr[:, None] + p @ cv           # (K, H)
        return (m_new, l_new, acc_new, bad)

    def body(carry, _):
        (ck, cv, cm), stats = carry
        stats = fold(stats, ck, cv, cm)
        ck = lax.ppermute(ck, axis_name, right)
        cv = lax.ppermute(cv, axis_name, right)
        cm = lax.ppermute(cm, axis_name, right)
        return ((ck, cv, cm), stats), None

    m0 = jnp.full((k_heads,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((k_heads,), jnp.float32)
    acc0 = jnp.zeros((k_heads, h_dim), jnp.float32)
    bad0 = jnp.zeros((k_heads,), bool)
    init = ((key_local, value_local, mask_local), (m0, l0, acc0, bad0))
    # rotate only between folds: R-1 fold+rotate steps, final fold outside
    ((ck, cv, cm), stats), _ = lax.scan(body, init, None, length=ring_size - 1)
    m, l, acc, bad = fold(stats, ck, cv, cm)
    # fully-masked cross-section -> zero context (reference NaN-guard
    # semantics, module.py:149-150); non-finite scores likewise when the
    # guard is on
    safe = l > 0
    if guard_nonfinite:
        safe = safe & ~bad
    return jnp.where(safe[:, None], acc / jnp.where(safe, l, 1.0)[:, None], 0.0)


def predictor_prior_ring(
    params, latent, mask, mesh, axis_name: str = "stock", cfg=None
):
    """The REAL FactorPredictor prior (mu_prior, sigma_prior) computed
    context-parallel: the cross-section is sharded over `axis_name`,
    each device builds only its LOCAL (K, n_local, H) key/value chunks
    from its latent shard, and ring attention assembles the exact (K, H)
    contexts without ever gathering the full cross-section — the
    explicit-collectives counterpart of models/predictor.py's dense
    einsum path (dropout-off semantics; equality is asserted by
    tests/test_collectives.py::TestRingAttention). The shared head MLP
    (module.py:181-187) then runs replicated, including the per-head
    non-finite-score zero-context guard (module.py:149-150).

    `params` is a FactorPredictor variable tree (or its 'params' leaf);
    `cfg` an optional ModelConfig supplying `leaky_relu_slope` (defaults
    to the torch default 0.01 the reference uses).
    """
    from jax.sharding import PartitionSpec as P

    from factorvae_tpu.parallel.compat import shard_map

    slope = cfg.leaky_relu_slope if cfg is not None else 0.01
    p = params.get("params", params)
    query = p["query"].astype(jnp.float32)
    w_key, b_key = p["key_kernel"], p["key_bias"]
    w_val, b_val = p["value_kernel"], p["value_bias"]

    def local(lat_l, mask_l):
        keys = jnp.einsum("nh,khj->knj", lat_l, w_key) + b_key[:, None, :]
        vals = jnp.einsum("nh,khj->knj", lat_l, w_val) + b_val[:, None, :]
        ctx = ring_cross_section_attention(
            query, keys, vals, mask_l, axis_name, guard_nonfinite=True)
        return ctx

    ctx = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name)),
        out_specs=P(),                      # replicated (K, H) context
        check_vma=False,
    )(latent.astype(jnp.float32), mask)

    def dense(name, x):
        d = p[name]["Dense_0"]
        return x @ d["kernel"] + d["bias"]

    h = jax.nn.leaky_relu(dense("proj", ctx), negative_slope=slope)
    mu = dense("mu", h)[:, 0]
    sigma = jax.nn.softplus(dense("sigma", h))[:, 0]
    return mu, sigma
