"""Explicit-collective implementations of the cross-stock reductions.

The GSPMD path (sharding.py) lets XLA insert collectives automatically.
These are the same ops written *explicitly* against a named mesh axis for
use under `jax.shard_map` — the framework's hand-built distributed
communication layer (the TPU-native analogue of a NCCL allreduce library;
the reference has no distributed layer at all, SURVEY.md §2.3). They ride
ICI within a slice and DCN across slices, as laid out by the mesh.

Every cross-stock reduction in the model family is covered:
  - `pmax_masked_softmax` — the stock-axis softmaxes (reference
    module.py:38,57,146): global max via `lax.pmax`, global denominator
    via `lax.psum`.
  - `psum_matvec` — the portfolio aggregation W^T y (module.py:64):
    shard-local partial products, `lax.psum` across shards.
  - `psum_masked_mean` — masked loss means over the sharded cross-section.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def pmax_masked_softmax(
    x: jnp.ndarray, mask: jnp.ndarray, axis_name: str, axis: int = 0
) -> jnp.ndarray:
    """Masked softmax over an axis that is sharded across `axis_name`.

    x, mask are the shard-local slices; the result equals the unsharded
    `ops.masked.masked_softmax` on the gathered array.
    """
    mask = jnp.broadcast_to(mask, x.shape)
    x = jnp.where(mask, x, _NEG_INF)
    local_max = jnp.max(x, axis=axis, keepdims=True)
    global_max = lax.pmax(local_max, axis_name)
    ex = jnp.where(mask, jnp.exp(x - global_max), 0.0)
    local_denom = jnp.sum(ex, axis=axis, keepdims=True)
    denom = lax.psum(local_denom, axis_name)
    return jnp.where(denom > 0, ex / jnp.where(denom > 0, denom, 1.0), 0.0)


def psum_matvec(
    weights: jnp.ndarray, vec: jnp.ndarray, axis_name: str
) -> jnp.ndarray:
    """(N_local, M)^T @ (N_local,) summed over all shards -> (M,) replicated.

    The distributed portfolio-return reduction (module.py:64 semantics)."""
    partial = weights.T @ vec
    return lax.psum(partial, axis_name)


def psum_masked_mean(
    x: jnp.ndarray, mask: jnp.ndarray, axis_name: str
) -> jnp.ndarray:
    """Masked mean over a fully sharded array -> replicated scalar."""
    mask = jnp.broadcast_to(mask, x.shape)
    total = lax.psum(jnp.sum(jnp.where(mask, x, 0.0)), axis_name)
    count = lax.psum(jnp.sum(mask.astype(x.dtype)), axis_name)
    return jnp.where(count > 0, total / jnp.maximum(count, 1.0), 0.0)


def psum_masked_mse(
    pred: jnp.ndarray, target: jnp.ndarray, mask: jnp.ndarray, axis_name: str
) -> jnp.ndarray:
    return psum_masked_mean((pred - target) ** 2, mask, axis_name)


def all_gather_stocks(x: jnp.ndarray, axis_name: str, axis: int = 0) -> jnp.ndarray:
    """Gather the sharded stock axis (e.g. to export full cross-section
    scores from a sharded prediction step)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)
