"""One validator for the mesh x fleet x stream composition matrix.

Before PR 6 each pairwise combination was policed in a different place
with a different message — `cli.py` rejected --mesh with --fleet_seeds,
the Trainer raised on stream + mesh, and a measured stream plan row was
silently overridden under --mesh. Those rejections are gone: the axes
compose (partition.py). What remains are genuine shape constraints —
divisibility of the sharded dimensions — and THIS module is the single
place they are stated, with one error-message format, so every caller
(CLI, Trainer, FleetTrainer, bench, autotune) fails identically and the
matrix is unit-testable in one place (tests/test_parallel.py).

Composition matrix (docs/sharding.md):

    axes enabled            constraint
    --------------------    ------------------------------------------
    mesh (serial)           days_per_step % data_parallel_size == 0
    mesh x fleet            num_seeds % mesh['data'] == 0 (seed lanes)
    mesh x stream           none beyond the serial-mesh constraint
    mesh x fleet x stream   the fleet constraint
    fleet / stream alone    none (validated by their own constructors)
"""

from __future__ import annotations

from typing import Optional

from factorvae_tpu.parallel.mesh import DATA_AXIS, data_parallel_size
from factorvae_tpu.parallel.partition import (
    SEED_AXIS,
    day_batch_axes,
    seed_parallel_size,
)


class CompositionError(ValueError):
    """Invalid mesh x fleet x stream composition (one message format:
    'invalid parallel composition [<axes>]: <detail>')."""


def _fail(axes: str, detail: str) -> None:
    raise CompositionError(
        f"invalid parallel composition [{axes}]: {detail}")


def mesh_shape_candidates(n_devices: int) -> list:
    """(data, stock) factorizations of `n_devices`, plus the
    single-device (1, 1) baseline — ONE enumeration shared by
    `bench.py --mesh` and `autotune_plan.py --mesh` so the two grids
    can never drift apart."""
    shapes = [(1, 1)]
    for sp in range(1, n_devices + 1):
        if n_devices % sp == 0:
            dp = n_devices // sp
            if (dp, sp) not in shapes:
                shapes.append((dp, sp))
    return shapes


def compatible_days_per_step(days_per_step: int, data_parallel: int) -> int:
    """Smallest days_per_step >= the requested one that the serial
    day-dp constraint accepts (days_per_step % dp == 0) — the ONE
    scaling rule the mesh bench/race apply to serial cells. Changing a
    run's dps changes its gradient-averaging semantics, so callers must
    REPORT the scaled value (and persist it next to any mesh winner it
    produced — plan rows carry it in the mesh block)."""
    dps = max(1, int(days_per_step))
    dp = max(1, int(data_parallel))
    if dps % dp:
        return dp * dps
    return dps


def validate(
    mesh: Optional[object] = None,
    num_seeds: int = 1,
    residency: str = "hbm",
    days_per_step: int = 1,
    stream_chunk_days: int = 32,
    hyper: bool = False,
) -> None:
    """Raise CompositionError if the requested axis composition cannot
    ship; a silent pass means Trainer/FleetTrainer/ChunkStream will
    compose these axes in one program.

    ``hyper=True`` labels the lane axis as a hyper-fleet CONFIG axis
    (ISSUE 12): the constraint is the same — lanes ride '{SEED_AXIS}'
    — but the one-line error names the hyper grid, so a grid whose lane
    count doesn't divide the mesh fails at construction (CLI exit 2)
    instead of as a mid-fit stacking error."""
    if residency not in ("hbm", "stream"):
        _fail("stream", f"panel_residency must be 'hbm' or 'stream'; "
                        f"got {residency!r}")
    if num_seeds < 1:
        _fail("fleet", f"need at least one seed; got {num_seeds}")
    if residency == "stream" and stream_chunk_days < 1:
        _fail("stream", f"stream_chunk_days must be >= 1; "
                        f"got {stream_chunk_days}")
    if mesh is None:
        return
    if num_seeds == 1:
        # Serial runs: day-level data parallelism over the batch axes —
        # every device must take an equal slice of each update's days.
        dp = data_parallel_size(mesh)
        if days_per_step % dp:
            _fail(
                "mesh",
                f"days_per_step={days_per_step} not divisible by the "
                f"data-parallel size {dp} (mesh "
                f"{dict(mesh.shape)}); raise days_per_step or shrink "
                f"the '{DATA_AXIS}' axis",
            )
        return
    # Fleet runs: seed (or hyper-config) lanes ride SEED_AXIS ('data');
    # day-batches shard over the 'host' axis when the mesh has one.
    axes = "mesh x hyper" if hyper else "mesh x fleet"
    lanes = "config lanes" if hyper else "seeds"
    seed_ways = seed_parallel_size(mesh)
    if num_seeds % seed_ways:
        _fail(
            axes,
            f"{'hyper grid' if hyper else 'fleet'} of {num_seeds} "
            f"{lanes} not divisible by the "
            f"'{SEED_AXIS}' mesh axis ({seed_ways} lanes; mesh "
            f"{dict(mesh.shape)}); pick a lane count that is a "
            f"multiple of {seed_ways} or reshape the mesh",
        )
    day = day_batch_axes(mesh, stacked=True)
    if day:
        dp = int(mesh.shape[day[0]])
        if days_per_step % dp:
            _fail(
                axes,
                f"days_per_step={days_per_step} not divisible by the "
                f"'{day[0]}' axis ({dp}) that day-batches shard over "
                f"on a hierarchical mesh (mesh {dict(mesh.shape)})",
            )
