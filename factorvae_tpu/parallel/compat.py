"""Version-tolerant `shard_map` (ISSUE 1 satellite).

`jax.shard_map` became a public API in jax 0.6 (with the `check_vma=`
keyword); earlier releases — including the sandbox's 0.4.x — only ship
`jax.experimental.shard_map.shard_map` with the equivalent keyword
spelled `check_rep=`. Every call site in this package (and the tests /
examples) goes through this wrapper so both spellings work unchanged.
"""

from __future__ import annotations

from typing import Any, Optional

try:  # jax >= 0.6: public API
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental API, check_rep= keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh: Any, in_specs: Any, out_specs: Any,
              check_vma: Optional[bool] = None):
    """`jax.shard_map` with the keyword signature of the public (>=0.6)
    API; `check_vma` maps to `check_rep` on older releases. Leave it
    None to take the jax default."""
    kw = {} if check_vma is None else {_CHECK_KW: check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
