from factorvae_tpu.parallel.compat import shard_map
from factorvae_tpu.parallel.mesh import (
    DATA_AXIS,
    HOST_AXIS,
    STOCK_AXIS,
    batch_axes,
    data_parallel_size,
    make_hierarchical_mesh,
    make_mesh,
    single_device_mesh,
)
from factorvae_tpu.parallel.multihost import (
    in_multihost_env,
    maybe_initialize,
    process_info,
)
from factorvae_tpu.parallel.ring import ring_cross_section_attention
from factorvae_tpu.parallel.sharding import (
    batch_sharding,
    make_batch_constraint,
    order_sharding,
    panel_shardings,
    replicated,
    shard_dataset,
)

__all__ = [
    "DATA_AXIS",
    "HOST_AXIS",
    "STOCK_AXIS",
    "batch_axes",
    "batch_sharding",
    "data_parallel_size",
    "make_hierarchical_mesh",
    "in_multihost_env",
    "make_batch_constraint",
    "make_mesh",
    "maybe_initialize",
    "process_info",
    "order_sharding",
    "panel_shardings",
    "replicated",
    "ring_cross_section_attention",
    "shard_dataset",
    "shard_map",
    "single_device_mesh",
]
