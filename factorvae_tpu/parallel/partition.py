"""Named-regex partition rules -> PartitionSpecs: ONE sharding story.

Before this module, the three scaling axes were wired ad hoc per path
and mutually exclusive: the serial `Trainer` had its own mesh
in_shardings, the seed-parallel fleet rejected meshes outright, and the
out-of-core stream fell back to HBM whenever a mesh appeared. This
module replaces that with the `match_partition_rules` /
`make_shard_and_gather_fns` pattern (SNIPPETS.md [1]-[3]): a single
table of (regex, PartitionSpec) rules matched against '/'-joined pytree
path names resolves the placement of EVERY array the training program
touches — the (stacked or serial) TrainState, the epoch day orders, the
HBM panel and the stream path's relocatable mini-panel chunks — so
Trainer, FleetTrainer, ChunkStream and scoring all compose on the same
mesh instead of pairwise-rejecting each other.

Axis semantics (docs/sharding.md has the full matrix):

- 'data'  — serial runs: day-level data parallelism (each device takes
  a slice of every update's day batch; GSPMD all-reduces gradients).
  Fleet runs: SEED lanes. S independent models have zero cross-model
  communication, so the seed axis is the cheapest thing to lay over the
  mesh — each 'data' slice trains S/dp seeds and no collective ever
  crosses it.
- 'stock' — the cross-section N, serial and fleet alike: panel rows,
  per-stock activations; the masked softmaxes / portfolio matvec become
  GSPMD collectives within a 'stock' group.
- 'host'  — (hierarchical meshes) day-batch data parallelism across
  hosts: the once-per-step gradient all-reduce may ride DCN while the
  latency-sensitive 'stock' reductions stay on ICI (mesh.py).

The oracle discipline the rules must preserve (tests/test_parallel.py):
S=1 on a 1x1 mesh is bitwise the serial Trainer; each axis enabled
alone is bitwise its single-axis path; mesh x stream is bitwise
mesh x hbm (the in-graph gather makes the chunked scan trace the same
partitioned program).
"""

from __future__ import annotations

import functools
import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from factorvae_tpu.parallel.mesh import DATA_AXIS, HOST_AXIS, STOCK_AXIS

# Seed lanes of a stacked (S, ...) fleet state ride the 'data' mesh axis
# (zero cross-seed communication makes it the free axis to occupy);
# day-batch data parallelism then moves to the 'host' axis when the
# mesh has one, and is simply off for fleet runs on a 2-axis mesh.
SEED_AXIS = DATA_AXIS

_is_spec = lambda x: isinstance(x, P)  # noqa: E731  (tree_map guard)


# ---------------------------------------------------------------------------
# Path naming + rule matching
# ---------------------------------------------------------------------------


def _key_str(k) -> str:
    """One path entry -> its bare name ('params', '0', 'kernel', ...)."""
    for attr in ("name", "key", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def tree_path_name(path) -> str:
    """'/'-joined pytree path, e.g. 'opt_state/0/mu/params/gru/kernel'."""
    return "/".join(_key_str(k) for k in path)


def named_tree_map(fn: Callable[[str, Any], Any], tree):
    """tree_map with the '/'-joined path name as the first argument."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [fn(tree_path_name(p), leaf) for p, leaf in flat]
    )


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree):
    """Pytree of PartitionSpecs resolved from (regex, spec) rules.

    First matching rule wins (`re.search` against the '/'-joined path
    name), so put specific rules before general ones. Scalar and
    single-element leaves are never partitioned (P()). A leaf no rule
    matches is a hard error: silently replicating a new TrainState
    field would un-shard it on every path at once — the failure must
    name the path so the rule table gets extended deliberately.
    """

    def get_spec(name, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return P()
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(
            f"no partition rule matches leaf '{name}' "
            f"(shape {shape}); extend the rule table"
        )

    return named_tree_map(get_spec, tree)


@functools.lru_cache(maxsize=8)
def _replicate_fn(sharding: NamedSharding):
    """Cached jitted identity with replicated out_shardings — the
    cross-process gather collective (one compile per mesh, not one per
    gathered leaf; NamedSharding hashes by (mesh, spec))."""
    return jax.jit(lambda t: t, out_shardings=sharding)


def make_shard_and_gather_fns(mesh: Mesh, specs):
    """(shard_fns, gather_fns) pytrees of per-leaf callables.

    shard_fn(x) places host (or single-device) data onto the mesh per
    its spec — through `multihost.global_put`, so on a pod slice every
    process materializes only its addressable shards. gather_fn(x)
    brings a (possibly sharded) array back to host numpy — the
    checkpoint path: per-seed unstacked checkpoints are written from
    gathered host buffers, never from sharded device arrays (orbax
    would otherwise couple the on-disk layout to the mesh shape).
    """
    from factorvae_tpu.parallel.multihost import global_put

    def make_shard(spec):
        sharding = NamedSharding(mesh, spec)

        def shard_fn(x):
            return global_put(x, sharding)

        return shard_fn

    replicate = _replicate_fn(NamedSharding(mesh, P()))

    def make_gather(spec):
        del spec  # the gather target is always host-replicated

        def gather_fn(x):
            if not getattr(x, "is_fully_addressable", True):
                # Multi-process array: an out_shardings=P() identity is
                # the collective that makes every process hold the whole
                # value; fully-addressable arrays skip the dispatch.
                x = replicate(x)
            return np.asarray(x)

        return gather_fn

    return (
        jax.tree_util.tree_map(make_shard, specs, is_leaf=_is_spec),
        jax.tree_util.tree_map(make_gather, specs, is_leaf=_is_spec),
    )


def shard_tree(mesh: Mesh, specs, tree):
    """Apply `make_shard_and_gather_fns`' shard side to a whole tree."""
    shard_fns, _ = make_shard_and_gather_fns(mesh, specs)
    return jax.tree_util.tree_map(lambda fn, x: fn(x), shard_fns, tree)


def gather_tree(mesh: Mesh, specs, tree):
    """Apply the gather side: sharded tree -> host-numpy tree."""
    _, gather_fns = make_shard_and_gather_fns(mesh, specs)
    return jax.tree_util.tree_map(lambda fn, x: fn(x), gather_fns, tree)


# ---------------------------------------------------------------------------
# The rule tables. ONE story: the serial table and the stacked (fleet)
# table name the SAME paths; the stacked one lays the leading seed axis
# over SEED_AXIS and keeps everything else identical — their spec trees
# differ exactly by that prefix (pinned in tests/test_parallel.py).
# ---------------------------------------------------------------------------

# Serial TrainState: replicated. The parameter tree is tiny (~3.5 MB at
# flagship shapes) — model parallelism buys nothing; the win axes are
# days ('data'/'host') and the cross-section ('stock').
TRAIN_STATE_RULES: list = [
    (r"^step$", P()),
    (r"^rng$", P()),
    (r"^(loss_scale|good_steps)$", P()),
    (r"^params/", P()),
    (r"^opt_state/", P()),
]

# Stacked (S, ...) fleet TrainState: the leading seed axis shards over
# SEED_AXIS; within a seed lane everything stays replicated.
FLEET_STATE_RULES: list = [
    (r"^step$", P(SEED_AXIS)),
    (r"^rng$", P(SEED_AXIS)),
    # mixed-precision loss-scale leaves (train/state.py): per-lane
    # scalars, (S,) stacked — ride the seed axis like step/rng.
    (r"^(loss_scale|good_steps)$", P(SEED_AXIS)),
    (r"^params/", P(SEED_AXIS)),
    (r"^opt_state/", P(SEED_AXIS)),
]

# Panel arrays (PanelDataset / the stream path's relocatable
# mini-panels — same axis layout, so one table serves both):
#   values     (N, D, C+1) -> rows shard over 'stock'
#   last_valid (D, N)      -> columns shard over 'stock'
#   next_valid (D, N)      -> columns shard over 'stock'
PANEL_RULES: list = [
    (r"(^|/)values$", P(STOCK_AXIS, None, None)),
    (r"(^|/)(last_valid|next_valid)$", P(None, STOCK_AXIS)),
]


def state_partition_specs(state, stacked: bool = False):
    """Spec tree for a TrainState (or a bare params tree), serial or
    stacked. `jax.eval_shape` structs work as leaves — only shapes are
    read."""
    return match_partition_rules(
        FLEET_STATE_RULES if stacked else TRAIN_STATE_RULES, state
    )


def params_partition_specs(params, stacked: bool = False):
    """Spec tree for a bare params tree (scoring / best-params buffers).
    Param paths lack the 'params/' TrainState prefix, so the catch-all
    seed rule is applied directly."""
    spec = P(SEED_AXIS) if stacked else P()
    return match_partition_rules([(r".*", spec)], params)


def panel_partition_specs(stacked: bool = False):
    """(values, last_valid, next_valid) specs, matching the panel rule
    table. `stacked=True` prepends the seed axis (the fleet-stream
    path's per-seed mini-panel stacks, (S, N, cT, C+1))."""
    d = {"values": np.zeros((2, 2, 2)),
         "last_valid": np.zeros((2, 2)), "next_valid": np.zeros((2, 2))}
    specs = match_partition_rules(PANEL_RULES, d)
    out = (specs["values"], specs["last_valid"], specs["next_valid"])
    if stacked:
        out = tuple(P(SEED_AXIS, *s) for s in out)
    return out


def day_batch_axes(mesh: Mesh, stacked: bool = False) -> tuple:
    """Mesh axes that shard the day-batch (B) dimension. Serial runs
    keep the historical ('host','data') / ('data',) assignment
    (mesh.batch_axes); fleet runs cede 'data' to the seed axis, so
    day-batches shard over 'host' when the mesh has one and are
    replicated otherwise."""
    if not stacked:
        from factorvae_tpu.parallel.mesh import batch_axes

        return batch_axes(mesh)
    return (HOST_AXIS,) if HOST_AXIS in mesh.axis_names else ()


def order_partition_spec(mesh: Mesh, stacked: bool = False) -> P:
    """Epoch day-order spec: serial (steps, B) -> P(None, day_axes);
    stacked (S, steps, B) -> P(seed, None, day_axes)."""
    day = day_batch_axes(mesh, stacked)
    day_spec = day if day else None
    if stacked:
        return P(SEED_AXIS, None, day_spec)
    return P(None, day_spec)


def eval_order_partition_spec(mesh: Mesh, stacked: bool = False) -> P:
    """The SHARED validation order (steps, B) — no seed axis even on
    fleet runs (every seed evaluates the same days)."""
    day = day_batch_axes(mesh, stacked)
    return P(None, day if day else None)


def eval_keys_partition_spec() -> P:
    """Stacked per-seed eval keys (S, key) -> seed axis."""
    return P(SEED_AXIS)


def named(mesh: Mesh, specs):
    """Spec pytree -> NamedSharding pytree (what jit in_shardings and
    device_put consume)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=_is_spec
    )


def seed_parallel_size(mesh: Optional[Mesh]) -> int:
    """How many ways the seed axis splits on this mesh (1 = no mesh)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(SEED_AXIS, 1))


def _dim_shard_sizes(dim: int, k: int) -> list:
    """GSPMD split of one dimension over k shards: every shard gets
    ceil(dim/k) rows except the tail, which gets what is left (possibly
    zero — an uneven split pads, and the padding is DEAD memory on the
    devices that hold it, which is exactly what the imbalance accounting
    must see)."""
    per = -(-dim // k)
    return [max(0, min(per, dim - i * per)) for i in range(k)]


def device_bytes(mesh: Mesh, specs, tree) -> "np.ndarray":
    """Per-device REAL bytes of `tree` placed per `specs` on `mesh` —
    the rule-table counterpart of `make_shard_and_gather_fns`, for
    accounting instead of placement (obs/memory.py's shard-balance
    bill). Returns an array shaped like `mesh.devices` (device-id
    layout) whose entries are the bytes of actual data (padding
    excluded) each device holds for this tree. `jax.eval_shape` structs
    work as leaves — only shape/dtype are read."""
    shape = tuple(int(s) for s in np.asarray(mesh.devices).shape)
    out = np.zeros(shape, dtype=np.int64)

    def add_leaf(spec, leaf):
        lshape = tuple(getattr(leaf, "shape", ()))
        itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        # bytes per device coordinate = product over dims of that
        # coordinate's shard length (replicated dims contribute fully on
        # every device).
        per_dim = []  # one (mesh-axis-index or None, sizes) per array dim
        entries = list(spec) if spec is not None else []
        for d, dim in enumerate(lshape):
            axes = entries[d] if d < len(entries) else None
            if axes is None:
                per_dim.append((None, [dim]))
                continue
            names = axes if isinstance(axes, (tuple, list)) else (axes,)
            k = 1
            idxs = []
            for nm in names:
                k *= int(mesh.shape[nm])
                idxs.append(mesh.axis_names.index(nm))
            per_dim.append((tuple(idxs), _dim_shard_sizes(int(dim), k)))
        it = np.ndindex(*shape)
        for coord in it:
            b = itemsize
            for idxs, sizes in per_dim:
                if idxs is None:
                    b *= sizes[0]
                else:
                    # linear shard index over the (possibly multi-axis)
                    # sharded dim, in mesh-axis order
                    li = 0
                    for i in idxs:
                        li = li * shape[i] + coord[i]
                    b *= sizes[li]
            out[coord] += b

    jax.tree_util.tree_map(add_leaf, specs, tree, is_leaf=_is_spec)
    return out
