"""Multi-host (multi-process) initialization.

A TPU pod slice runs one JAX process per host; `jax.distributed.initialize`
wires them into a single logical device set, after which the framework's
mesh code (mesh.py) spans all hosts transparently: `jax.devices()` returns
the global device list, GSPMD gradient all-reduce rides ICI within a slice
and DCN across slices, and every Trainer/collective path works unchanged
(they only ever reference mesh axes, never host boundaries). This is the
multi-host story a GPU framework gets from NCCL+MPI ranks; here the
runtime already speaks the collectives, so the only job is process wiring.

Data layout under multi-host: the panel is small (O(1) GB), so every host
builds the same HBM-resident panel and the day order is identical on all
processes (it is derived from seeded host RNG with the same seed) — each
process then owns the shards GSPMD assigns to its local devices. No
per-host input pipeline divergence exists to manage.

Usage:
    from factorvae_tpu.parallel.multihost import maybe_initialize
    maybe_initialize()            # no-op on single host
    # ... build mesh over jax.devices() as usual

The CLI calls this automatically when the standard cluster env is present.
"""

from __future__ import annotations

import os
from typing import Optional


def in_multihost_env() -> bool:
    """True when a multi-process cluster environment is detected (the
    standard JAX coordinator variables, or a TPU pod's own metadata that
    `jax.distributed.initialize()` can auto-discover)."""
    return bool(
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    )


def maybe_initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed when configured; returns True if it ran.

    With no arguments and no cluster env, this is a no-op (single-host) —
    safe to call unconditionally.
    """
    import jax

    if coordinator_address is None and not in_multihost_env():
        return False
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return True


def global_put(x, sharding):
    """Place identical-on-every-process host data onto a (possibly
    multi-process) sharding.

    `jax.device_put` onto a multi-process sharding runs an
    equality-across-processes assertion that is both slow (it ships the
    whole array over the coordinator) and wrong for NaN padding
    (NaN != NaN — the panel's padded rows trip it). The standard pod
    pattern is used instead: every process materializes just its
    addressable shards from its local copy via
    `jax.make_array_from_callback`. Single-process falls back to plain
    device_put.
    """
    import jax
    import numpy as np

    if is_global(x):
        # already spans processes (e.g. a dataset shared by a second
        # Trainer) — re-placing would require a cross-process gather
        return x
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    host = np.asarray(x)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )


def is_global(x) -> bool:
    """True for an array already spanning processes (not fully
    addressable locally) — i.e. one that must NOT be re-placed."""
    return not getattr(x, "is_fully_addressable", True)


def process_info() -> dict:
    """Host/process layout for logging."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
