"""Device-mesh construction.

The distributed backend of this framework is XLA's collectives over
ICI/DCN, reached through `jax.sharding` — the TPU-native replacement for
the NCCL/MPI layer a GPU framework would hand-roll (the reference has no
distributed support at all; SURVEY.md §2.3 specifies this surface).

Mesh axes:
- 'data'  — trading days. Each device takes a slice of every update's
  day-batch; gradients are all-reduced over ICI by GSPMD.
- 'stock' — the cross-section. Shards the padded instrument axis of the
  panel and every per-stock activation; the cross-stock reductions
  (masked softmaxes, portfolio matmul, loss means) become psum-style
  collectives inserted by GSPMD. This is the model's analogue of
  sequence/context parallelism: the "long axis" of this model family is
  the stock universe (N up to ~800 for CSI800), not time (T=20-60), per
  SURVEY.md §5.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from factorvae_tpu.config import MeshConfig

DATA_AXIS = "data"
STOCK_AXIS = "stock"


def make_mesh(
    cfg: Optional[MeshConfig] = None, devices: Optional[Sequence] = None
) -> Mesh:
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = cfg.shape(len(devices))
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, (DATA_AXIS, STOCK_AXIS))


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), (DATA_AXIS, STOCK_AXIS))
