"""Device-mesh construction.

The distributed backend of this framework is XLA's collectives over
ICI/DCN, reached through `jax.sharding` — the TPU-native replacement for
the NCCL/MPI layer a GPU framework would hand-roll (the reference has no
distributed support at all; SURVEY.md §2.3 specifies this surface).

Mesh axes:
- 'data'  — trading days. Each device takes a slice of every update's
  day-batch; gradients are all-reduced over ICI by GSPMD.
- 'stock' — the cross-section. Shards the padded instrument axis of the
  panel and every per-stock activation; the cross-stock reductions
  (masked softmaxes, portfolio matmul, loss means) become psum-style
  collectives inserted by GSPMD. This is the model's analogue of
  sequence/context parallelism: the "long axis" of this model family is
  the stock universe (N up to ~800 for CSI800), not time (T=20-60), per
  SURVEY.md §5.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from factorvae_tpu.config import MeshConfig

DATA_AXIS = "data"
STOCK_AXIS = "stock"
HOST_AXIS = "host"


def make_mesh(
    cfg: Optional[MeshConfig] = None, devices: Optional[Sequence] = None
) -> Mesh:
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = cfg.shape(len(devices))
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:  # graftlint: disable=JGL007 create_device_mesh only optimizes topology order; the reshape fallback uses the same devices and is deterministic — nothing was lost worth surfacing
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, (DATA_AXIS, STOCK_AXIS))


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), (DATA_AXIS, STOCK_AXIS))


def make_hierarchical_mesh(
    cfg: Optional[MeshConfig] = None,
    devices: Optional[Sequence] = None,
    num_hosts: Optional[int] = None,
) -> Mesh:
    """3-axis ('host', 'data', 'stock') mesh for pod-slice topologies.

    The outer 'host' axis follows process boundaries (each host's devices
    stay contiguous in the device array), so collectives whose replica
    groups cross the 'host' axis ride DCN while groups confined to one
    host's block stay on ICI. The sharding helpers treat ('host','data')
    jointly as the batch axis: day-level gradient all-reduce crosses DCN
    once per optimizer step with the small (~3.5 MB at flagship shapes)
    gradient tree — the latency-tolerant collective — while the
    latency-sensitive per-day 'stock' reductions (masked softmaxes,
    portfolio matvec; module.py:38,57,64,146 semantics) never leave a
    host's ICI domain.

    `num_hosts` defaults to the real process count; pass it explicitly to
    simulate host granularity on the single-process CPU test rig.
    """
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    if num_hosts is None:
        num_hosts = len({d.process_index for d in devices}) or 1
    if len(devices) % num_hosts:
        raise ValueError(
            f"{len(devices)} devices not divisible by num_hosts={num_hosts}"
        )
    per_host = len(devices) // num_hosts
    sp = cfg.stock_axis
    if per_host % sp:
        raise ValueError(
            f"per-host device count {per_host} not divisible by "
            f"stock_axis={sp}; the 'stock' groups must fit inside one "
            f"host's ICI domain"
        )
    if cfg.data_axis > 0 and cfg.data_axis != num_hosts * (per_host // sp):
        raise ValueError(
            f"MeshConfig.data_axis={cfg.data_axis} conflicts with the "
            f"derived total data parallelism "
            f"{num_hosts} hosts x {per_host // sp} = "
            f"{num_hosts * (per_host // sp)}; leave it at -1 or match it"
        )
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    arr = np.asarray(devices).reshape(num_hosts, per_host // sp, sp)
    # the ICI-only guarantee for 'stock'/'data' groups requires every
    # host row to hold devices of exactly one process — an uneven
    # per-host device distribution (e.g. a degraded slice) must be a
    # hard error, not a silent DCN-riding softmax
    for h in range(num_hosts):
        procs = {d.process_index for d in arr[h].ravel()}
        if len(procs) > 1:
            raise ValueError(
                f"host row {h} mixes devices of processes {sorted(procs)}; "
                f"devices are not evenly distributed across hosts "
                f"({len(devices)} devices / {num_hosts} hosts)"
            )
    return Mesh(arr, (HOST_AXIS, DATA_AXIS, STOCK_AXIS))


def batch_axes(mesh: Mesh) -> tuple:
    """The mesh axes that jointly shard the day-batch dimension:
    ('host', 'data') on a hierarchical mesh, ('data',) otherwise."""
    if HOST_AXIS in mesh.axis_names:
        return (HOST_AXIS, DATA_AXIS)
    return (DATA_AXIS,)


def data_parallel_size(mesh: Mesh) -> int:
    """Total day-level data parallelism (product of the batch axes)."""
    return int(np.prod([mesh.shape[a] for a in batch_axes(mesh)]))
