"""Render a RUN.jsonl host timeline as a text Gantt + overlap report.

    python -m factorvae_tpu.obs.timeline RUN.jsonl [--width 72]
        [--top 10] [--json] [--follow]

Reads the `span` / `mark` records that `utils.logging.Timeline` emits
(Trainer/FleetTrainer epochs on the "device" resource, ChunkStream
prefetch on "stream", checkpoint saves/serializes on "checkpoint",
compile-watchdog spans on "compile") and prints:

- one Gantt lane per resource (merged busy intervals over the run
  window), so the overlap structure of the pipeline — is the prefetch
  really hiding behind the epoch scan? is the async checkpoint really
  off the critical path? — is visible at a glance;
- per-resource totals: busy seconds, span count, and `overlap_frac` —
  the fraction of that resource's busy time that overlapped "device"
  busy time. This is the run-level generalization of the ChunkStream
  ledger's overlap number: ~1.0 means the work hid behind compute,
  ~0.0 means it ran in the gaps (or the gaps ran in it).

Span names deliberately match `utils.profiling.step_annotation` names
(`train_epoch_{e}`, ...), so a host span here can be located on the
device lanes of a `--profile` trace (utils/trace_summary.py) by name.

Serving-plane spans additionally carry `trace` / `span` / `parent`
fields (the fleet trace plane, obs/trace.py); this renderer ignores
them — they are additive annotations on the same `span` records, and
the per-resource Gantt here stays the resource-utilization view while
`python -m factorvae_tpu.obs.trace` renders the per-request causal
tree. The per-process-section discipline below (span_sections) is the
same lesson the trace collector solves properly: records from
different processes share NO time base until clock probes align them
(obs/collect.py).
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

Interval = Tuple[float, float]

DEVICE_RESOURCE = "device"

# Recovery-action marks (ISSUE 9, docs/robustness.md): shown on the
# Gantt as `!` instants and summarized in a RECOVERY line, so a healed
# run's damage is visible in the same rendering as its pipeline.
RECOVERY_MARK_NAMES = (
    "recovery_rollback",
    "recovery_rollback_unavailable",
    "ckpt_quarantine",
    "ckpt_unverified",
    "serve_quarantine",
    "circuit_open",
    "circuit_close",
    "stream_retry",
    "cold_start_retry",
    "sigterm_drain",
)


def recovery_marks(run: dict) -> List[dict]:
    """The stream's recovery-action marks, in stream order."""
    return [m for m in run.get("marks", [])
            if m.get("name") in RECOVERY_MARK_NAMES]


def load_run(path: str) -> dict:
    """Split a RUN.jsonl into {"spans", "marks", "epochs", "meta",
    "events"} record lists (unparseable lines are skipped, not fatal —
    a live-tailed file may end mid-line). Parse bookkeeping lands in
    `_stats` so `open_run` can tell an async-kill torn tail (warning)
    from a file that isn't JSONL at all (error)."""
    out: dict = {"spans": [], "marks": [], "epochs": [], "meta": [],
                 "events": []}
    lines = bad = 0
    last_bad = False
    # errors="replace": a binary (non-UTF-8) file must surface as "no
    # line parses" — the one-line not-a-JSONL error — not as a
    # UnicodeDecodeError traceback out of the iterator.
    with open(path, errors="replace") as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            lines += 1
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                last_bad = True
                continue
            last_bad = False
            if not isinstance(rec, dict):
                bad += 1
                continue
            # Stream position: the report needs record ORDER across the
            # split lists (e.g. which plan record precedes which run's
            # epochs in a concatenated session stream).
            rec.setdefault("_line", i)
            ev = rec.get("event")
            if ev == "span":
                out["spans"].append(rec)
            elif ev == "mark":
                out["marks"].append(rec)
            elif ev in ("epoch", "fleet_epoch"):
                out["epochs"].append(rec)
            elif ev == "run_meta":
                out["meta"].append(rec)
            else:
                out["events"].append(rec)
    out["_stats"] = {"lines": lines, "bad": bad, "last_bad": last_bad}
    return out


class RunStreamError(Exception):
    """A RUN.jsonl that cannot be rendered at all — missing, empty, or
    not JSONL. Carries the ONE-line message the CLIs print (ISSUE 7: a
    truncated stream is an error message, never a traceback)."""


def open_run(path: str) -> Tuple[dict, List[str]]:
    """`load_run` + stream sanity for the CLI entry points: returns
    (run, warnings). Raises RunStreamError on a missing/unreadable
    file, an empty stream, or a file none of whose lines parse as
    JSONL. A trailing partially-written line — the artifact of killing
    an async writer — is SKIPPED with a warning, and so are isolated
    corrupt lines in the middle; only a stream with nothing readable is
    fatal."""
    try:
        run = load_run(path)
    except OSError as e:
        raise RunStreamError(
            f"cannot read {path}: {e.strerror or e}") from e
    stats = run["_stats"]
    if stats["lines"] == 0:
        raise RunStreamError(
            f"{path} is empty — no run has written to this stream yet")
    if stats["bad"] == stats["lines"]:
        raise RunStreamError(
            f"{path} is not a JSONL metric stream "
            f"(none of its {stats['lines']} lines parse)")
    warnings = []
    if stats["last_bad"]:
        warnings.append(
            f"{path}: trailing partial line skipped (stream was cut "
            "mid-write — an async kill artifact, not corruption)")
        if stats["bad"] > 1:
            warnings.append(
                f"{path}: {stats['bad'] - 1} additional unparseable "
                "line(s) skipped")
    elif stats["bad"]:
        warnings.append(
            f"{path}: {stats['bad']} unparseable line(s) skipped")
    return run, warnings


def merge_intervals(iv: List[Interval]) -> List[Interval]:
    """Sorted union of possibly-overlapping intervals."""
    out: List[Interval] = []
    for lo, hi in sorted(iv):
        if hi <= lo:
            continue
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def total(iv: List[Interval]) -> float:
    return sum(hi - lo for lo, hi in iv)


def intersect(a: List[Interval], b: List[Interval]) -> List[Interval]:
    """Intersection of two MERGED interval lists (linear sweep)."""
    out: List[Interval] = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if lo < hi:
            out.append((lo, hi))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def resource_intervals(spans: List[dict]) -> dict:
    """resource -> merged busy intervals."""
    by_res: dict = {}
    for s in spans:
        try:
            by_res.setdefault(s.get("resource", "host"), []).append(
                (float(s["t0"]), float(s["t1"])))
        except (KeyError, TypeError, ValueError):
            continue
    return {r: merge_intervals(iv) for r, iv in by_res.items()}


def overlap_report(spans: List[dict]) -> List[dict]:
    """Per-resource busy totals + overlap_frac vs the device lane.
    overlap_frac is None for the device lane itself and when no device
    spans exist (nothing to overlap with — report honestly, don't
    default to 0 or 1)."""
    res = resource_intervals(spans)
    device = res.get(DEVICE_RESOURCE, [])
    counts: dict = {}
    for s in spans:
        counts[s.get("resource", "host")] = counts.get(
            s.get("resource", "host"), 0) + 1
    rows = []
    for r in sorted(res):
        busy = total(res[r])
        if r == DEVICE_RESOURCE or not device or busy <= 0.0:
            frac: Optional[float] = None
        else:
            frac = total(intersect(res[r], device)) / busy
        rows.append({
            "resource": r,
            "busy_seconds": round(busy, 6),
            "spans": counts.get(r, 0),
            "overlap_frac": None if frac is None else round(frac, 4),
        })
    return rows


def gantt(spans: List[dict], width: int = 72,
          marks: Optional[List[dict]] = None) -> str:
    """One text lane per resource over the run window. `marks`
    (recovery events, ISSUE 9) overlay as `!` at their instant on their
    resource's lane — a lane that only ever saw marks (e.g. `recovery`)
    still appears."""
    res = resource_intervals(spans)
    marks = [m for m in (marks or []) if isinstance(m.get("t"),
                                                    (int, float))]
    if not res and not marks:
        return "(no spans)"
    los = [iv[0][0] for iv in res.values() if iv] + [m["t"] for m in marks]
    his = [iv[-1][1] for iv in res.values() if iv] + [m["t"] for m in marks]
    lo, hi = min(los), max(his)
    window = max(hi - lo, 1e-9)
    lanes = sorted(set(res) | {m.get("resource", "host") for m in marks})
    name_w = max(len(r) for r in lanes)
    lines = [f"{'':<{name_w}}  |{'run window':-^{width}}| "
             f"{lo:.3f}s .. {hi:.3f}s"]
    for r in lanes:
        cells = [" "] * width
        for a, b in res.get(r, []):
            c0 = int((a - lo) / window * width)
            c1 = max(c0 + 1, int((b - lo) / window * width + 0.5))
            for c in range(c0, min(c1, width)):
                cells[c] = "#"
        for m in marks:
            if m.get("resource", "host") != r:
                continue
            c = min(int((m["t"] - lo) / window * width), width - 1)
            cells[c] = "!"
        lines.append(f"{r:<{name_w}}  |{''.join(cells)}|")
    return "\n".join(lines)


def span_sections(run: dict) -> List[List[dict]]:
    """Partition a stream's spans into per-process sections at
    `run_meta` boundaries (every file-backed MetricsLogger attach
    writes one). Each process's Timeline origin restarts near zero, so
    spans from different sections of a concatenated session stream
    share NO time base: merging them would overlay separate runs into
    one window and fabricate overlap between work that never ran
    concurrently. Streams without positional info (hand-built lists)
    or with a single header stay one section."""
    bounds = sorted(m["_line"] for m in run.get("meta", [])
                    if m.get("_line") is not None)
    spans = run["spans"]
    if len(bounds) <= 1 or any(s.get("_line") is None for s in spans):
        return [spans] if spans else []
    sections: List[List[dict]] = [[] for _ in bounds]
    for s in spans:
        # the section whose header precedes this span
        i = sum(1 for b in bounds if b < s["_line"]) - 1
        sections[max(i, 0)].append(s)
    return [sec for sec in sections if sec]


def _marks_for_section(run: dict, spans: List[dict],
                       rmarks: List[dict]) -> List[dict]:
    """The recovery marks sharing a span section's time base: those
    between the same pair of `run_meta` headers (each process/section
    has its own perf_counter origin — a mark from another section
    overlaid here would land at a fabricated spot). Single-section
    streams and positionless records keep everything."""
    if not spans or not rmarks:
        return []
    bounds = sorted(m["_line"] for m in run.get("meta", [])
                    if m.get("_line") is not None)
    if len(bounds) <= 1 or any(s.get("_line") is None for s in spans):
        return rmarks
    # the section is owned by the last header preceding its spans
    first = min(s["_line"] for s in spans)
    i = max(sum(1 for b in bounds if b < first) - 1, 0)
    lo = bounds[i]
    hi = bounds[i + 1] if i + 1 < len(bounds) else float("inf")
    return [m for m in rmarks
            if m.get("_line") is None or lo <= m["_line"] < hi]


def format_report(run: dict, width: int = 72, top: int = 10) -> str:
    sections = span_sections(run)
    rmarks = recovery_marks(run)
    lines: List[str] = []
    for i, spans in enumerate(sections):
        if len(sections) > 1:
            lines.append(f"=== run section {i + 1}/{len(sections)} "
                         "(separate process: own time base) ===")
        lines.append(gantt(spans, width=width,
                           marks=_marks_for_section(run, spans, rmarks)))
        lines.append("")
        rows = overlap_report(spans)
        if rows:
            w = max(len("resource"), max(len(r["resource"]) for r in rows))
            lines.append(f"{'resource':<{w}} {'busy':>10} {'spans':>6}  "
                         "overlap_frac")
            for r in rows:
                frac = ("-" if r["overlap_frac"] is None
                        else f"{r['overlap_frac']:.1%}")
                lines.append(
                    f"{r['resource']:<{w}} {r['busy_seconds']:>9.3f}s "
                    f"{r['spans']:>6}  {frac}")
        if top > 0 and spans:
            longest = sorted(spans,
                             key=lambda s: -float(s.get("dur", 0.0)))[:top]
            lines.append("")
            lines.append(f"longest spans (top {len(longest)}):")
            for s in longest:
                lines.append(
                    f"  {s.get('dur', 0.0):>9.3f}s  [{s.get('resource')}] "
                    f"{s.get('name')}")
        if len(sections) > 1:
            lines.append("")
    compiles = compile_summary(run)
    if compiles["records"]:
        lines.append(
            f"compiled programs: {len(compiles['by_fn'])} jits, "
            f"{compiles['records']} compiles, "
            f"{compiles['total_wall_s']:.2f}s total compile wall"
            + (f", peak program HBM estimate "
               f"{compiles['max_peak_bytes'] / 1e6:.1f} MB"
               if compiles.get("max_peak_bytes") else ""))
    storms = [m for m in run["marks"] if m.get("name") == "retrace_storm"]
    if storms:
        worst = max(storms, key=lambda m: m.get("compiles", 0))
        cost = compiles["by_fn"].get(worst.get("fn"), {}).get("wall_s")
        lines.append(
            f"RETRACE STORM: '{worst.get('fn')}' compiled "
            f"{worst.get('compiles')} times over {worst.get('calls')} calls"
            # the cost dimension (ISSUE 7): what the storm actually
            # burned, from the per-miss compile records
            + (f" — {cost:.2f}s of compile wall" if cost else ""))
    if rmarks:
        by: dict = {}
        for m in rmarks:
            by[m["name"]] = by.get(m["name"], 0) + 1
        lines.append(
            "RECOVERY: "
            + ", ".join(f"{k} x{n}" for k, n in sorted(by.items()))
            + " (`!` marks on the Gantt; detail: obs.report)")
    return "\n".join(lines)


def compile_summary(run: dict) -> dict:
    """Aggregate the stream's `compile` records (obs/watchdog.py emits
    one per detected cache miss): total/per-fn wall seconds, compile
    counts, and the largest cost/memory figures the guarded capture
    yielded (nulls where the jax version lacks the APIs)."""
    recs = [r for r in run["events"] if r.get("event") == "compile"]
    by_fn: dict = {}
    for r in recs:
        fn = str(r.get("fn"))
        e = by_fn.setdefault(fn, {"compiles": 0, "wall_s": 0.0,
                                  "flops": None, "peak_bytes": None})
        e["compiles"] += 1
        e["wall_s"] = round(e["wall_s"] + float(r.get("wall_s") or 0.0), 6)
        for k in ("flops", "peak_bytes"):
            v = r.get(k)
            if v is not None:
                e[k] = max(e[k] or 0, v)
    peaks = [e["peak_bytes"] for e in by_fn.values()
             if e["peak_bytes"] is not None]
    return {
        "records": len(recs),
        "total_wall_s": round(sum(float(r.get("wall_s") or 0.0)
                                  for r in recs), 6),
        "max_peak_bytes": max(peaks) if peaks else None,
        "by_fn": by_fn,
    }


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.obs.timeline",
        description="Text Gantt + per-resource overlap for a RUN.jsonl "
                    "span stream")
    ap.add_argument("run_jsonl")
    ap.add_argument("--width", type=int, default=72)
    ap.add_argument("--top", type=int, default=10,
                    help="longest spans listed (0 disables)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable overlap report instead of text")
    ap.add_argument("--follow", action="store_true",
                    help="tail an in-flight stream instead: delegates "
                         "to the live follower (obs/live.py), emitting "
                         "health/compile/recovery flags as alerts while "
                         "the run writes (Gantt rendering needs the "
                         "finished stream — rerun without --follow)")
    ap.add_argument("--idle-timeout", type=float, default=None,
                    help="with --follow: stop after this many seconds "
                         "without new bytes (default: follow forever)")
    args = ap.parse_args(argv)
    import sys

    if args.follow:
        from factorvae_tpu.obs import live

        follow_args = [args.run_jsonl, "--follow"]
        if args.json:
            follow_args.append("--json")
        if args.idle_timeout is not None:
            follow_args += ["--idle-timeout", str(args.idle_timeout)]
        return live.main(follow_args)

    try:
        run, warnings = open_run(args.run_jsonl)
    except RunStreamError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    if args.json:
        print(json.dumps({
            # per-section: spans across run_meta boundaries carry
            # separate per-process time bases (see span_sections)
            "sections": [overlap_report(sec)
                         for sec in span_sections(run)],
            "num_spans": len(run["spans"]),
            "compiles": compile_summary(run),
            "retrace_storms": [m for m in run["marks"]
                               if m.get("name") == "retrace_storm"],
            "recovery_marks": recovery_marks(run),
        }, indent=2))
    else:
        print(format_report(run, width=args.width, top=args.top))
    return 0 if run["spans"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
