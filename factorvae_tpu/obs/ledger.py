"""Perf-regression ledger: bench artifacts as a tracked trajectory.

Seven rounds of bench runs produced one-off `BENCH_*.json` artifacts; a
throughput regression today is invisible unless someone rereads
PERF.md. This module turns every headline bench row into a LINE of
`BENCH_HISTORY.jsonl` (`bench.py --track` appends after each emit) and
checks the latest row per metric key against the trailing median of its
own history:

    python -m factorvae_tpu.obs.ledger                  # check, exit 1 on regression
    python -m factorvae_tpu.obs.ledger --backfill       # seed history from BENCH_*.json
    python -m factorvae_tpu.obs.ledger --json           # machine-readable report

Row schema (one JSON object per line):

    {"ts", "metric", "value", "unit", "platform", "vs_baseline",
     "plan": <the bench plan block>, "run_meta": {git_sha, env, ...}}

**Rig discipline**: every fresh row carries `run_meta.env` — the
backend environment (`JAX_PLATFORMS`, the virtual-device count, sorted
`XLA_FLAGS`; utils/logging.backend_env) plus platform/device_count —
and two rows are comparable ONLY when their rig keys match exactly.
A laptop run can never flag a chip series (or vice versa) as a
regression; rows from other rigs are reported as skipped, not
compared. Backfilled rows (pre-ledger artifacts recorded no
environment) get a platform-only rig of their own.

Metrics are higher-is-better (every bench series is windows/sec
flavored; the `fail_unit` discipline keeps units stable per metric) —
a regression is `latest < (1 - threshold) x trailing median`. The
default threshold (0.4) sits above this sandbox's documented ±30%
run-to-run CPU variance; tune per rig with `--threshold`.
"""

from __future__ import annotations

import glob
import json
import os
import time
from statistics import median
from typing import List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
HISTORY_ENV = "FACTORVAE_BENCH_HISTORY"
DEFAULT_HISTORY_PATH = os.path.join(_REPO_ROOT, "BENCH_HISTORY.jsonl")

DEFAULT_THRESHOLD = 0.4
DEFAULT_WINDOW = 5


def history_path(path: Optional[str] = None) -> str:
    return path or os.environ.get(HISTORY_ENV) or DEFAULT_HISTORY_PATH


def rig_key(row: dict) -> str:
    """Canonical comparability key of one ledger row: platform +
    device_count + the backend env (sorted-JSON so dict order never
    splits a rig). Backfilled rows (no recorded env) key on platform
    alone — their own rig, never compared against instrumented rows."""
    meta = row.get("run_meta") or {}
    key = {
        "platform": row.get("platform"),
        "device_count": meta.get("device_count"),
        "env": meta.get("env"),
        # Pre-ledger artifacts recorded no environment AND spanned
        # different sandboxes round to round (PERF.md documents ±30%
        # and a 2x CPU difference across rounds): each backfilled
        # artifact is its own rig — historical context on the
        # trajectory, never a regression baseline.
        "backfill_source": meta.get("backfill_source"),
    }
    return json.dumps(key, sort_keys=True)


def make_row(payload: dict, run_meta: Optional[dict] = None) -> dict:
    from factorvae_tpu.utils import logging as loglib

    if run_meta is None:
        # A payload-embedded run_meta is the MEASURING process's rig
        # (bench.py's subprocess-measured payloads carry one: the
        # forced-CPU fallback and the accel child run under different
        # platform pins than the driver parent appending this row).
        # Only a payload without one falls back to this process's env.
        run_meta = payload.get("run_meta") or loglib.run_meta()
    return {
        "ts": round(time.time(), 3),
        "metric": payload.get("metric"),
        "value": payload.get("value"),
        "unit": payload.get("unit"),
        "platform": payload.get("platform"),
        "vs_baseline": payload.get("vs_baseline"),
        "plan": payload.get("plan"),
        "run_meta": run_meta,
    }


def _trackable(payload: dict) -> Optional[Tuple[str, float]]:
    """(metric, value) when a payload belongs in the history, else
    None. ONE definition of the rule for --track and --backfill alike:
    failure payloads (`*_failed` metrics, non-positive or non-numeric
    values) carry no throughput and would poison the median the next
    real run is judged against."""
    metric = str(payload.get("metric") or "")
    try:
        value = float(payload.get("value"))
    except (TypeError, ValueError):
        return None
    if not metric or metric.endswith("_failed") or value <= 0:
        return None
    return metric, value


def append_row(payload: dict, path: Optional[str] = None,
               run_meta: Optional[dict] = None) -> Optional[str]:
    """Append one bench payload as a history row; untrackable payloads
    (see `_trackable`) are skipped. Returns the path written, or None
    when the row was skipped."""
    if _trackable(payload) is None:
        return None
    p = history_path(path)
    with open(p, "a") as fh:
        fh.write(json.dumps(make_row(payload, run_meta=run_meta)) + "\n")
    return p


def load_history(path: Optional[str] = None) -> List[dict]:
    """Rows in file order; unparseable lines are skipped (the ledger is
    append-only and a kill mid-append may tear the last line)."""
    rows = []
    try:
        fh = open(history_path(path))
    except OSError:
        return rows
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("metric") is not None:
                rows.append(rec)
    return rows


def check(path: Optional[str] = None, threshold: float = DEFAULT_THRESHOLD,
          window: int = DEFAULT_WINDOW) -> Tuple[bool, dict]:
    """(ok, report). For each metric key, the LATEST row is compared
    against the trailing median of up to `window` PRIOR same-rig rows;
    `ok` is False when any metric regressed past the threshold. Rows
    from other rigs are counted as skipped per metric — refused, not
    compared."""
    rows = load_history(path)
    by_metric: dict = {}
    for r in rows:
        by_metric.setdefault(r["metric"], []).append(r)
    # Backfilled rows are HISTORY by definition, wherever they sit in
    # the file: a `--backfill` run after fresh --track rows exist must
    # not demote the latest tracked row to mid-series (which would
    # silently turn the gate into no_comparable_history for that
    # metric). Stable-sort backfill rows ahead of instrumented ones.
    for metric, series in by_metric.items():
        by_metric[metric] = sorted(
            series, key=lambda r: 0 if (r.get("run_meta") or {}).get(
                "backfill_source") else 1)
    report: dict = {"path": history_path(path), "rows": len(rows),
                    "threshold": threshold, "window": window, "metrics": []}
    ok = True
    for metric in sorted(by_metric):
        series = by_metric[metric]
        latest = series[-1]
        prior = series[:-1]
        rig = rig_key(latest)
        same = [r for r in prior if rig_key(r) == rig]
        entry: dict = {
            "metric": metric,
            "unit": latest.get("unit"),
            "latest": latest.get("value"),
            "history": len(prior),
            "other_rig_skipped": len(prior) - len(same),
        }
        vals = []
        for r in same[-window:]:
            try:
                v = float(r.get("value"))
            except (TypeError, ValueError):
                continue
            if v > 0:
                vals.append(v)
        if not vals:
            entry["status"] = "no_comparable_history"
        else:
            med = median(vals)
            try:
                ratio = float(latest.get("value")) / med
            except (TypeError, ValueError, ZeroDivisionError):
                ratio = None
            entry["trailing_median"] = round(med, 3)
            entry["ratio_vs_median"] = (round(ratio, 4)
                                        if ratio is not None else None)
            if ratio is None or ratio < 1.0 - threshold:
                entry["status"] = "REGRESSION"
                ok = False
            elif ratio > 1.0 + threshold:
                entry["status"] = "improvement"
            else:
                entry["status"] = "ok"
        report["metrics"].append(entry)
    report["ok"] = ok
    return ok, report


def _payloads_from_artifact(fname: str) -> List[dict]:
    """Bench payloads in one checked-in artifact: a direct payload dict
    ({metric, value, unit}), or a driver wrapper whose `tail` holds the
    bench's emitted JSON line(s). Anything else yields nothing."""
    try:
        with open(fname) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return []
    if not isinstance(data, dict):
        return []
    if {"metric", "value", "unit"} <= set(data):
        return [data]
    out = []
    for line in str(data.get("tail", "")).splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and {"metric", "value", "unit"} <= set(rec):
            out.append(rec)
    return out


def backfill(artifacts: Optional[List[str]] = None,
             path: Optional[str] = None,
             repo_root: str = _REPO_ROOT) -> dict:
    """Seed (or extend) the history from checked-in bench artifacts, so
    the trajectory starts at PR 1 instead of empty. Default set: every
    `BENCH_*.json` at the repo root plus `SCALE_MESH_COMPOSED.json`
    (the composed-grid series), in name order — the round numbering
    (`_r01`..) makes that chronological. Rows already present for a
    (metric, value, source) are not duplicated, so backfill is
    idempotent. Backfilled rows carry `run_meta.backfill_source` and no
    env block (pre-ledger artifacts recorded none): each artifact forms
    its OWN rig, so the pre-ledger rounds — measured on different
    sandboxes — chart the trajectory without ever serving as a
    regression baseline (rig_key)."""
    if artifacts is None:
        artifacts = sorted(
            f for f in glob.glob(os.path.join(repo_root, "BENCH_*.json"))
            if not f.endswith("BENCH_HISTORY.jsonl"))
        composed = os.path.join(repo_root, "SCALE_MESH_COMPOSED.json")
        if os.path.exists(composed):
            artifacts.append(composed)
    existing = {
        (r.get("metric"), r.get("value"),
         (r.get("run_meta") or {}).get("backfill_source"))
        for r in load_history(path)}
    p = history_path(path)
    added, skipped = [], []
    with open(p, "a") as fh:
        for fname in artifacts:
            payloads = _payloads_from_artifact(fname)
            src = os.path.basename(fname)
            if not payloads:
                skipped.append(src)
                continue
            for payload in payloads:
                tv = _trackable(payload)
                if tv is None:
                    continue
                metric, value = tv
                if (payload.get("metric"), payload.get("value"),
                        src) in existing:
                    continue
                row = make_row(payload,
                               run_meta={"backfill_source": src})
                row["ts"] = None  # measurement time unknown; order known
                fh.write(json.dumps(row) + "\n")
                added.append({"metric": metric, "value": value,
                              "source": src})
    return {"path": p, "added": added, "skipped_artifacts": skipped}


def format_report(report: dict) -> str:
    lines = [f"perf ledger: {report['path']} ({report['rows']} rows, "
             f"threshold {report['threshold']:.0%}, "
             f"window {report['window']})"]
    if not report["metrics"]:
        lines.append("  (empty history — run `bench.py --track` or "
                     "`python -m factorvae_tpu.obs.ledger --backfill`)")
    for e in report["metrics"]:
        med = e.get("trailing_median")
        ratio = e.get("ratio_vs_median")
        detail = (f"latest {e['latest']:g} vs median {med:g} "
                  f"(x{ratio:g})" if med is not None
                  else f"latest {e['latest']:g} — {e['status']}")
        mark = {"REGRESSION": "!!", "improvement": "++"}.get(
            e["status"], "  ")
        skip = (f"  [{e['other_rig_skipped']} other-rig rows skipped]"
                if e.get("other_rig_skipped") else "")
        lines.append(f"{mark} {e['metric']}: {detail}{skip}")
    lines.append("OK" if report["ok"] else
                 "REGRESSION detected (exit 1)")
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.obs.ledger",
        description="perf-regression check over BENCH_HISTORY.jsonl "
                    "(latest row vs trailing same-rig median per metric)")
    ap.add_argument("history", nargs="?", default=None,
                    help=f"history path (default: ${HISTORY_ENV} or "
                         f"{os.path.basename(DEFAULT_HISTORY_PATH)} at "
                         "the repo root)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="regression when latest < (1-threshold) x median")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="trailing same-rig rows in the median")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--backfill", action="store_true",
                    help="seed the history from the checked-in "
                         "BENCH_*.json artifacts (idempotent), then check")
    args = ap.parse_args(argv)
    if args.backfill:
        res = backfill(path=args.history)
        if not args.json:
            print(f"backfilled {len(res['added'])} rows -> {res['path']}"
                  + (f" (no payload in: "
                     f"{', '.join(res['skipped_artifacts'])})"
                     if res["skipped_artifacts"] else ""))
    elif not os.path.exists(history_path(args.history)):
        print(f"error: no bench history at {history_path(args.history)} "
              "(seed it with --backfill or `python bench.py --track`)")
        return 2
    ok, report = check(path=args.history, threshold=args.threshold,
                       window=args.window)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
