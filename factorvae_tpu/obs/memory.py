"""Device-memory accounting: shard balance from the rule tables +
live-buffer watermarks where the backend exposes them.

Two complementary views (ISSUE 7):

- **Static shard balance** (`shard_balance_block`): given the PR-6
  partition rules, how many REAL bytes of the TrainState and the panel
  does each device hold, and how uneven is the split? GSPMD pads an
  uneven dimension (N=800 over a 3-way 'stock' axis -> shards of
  267/267/266 real rows plus dead padding), so `imbalance_frac` =
  (max - min) / max over per-device bytes is the number that catches a
  lopsided axis before it becomes a straggler. Computed from abstract
  shapes (`jax.eval_shape` structs work) — no device traffic.

- **Live watermarks** (`device_memory_stats` / `watermark_event`):
  `Device.memory_stats()` where the backend implements it (TPU/GPU;
  host CPU returns nothing). `watermark_event` emits one `memory` mark
  per call onto the installed timeline with per-device
  `bytes_in_use` / `peak_bytes_in_use` — the measured complement of
  the per-program `memory_analysis` estimate in the `compile` records.
  No timeline, or no stats: a no-op. Observation-only throughout.
"""

from __future__ import annotations

from typing import Optional

from factorvae_tpu.utils.logging import current_timeline

__all__ = [
    "device_memory_stats",
    "shard_balance",
    "shard_balance_block",
    "watermark_event",
]


def shard_balance(mesh, specs, tree) -> dict:
    """Per-device byte bill of `tree` placed per `specs`:
    {total_bytes, min/max/mean bytes_per_device, imbalance_frac}."""
    import numpy as np

    from factorvae_tpu.parallel.partition import device_bytes

    per = device_bytes(mesh, specs, tree).reshape(-1)
    hi = int(per.max()) if per.size else 0
    lo = int(per.min()) if per.size else 0
    return {
        "total_bytes": int(per.sum()),
        "bytes_per_device_max": hi,
        "bytes_per_device_min": lo,
        "bytes_per_device_mean": float(np.mean(per)) if per.size else 0.0,
        "imbalance_frac": round((hi - lo) / hi, 4) if hi else 0.0,
    }


def _panel_tree(dataset) -> Optional[dict]:
    """Abstract {values, last_valid, next_valid} of a PanelDataset,
    residency-agnostic (stream datasets hold host numpy; HBM datasets
    device arrays — only shapes/dtypes are read either way)."""
    names = (("values", "last_valid", "next_valid")
             if getattr(dataset, "residency", "hbm") == "hbm"
             else ("values_np", "last_valid_np", "next_valid_np"))
    try:
        arrs = [getattr(dataset, n) for n in names]
    except AttributeError:
        return None
    return dict(zip(("values", "last_valid", "next_valid"), arrs))


def shard_balance_block(mesh, state=None, dataset=None,
                        stacked: bool = False) -> dict:
    """The one JSON-ready block Trainer/FleetTrainer log (and bench
    --mesh cells carry): a `state` bill from TRAIN_STATE_RULES /
    FLEET_STATE_RULES and a `panel` bill from PANEL_RULES, per device.
    A stream-resident dataset's panel never lives on device, so its
    panel bill reports the PER-CHUNK mini-panel footprint semantics via
    `residency` instead of pretending the whole panel is resident."""
    from factorvae_tpu.parallel import partition

    block: dict = {
        "mesh": {str(n): int(s) for n, s in
                 zip(mesh.axis_names, mesh.devices.shape)},
        "devices": int(mesh.devices.size),
    }
    if state is not None:
        specs = partition.state_partition_specs(state, stacked=stacked)
        block["state"] = shard_balance(mesh, specs, state)
    if dataset is not None:
        tree = _panel_tree(dataset)
        if tree is not None:
            # the ONE panel rule resolution (parallel/partition.py) —
            # the bill must account exactly what the placement places
            specs = dict(zip(("values", "last_valid", "next_valid"),
                             partition.panel_partition_specs()))
            block["panel"] = shard_balance(mesh, specs, tree)
            block["panel"]["residency"] = getattr(dataset, "residency",
                                                  "hbm")
    return block


def device_memory_stats() -> Optional[list]:
    """Per-device allocator stats where the backend exposes them
    ([{device, bytes_in_use, peak_bytes_in_use, bytes_limit}, ...]), or
    None (host CPU, older jaxlibs). Never raises."""
    try:
        import jax

        out = []
        for d in jax.local_devices():
            fn = getattr(d, "memory_stats", None)
            stats = fn() if callable(fn) else None
            if not stats:
                continue
            out.append({
                "device": f"{d.platform}:{d.id}",
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            })
        return out or None
    except Exception:
        return None


def watermark_event(**fields) -> bool:
    """Emit a `memory` mark with the live per-device watermarks onto the
    installed timeline. No timeline or no backend stats: no-op (False).
    The epoch loops call this once per epoch — host-side observation
    only, zero effect on the compiled programs."""
    tl = current_timeline()
    if tl is None:
        return False
    stats = device_memory_stats()
    if stats is None:
        return False
    peak = max((s.get("peak_bytes_in_use") or 0) for s in stats)
    tl.event("memory", cat="memory", resource="memory", devices=stats,
             peak_bytes_in_use=peak, **fields)
    return True
