"""Fleet stream collector — merge per-process RUN.jsonl streams onto
one clock (pillar 6, the transport half of obs/trace.py).

Every fleet process — router and each worker daemon — writes its own
RUN.jsonl whose span times are relative to its OWN `perf_counter`
origin (utils/logging.py `Timeline`). PR 5 worked around exactly this
inside one stream with per-process sections (`obs.timeline`
`span_sections`); a fleet makes the workaround untenable: a trace's
spans live in N files on M hosts, each on a different base. This module
solves it:

* **Transport** — router and workers expose ``GET /runstream?since=<n>``
  serving their RUN.jsonl tail from byte offset `n`, cut at the last
  newline (``obs/live.py tail_bytes`` — the PR-10 torn-line follower
  contract over HTTP) with the resume offset in an ``X-Runstream-Next``
  response header. Polling with the returned offset is an incremental,
  idempotent tail-follow of a remote file.

* **Clock alignment** — the pool's health watcher already scrapes every
  worker's ``/healthz`` on an interval; that response now echoes the
  worker's timeline clock (``"mono"``, seconds on ITS base). The
  watcher wraps the scrape in local before/after stamps and logs a
  ``clock_probe`` mark ``{worker, remote_mono, local_t0, local_t1}``
  into the ROUTER's stream. Offset estimation is classic NTP-style:
  ``offset = (local_t0 + local_t1)/2 - remote_mono``, best probe = the
  minimum round trip (tightest bound on where inside the RTT the remote
  stamp landed). `estimate_offsets` keeps the min-RTT probe per worker;
  remote joins get a first probe from the `/register` handshake, so a
  worker is alignable as soon as it is routable.

* **Merge** — `merge_records` rebases every worker record's times
  (`t0`/`t1`/`t`) by its offset onto the router base, tags each record
  with its source process (``proc`` field, additive), and sorts by
  time. The output is one JSONL stream `obs.trace` renders trees from
  as if the fleet had been one process.

CLI::

    python -m factorvae_tpu.obs.collect --router http://HOST:PORT \
        [--out MERGED.jsonl] [--since-file STATE.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

#: mark name the pool/remote handshake probes log under
CLOCK_PROBE = "clock_probe"


def parse_lines(payload: str) -> List[dict]:
    """JSON records from a /runstream payload; blank/torn lines are
    impossible by the tail_bytes contract but tolerated anyway."""
    records = []
    for line in payload.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


def fetch_runstream(base_url: str, since: int = 0,
                    timeout: float = 10.0) -> Tuple[List[dict], int]:
    """One /runstream poll against a fleet process. Returns (records,
    next_offset); pass `next_offset` back as `since` to tail."""
    url = f"{base_url.rstrip('/')}/runstream?since={int(since)}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        payload = resp.read().decode("utf-8", errors="replace")
        nxt = int(resp.headers.get("X-Runstream-Next", since))
    return parse_lines(payload), nxt


def fetch_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def estimate_offsets(router_records: Iterable[dict]) -> Dict[str, dict]:
    """Per-worker clock offset from `clock_probe` marks in the router
    stream: {worker_id: {"offset", "rtt", "probes"}}. The kept estimate
    is the minimum-RTT probe's midpoint offset — the probe whose
    round trip bounds the remote stamp tightest."""
    best: Dict[str, dict] = {}
    for rec in router_records:
        if rec.get("event") != "mark" or rec.get("name") != CLOCK_PROBE:
            continue
        wid = rec.get("worker")
        try:
            t0 = float(rec["local_t0"])
            t1 = float(rec["local_t1"])
            remote = float(rec["remote_mono"])
        except (KeyError, TypeError, ValueError):
            continue
        rtt = max(0.0, t1 - t0)
        offset = (t0 + t1) / 2.0 - remote
        cur = best.get(wid)
        if cur is None:
            best[wid] = {"offset": offset, "rtt": rtt, "probes": 1}
        else:
            cur["probes"] += 1
            if rtt < cur["rtt"]:
                cur["offset"], cur["rtt"] = offset, rtt
    return best


def rebase(rec: dict, offset: float, proc: str) -> dict:
    """Copy of `rec` with its timeline times shifted onto the collector
    base and a `proc` source tag. Wall-clock `ts` is left alone — it
    was never a usable cross-process axis and stays what the writer
    wrote."""
    out = dict(rec)
    for key in ("t0", "t1", "t"):
        if key in out and isinstance(out[key], (int, float)):
            out[key] = round(float(out[key]) + offset, 6)
    out["proc"] = proc
    return out


def merge_records(router_records: List[dict],
                  worker_records: Dict[str, List[dict]],
                  offsets: Optional[Dict[str, dict]] = None) -> List[dict]:
    """One stream on the router clock: router records pass through
    (offset 0, proc="router"); each worker's records shift by its
    estimated offset. Workers with no probe yet merge unshifted but
    tagged `aligned=False` so a renderer can refuse to compare their
    times. Sorted by timeline time (run_meta headers first)."""
    if offsets is None:
        offsets = estimate_offsets(router_records)
    merged = [rebase(r, 0.0, "router") for r in router_records]
    for wid, records in worker_records.items():
        est = offsets.get(wid)
        for rec in records:
            out = rebase(rec, est["offset"] if est else 0.0, wid)
            if est is None:
                out["aligned"] = False
            merged.append(out)

    def key(rec: dict) -> tuple:
        t = rec.get("t0", rec.get("t"))
        return (0, 0.0) if t is None else (1, float(t))

    merged.sort(key=key)
    return merged


def discover_workers(router_url: str, timeout: float = 10.0) -> Dict[str, str]:
    """{worker_id: base_url} for routable workers, from router /stats."""
    stats = fetch_json(f"{router_url.rstrip('/')}/stats", timeout=timeout)
    pool = stats.get("pool", stats)
    out = {}
    for w in pool.get("workers", ()):
        if w.get("state") in ("ok", "degraded") and w.get("url"):
            out[w["worker_id"]] = w["url"]
    return out


def collect_fleet(router_url: str,
                  since: Optional[Dict[str, int]] = None,
                  timeout: float = 10.0,
                  ) -> Tuple[List[dict], Dict[str, int]]:
    """One collection sweep over a live fleet: pull the router's tail,
    discover workers, pull each worker's tail, align and merge. `since`
    maps process id -> byte offset from the previous sweep (mutated
    copy returned), so repeated sweeps are an incremental tail-follow
    of the whole fleet."""
    since = dict(since or {})
    router_records, since["router"] = fetch_runstream(
        router_url, since.get("router", 0), timeout=timeout)
    worker_records: Dict[str, List[dict]] = {}
    for wid, url in discover_workers(router_url, timeout=timeout).items():
        try:
            worker_records[wid], since[wid] = fetch_runstream(
                url, since.get(wid, 0), timeout=timeout)
        except OSError:
            continue   # worker died between discovery and pull — next sweep
    return merge_records(router_records, worker_records), since


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.obs.collect",
        description="Merge a serving fleet's RUN.jsonl streams onto one "
                    "clock (trace plane transport, docs/observability.md "
                    "pillar 6).")
    p.add_argument("--router", required=True,
                   help="router base URL, e.g. http://127.0.0.1:8700")
    p.add_argument("--out", default=None,
                   help="write merged JSONL here (default: stdout)")
    p.add_argument("--since-file", default=None,
                   help="JSON file persisting per-process offsets across "
                        "invocations (incremental collection)")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)

    since: Dict[str, int] = {}
    if args.since_file:
        try:
            with open(args.since_file) as fh:
                since = {k: int(v) for k, v in json.load(fh).items()}
        except (OSError, ValueError):
            since = {}
    try:
        merged, since = collect_fleet(args.router, since=since,
                                      timeout=args.timeout)
    except OSError as e:
        print(f"error: cannot reach fleet at {args.router}: {e}",
              file=sys.stderr)
        return 2
    out_fh = open(args.out, "a") if args.out else sys.stdout
    try:
        for rec in merged:
            out_fh.write(json.dumps(rec) + "\n")
    finally:
        if args.out:
            out_fh.close()
    if args.since_file:
        with open(args.since_file, "w") as fh:
            json.dump(since, fh)
    print(f"collected {len(merged)} record(s) from "
          f"{len(set(r.get('proc') for r in merged))} process(es)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
