"""Run observatory: training-health telemetry for the whole pipeline.

Five pillars (ISSUE 5/7/10; docs/observability.md has the long-form
story):

- **On-device health probes** (`obs.probes`, wired through
  `train/loop.py make_step_fns(obs=True)`): scalar probes — grad/param/
  update global norms, per-term losses, non-finite counts, factor-
  posterior spread — compiled into the existing epoch-scan aux, so they
  cost zero extra dispatches, vmap cleanly across the fleet seed axis,
  and are BITWISE-NEUTRAL when off (the default; the off path is the
  pre-observatory trace, pinned in tests/test_obs.py — the same
  discipline as `panel_residency`).
- **Unified host timeline** (`utils/logging.Timeline` +
  `python -m factorvae_tpu.obs.timeline`): Trainer/FleetTrainer epochs,
  the ChunkStream transfer ledger, async checkpoint saves and the jit
  compile watchdog all emit monotonic-clock spans into one RUN.jsonl;
  the CLI renders a text Gantt and computes per-resource overlap
  fractions, cross-linkable with `--profile` device traces via shared
  span names.
- **Run reports** (`python -m factorvae_tpu.obs.report RUN.jsonl`):
  per-epoch tables plus health flags — NaN/inf hits, grad-norm spikes,
  val-metric divergence, throughput regressions vs the plan row's
  measured envelope — in human or JSON form. `bench.py --obs` measures
  the probes' own overhead so the cost of watching is itself a tracked
  number.
- **Compiled-program observatory** (ISSUE 7; `obs/compile.py`,
  `obs/comms.py`, `obs/memory.py`, `obs/ledger.py`): what did XLA
  actually build? Every watched jit's cache miss emits a `compile`
  record (wall time + guarded `cost_analysis`/`memory_analysis` bill);
  the compiled HLO text is statically scanned for collective ops with
  per-mesh-axis byte attribution (`bench.py --mesh` comms blocks); the
  rule tables yield a per-device shard-balance bill; and `bench.py
  --track` appends every headline bench row to `BENCH_HISTORY.jsonl`,
  which `python -m factorvae_tpu.obs.ledger` checks for regressions
  against the trailing median — the perf trajectory, not one-off
  artifacts.
- **Live telemetry plane** (ISSUE 10; `obs/live.py`, `obs/metrics.py`,
  `obs/drift.py`): a streaming RUN.jsonl follower that emits
  `obs.report`'s flags as alerts while the run is IN FLIGHT (torn-line
  tolerant; flags pinned identical to the post-hoc report), Prometheus
  text exposition — the daemon's `GET /metrics` plus a trainer-side
  textfile exporter — and served-score drift monitors (per-model
  distribution digests, day-over-day rank correlation, `score_drift`
  flags) feeding the walk-forward loop of ROADMAP item 4.
"""

from factorvae_tpu.obs.compile import (
    capture_compile,
    guarded_compiled_text,
    guarded_cost_analysis,
    guarded_memory_analysis,
)
from factorvae_tpu.obs.probes import (
    EVAL_PROBE_KEYS,
    TRAIN_PROBE_KEYS,
    finalize_eval_probes,
    finalize_train_probes,
    grad_probes,
    loss_probes,
)
from factorvae_tpu.obs.watchdog import WatchedJit, watch_jit

__all__ = [
    "EVAL_PROBE_KEYS",
    "TRAIN_PROBE_KEYS",
    "WatchedJit",
    "capture_compile",
    "finalize_eval_probes",
    "finalize_train_probes",
    "grad_probes",
    "guarded_compiled_text",
    "guarded_cost_analysis",
    "guarded_memory_analysis",
    "loss_probes",
    "watch_jit",
]
