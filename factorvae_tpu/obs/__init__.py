"""Run observatory: training-health telemetry for the whole pipeline.

Three pillars (ISSUE 5; docs/observability.md has the long-form story):

- **On-device health probes** (`obs.probes`, wired through
  `train/loop.py make_step_fns(obs=True)`): scalar probes — grad/param/
  update global norms, per-term losses, non-finite counts, factor-
  posterior spread — compiled into the existing epoch-scan aux, so they
  cost zero extra dispatches, vmap cleanly across the fleet seed axis,
  and are BITWISE-NEUTRAL when off (the default; the off path is the
  pre-observatory trace, pinned in tests/test_obs.py — the same
  discipline as `panel_residency`).
- **Unified host timeline** (`utils/logging.Timeline` +
  `python -m factorvae_tpu.obs.timeline`): Trainer/FleetTrainer epochs,
  the ChunkStream transfer ledger, async checkpoint saves and the jit
  compile watchdog all emit monotonic-clock spans into one RUN.jsonl;
  the CLI renders a text Gantt and computes per-resource overlap
  fractions, cross-linkable with `--profile` device traces via shared
  span names.
- **Run reports** (`python -m factorvae_tpu.obs.report RUN.jsonl`):
  per-epoch tables plus health flags — NaN/inf hits, grad-norm spikes,
  val-metric divergence, throughput regressions vs the plan row's
  measured envelope — in human or JSON form. `bench.py --obs` measures
  the probes' own overhead so the cost of watching is itself a tracked
  number.
"""

from factorvae_tpu.obs.probes import (
    EVAL_PROBE_KEYS,
    TRAIN_PROBE_KEYS,
    finalize_eval_probes,
    finalize_train_probes,
    grad_probes,
    loss_probes,
)
from factorvae_tpu.obs.watchdog import WatchedJit, watch_jit

__all__ = [
    "EVAL_PROBE_KEYS",
    "TRAIN_PROBE_KEYS",
    "WatchedJit",
    "finalize_eval_probes",
    "finalize_train_probes",
    "grad_probes",
    "loss_probes",
    "watch_jit",
]
