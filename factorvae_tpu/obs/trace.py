"""Fleet trace plane — deterministic distributed tracing (pillar 6).

Since PR 15/17 one scored request crosses processes: router ingress →
(possibly hedged / rerouted) forward → worker queue wait → tick fusion
→ fused jit dispatch → response, and PR 13's walk-forward operator
drives judge/refit/promote traffic through the same plane. Each process
writes its own RUN.jsonl with its own perf_counter origin, so without a
shared request identity "where did this p99 request spend its time" is
unanswerable. This module is the identity half of the answer (the
stream-merge half is obs/collect.py):

* **Context** — a trace context is a plain dict ``{"trace_id",
  "span_id"}`` (plus an optional ``"parent"`` while being built). Ids
  are DETERMINISTIC: the router derives a trace id from its monotonically
  increasing request counter (``r-000042``), the walk-forward operator
  from its cycle id (``wf-c00003``), a router-less daemon from its own
  counter (``d-000007``) — no host RNG anywhere, so tests replay
  identical ids. Child span ids are hierarchical: ``child(ctx, label)``
  appends ``.label`` to the parent's span id, which makes every span id
  self-describing (``r-000042/in.h1.q3`` reads "hedge leg 1, queue slot
  3 of request 42") and collision-free as long as sibling labels are
  unique — callers use counters (forward leg ``f0, f1``, hedge legs
  ``h0, h1``, queue slots ``q<n>``) to guarantee that.

* **Wire format** — one HTTP header, ``X-Factorvae-Trace:
  <trace_id>;<span_id>``, attached to every router forward (and to
  ``POST /admit`` fan-outs); the receiver parents its spans under the
  sender's span id. JSONL requests carry the same pair as a ``"trace"``
  object field, so stdin/batch scoring and in-process daemon calls join
  a trace without HTTP. Both carriers are additive: traceless requests
  flow exactly as before.

* **Span records** — workers/routers do not grow a new log: the
  existing Timeline span records carry ``trace``/``span``/``parent``
  fields through ``**fields`` passthrough. Fused spans that serve many
  requests at once (``serve_tick``) carry a ``traces`` list plus a
  ``members`` list of the member span ids; the tree renderer grafts
  them into each member trace at the right parent.

* **Rendering** — ``python -m factorvae_tpu.obs.trace`` assembles
  per-trace span trees from one or more RUN.jsonl streams (typically
  the merged stream obs/collect.py writes), renders a tree + Gantt per
  trace (``--trace <id>``), ranks tail exemplars (``--slowest N``) and
  reports a per-stage wall breakdown (queue vs tick-hold vs dispatch vs
  response) so a p99 complaint decomposes into the stage that caused it.

* **Sampling** — ``sample_keep(trace_id, rate)`` is a deterministic
  hash-of-trace-id filter (sha256, no RNG) with a tail bias: callers
  pass ``breach=True`` for SLO-breaching traces, which are ALWAYS kept.
  The CLI's ``--trace_sample`` applies the same policy at read time.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from typing import Any, Dict, Iterable, List, Optional, Tuple

TRACE_HEADER = "X-Factorvae-Trace"

# Span names emitted along the serving path, in causal order; the stage
# breakdown reports wall per stage under these keys.
STAGES = ("router_ingress", "router_forward", "serve_queue", "serve_tick",
          "serve_dispatch", "serve_request")


# ---------------------------------------------------------------------------
# Context construction / propagation
# ---------------------------------------------------------------------------


def root_ctx(trace_id: str, span_id: str = "in") -> dict:
    """A fresh root context. `trace_id` must come from a deterministic
    per-process counter (router request counter, wf cycle id) — never
    from RNG or wall clock."""
    return {"trace_id": str(trace_id), "span_id": str(span_id)}


def child(ctx: dict, label: str) -> dict:
    """Child context: hierarchical span id, parent = the given ctx."""
    sid = f"{ctx['span_id']}.{label}"
    return {"trace_id": ctx["trace_id"], "span_id": sid,
            "parent": ctx["span_id"]}


def span_fields(ctx: Optional[dict], **extra: Any) -> dict:
    """Timeline `**fields` for a span carrying this context. Returns
    `extra` unchanged on a None/invalid ctx so call sites stay
    unconditional."""
    if not isinstance(ctx, dict) or "trace_id" not in ctx:
        return extra
    fields = {"trace": ctx["trace_id"], "span": ctx["span_id"]}
    parent = ctx.get("parent")
    if parent:
        fields["parent"] = parent
    fields.update(extra)
    return fields


def format_header(ctx: dict) -> str:
    return f"{ctx['trace_id']};{ctx['span_id']}"


def parse_header(value: Optional[str]) -> Optional[dict]:
    """Parse `X-Factorvae-Trace`; None on absent/malformed (a bad
    header must never fail the request it rides on)."""
    if not value or ";" not in value:
        return None
    tid, _, sid = value.partition(";")
    tid, sid = tid.strip(), sid.strip()
    if not tid or not sid:
        return None
    return {"trace_id": tid, "span_id": sid}


def wire_ctx(req: Any) -> Optional[dict]:
    """The `"trace"` field of a JSONL request dict, validated."""
    if not isinstance(req, dict):
        return None
    t = req.get("trace")
    if (isinstance(t, dict) and isinstance(t.get("trace_id"), str)
            and isinstance(t.get("span_id"), str)):
        return {"trace_id": t["trace_id"], "span_id": t["span_id"]}
    return None


def sample_keep(trace_id: str, rate: float, breach: bool = False) -> bool:
    """Deterministic tail-biased sampling: SLO breachers are always
    kept; otherwise keep iff sha256(trace_id) falls under `rate`.
    rate>=1 keeps everything, rate<=0 keeps only breachers."""
    if breach:
        return True
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = int.from_bytes(hashlib.sha256(trace_id.encode()).digest()[:8], "big")
    return (h / float(1 << 64)) < rate


# ---------------------------------------------------------------------------
# Assembly: records -> per-trace span trees
# ---------------------------------------------------------------------------


def assemble_traces(records: Iterable[dict]) -> Dict[str, dict]:
    """Group span records by trace id.

    Returns {trace_id: {"spans": [rec...], "shared": [rec...]}} where
    `spans` carry an explicit `trace` field and `shared` are fused
    spans (a `traces` list) serving several traces at once. Records are
    kept verbatim — the collector has already mapped times onto one
    base when streams were merged.
    """
    traces: Dict[str, dict] = {}

    def bucket(tid: str) -> dict:
        return traces.setdefault(tid, {"spans": [], "shared": []})

    for rec in records:
        if rec.get("event") != "span":
            continue
        tid = rec.get("trace")
        if isinstance(tid, str):
            bucket(tid)["spans"].append(rec)
        for t in rec.get("traces") or ():
            if isinstance(t, str):
                bucket(t)["shared"].append(rec)
    return traces


def _tree_index(trace: dict) -> Tuple[Dict[str, List[dict]], List[dict]]:
    """(parent span_id -> children, roots). Shared spans are grafted
    under their first member span id that belongs to this trace; spans
    whose parent never arrived (partial collection) surface as extra
    roots rather than vanishing."""
    by_id: Dict[str, dict] = {}
    for rec in trace["spans"] + trace["shared"]:
        sid = rec.get("span")
        if isinstance(sid, str):
            # Last write wins; duplicate ids only happen on re-collected
            # overlapping streams where the records are identical.
            by_id[sid] = rec
    members = set(by_id)
    roots: List[dict] = []
    children: Dict[str, List[dict]] = {}
    for rec in trace["spans"]:
        parent = rec.get("parent")
        if isinstance(parent, str) and parent in by_id:
            children.setdefault(parent, []).append(rec)
        else:
            roots.append(rec)
    for rec in trace["shared"]:
        parent = rec.get("parent")
        anchor = None
        if isinstance(parent, str) and parent in by_id:
            anchor = parent
        else:
            for m in rec.get("members") or ():
                if m in members:
                    anchor = m
                    break
        if anchor is not None:
            children.setdefault(anchor, []).append(rec)
        else:
            roots.append(rec)
    for recs in children.values():
        recs.sort(key=lambda r: r.get("t0", 0.0))
    roots.sort(key=lambda r: r.get("t0", 0.0))
    return children, roots


def render_tree(tid: str, trace: dict, width: int = 100) -> str:
    """Text tree + proportional bars for one trace."""
    children, roots = _tree_index(trace)
    spans = trace["spans"] + trace["shared"]
    if not spans:
        return f"trace {tid}: no spans"
    t_lo = min(r.get("t0", 0.0) for r in spans)
    t_hi = max(r.get("t1", 0.0) for r in spans)
    total = max(t_hi - t_lo, 1e-9)
    bar_w = max(20, width - 64)
    lines = [f"trace {tid}  wall {total * 1e3:.2f} ms  spans {len(spans)}"]
    seen = set()

    def emit(rec: dict, depth: int) -> None:
        key = (rec.get("span"), rec.get("name"), rec.get("t0"))
        if key in seen:       # shared spans graft once per anchor; render once
            return
        seen.add(key)
        t0, t1 = rec.get("t0", t_lo), rec.get("t1", t_lo)
        lo = int((t0 - t_lo) / total * bar_w)
        hi = max(lo + 1, int((t1 - t_lo) / total * bar_w))
        bar = " " * lo + "=" * (hi - lo)
        annot = ""
        for k in ("worker", "outcome", "leg", "requests", "models", "cycle"):
            if k in rec:
                annot += f" {k}={rec[k]}"
        label = f"{'  ' * depth}{rec.get('name', '?')}"
        lines.append(
            f"{label:<36} {(t1 - t0) * 1e3:9.3f} ms |{bar:<{bar_w}}|{annot}")
        sid = rec.get("span")
        if isinstance(sid, str):
            for c in children.get(sid, ()):
                emit(c, depth + 1)

    for root in roots:
        emit(root, 0)
    return "\n".join(lines)


def trace_wall(trace: dict) -> float:
    spans = trace["spans"] + trace["shared"]
    if not spans:
        return 0.0
    return (max(r.get("t1", 0.0) for r in spans)
            - min(r.get("t0", 0.0) for r in spans))


def trace_breached(trace: dict, slo_s: Optional[float]) -> bool:
    return slo_s is not None and trace_wall(trace) > slo_s


def stage_breakdown(traces: Dict[str, dict]) -> Dict[str, dict]:
    """Per-stage wall percentiles across traces: {stage: {n, p50_ms,
    p99_ms}}. A trace contributes the SUM of its spans per stage (a
    hedged trace has two forward legs; both waits were real)."""
    per_stage: Dict[str, List[float]] = {s: [] for s in STAGES}
    for trace in traces.values():
        sums: Dict[str, float] = {}
        for rec in trace["spans"] + trace["shared"]:
            name = rec.get("name")
            if name in per_stage:
                sums[name] = sums.get(name, 0.0) + float(rec.get("dur", 0.0))
        for name, s in sums.items():
            per_stage[name].append(s)
    out: Dict[str, dict] = {}
    for name, walls in per_stage.items():
        if not walls:
            continue
        walls.sort()
        out[name] = {
            "n": len(walls),
            "p50_ms": round(_pctl(walls, 0.50) * 1e3, 3),
            "p99_ms": round(_pctl(walls, 0.99) * 1e3, 3),
        }
    return out


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def load_records(paths: Iterable[str]) -> List[dict]:
    """All JSON records from the given JSONL files, torn lines skipped
    (the tail of a live stream may hold a partial write)."""
    records: List[dict] = []
    for path in paths:
        with open(path, "r", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    return records


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.obs.trace",
        description="Render per-trace span trees from (merged) RUN.jsonl "
                    "streams.")
    p.add_argument("paths", nargs="+", help="RUN.jsonl stream(s); pass the "
                   "obs.collect merged stream for cross-process trees")
    p.add_argument("--trace", default=None, help="render this trace id only")
    p.add_argument("--slowest", type=int, default=0, metavar="N",
                   help="render the N slowest traces (tail exemplars)")
    p.add_argument("--trace_sample", type=float, default=1.0, metavar="RATE",
                   help="deterministic keep-rate by trace-id hash; "
                   "SLO breachers (--slo_ms) always kept")
    p.add_argument("--slo_ms", type=float, default=None,
                   help="SLO for breach marking/sampling bias")
    p.add_argument("--stages", action="store_true",
                   help="print the per-stage p50/p99 breakdown")
    args = p.parse_args(argv)

    traces = assemble_traces(load_records(args.paths))
    slo_s = args.slo_ms / 1e3 if args.slo_ms is not None else None
    kept = {tid: tr for tid, tr in traces.items()
            if sample_keep(tid, args.trace_sample,
                           breach=trace_breached(tr, slo_s))}
    if not kept:
        print("no traces found", file=sys.stderr)
        return 1
    if args.trace is not None:
        tr = kept.get(args.trace)
        if tr is None:
            print(f"trace {args.trace!r} not found "
                  f"({len(kept)} traces present)", file=sys.stderr)
            return 1
        print(render_tree(args.trace, tr))
        return 0
    ranked = sorted(kept.items(), key=lambda kv: -trace_wall(kv[1]))
    shown = ranked[:args.slowest] if args.slowest else ranked
    for tid, tr in shown:
        mark = " SLO-BREACH" if trace_breached(tr, slo_s) else ""
        print(f"{tid:<24} wall {trace_wall(tr) * 1e3:9.2f} ms  "
              f"spans {len(tr['spans']) + len(tr['shared']):3d}{mark}")
    if args.slowest:
        for tid, tr in shown:
            print()
            print(render_tree(tid, tr))
    if args.stages:
        print()
        for name, row in stage_breakdown(kept).items():
            print(f"{name:<16} n={row['n']:<5d} p50={row['p50_ms']:9.3f} ms  "
                  f"p99={row['p99_ms']:9.3f} ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
